"""GL-P-COST — static roofline cost model for a built step.

GL-P-MEM answers "does it fit"; this pass answers "how fast should it
be", the objective function ROADMAP item 4's plan search needs (the GDP
framing, arxiv 1910.01578: a placement/config search is only as good as
its cheap static cost signal; arxiv 2104.05755 frames per-kernel
efficiency in exactly these roofline terms).

From nothing but the step's jaxpr and a hardware profile it produces:

- **per-op-class FLOPs and HBM bytes** — every equation is classified
  (matmul / conv / elementwise / reduce / gather / layout), charged
  ``2·M·N·K``-style FLOPs and its operand+result bytes, and rolled up
  per class with a per-class roofline time ``max(flops/peak,
  bytes/hbm_bw)``.  ``scan`` bodies multiply by trip count; control-flow
  wrappers are descended, not charged.
- **per-``pallas_call`` compute** — the kernel body's FLOPs × grid
  points, streamed bytes, and the VMEM-resident block footprint (from
  GL-P-MEM's block-shape walk), so a kernel that will spill VMEM is a
  named bottleneck, not a mystery slowdown.
- **a collective time model over the mesh** — payload bytes per
  reduce-scatter / all-gather / all-to-all from GL-P-COLL's extractor
  (or the analytic ZeRO schedule when the single-device trace carries
  no collectives), ring-scaled wire bytes / per-link ICI bandwidth.
- **predicted step_ms / MFU% / overlap headroom** — compute and
  collective time under the perfect-overlap model ``step =
  max(compute, comm)``; headroom is how much compute slack remains to
  hide the collectives.

When the step was lowered, XLA's own per-signature ``cost_analysis()``
FLOPs/bytes refine the walk's totals (the walk's class *proportions*
are kept — XLA reports totals only).  :func:`cost_report` returns the
dict attached to the ``preflight`` telemetry record (schema
``paddle_tpu.metrics/13``); :func:`cost_budget_pass` turns it into a
GL-P-COST finding when predicted MFU falls below ``--mfu_floor``,
naming the bottleneck: ``memory-bound:<class>``, ``collective-bound``,
or ``vmem-spill:<kernel>``.

Hardware profiles (``--hw_profile``) are a closed table —
:func:`hw_profile` raises a clean error listing the known names rather
than a KeyError, and ``auto`` resolves from the attached devices.
"""

from __future__ import annotations

from dataclasses import dataclass

from paddle_tpu.analysis.core import Finding, finalize


def _pname(name: str) -> str:
    return f"<program:{name}>"


# -- hardware profiles ----------------------------------------------------------


@dataclass(frozen=True)
class HwProfile:
    """Static machine model for the roofline: peak matmul FLOP/s (bf16
    for TPUs), HBM and per-ICI-link bandwidth, and the default memory
    budgets GL-P-MEM gates against when flags leave them unset."""

    name: str
    description: str
    peak_flops: float      # FLOP/s, dense matmul peak (bf16 on TPU)
    hbm_gbps: float        # GB/s, HBM (or host RAM) streaming bandwidth
    ici_gbps: float        # GB/s per ICI link direction (loopback on CPU)
    hbm_gb: float          # HBM capacity per chip
    vmem_mb: float         # VMEM per core (L2-ish working set on CPU)


HW_PROFILES: dict[str, HwProfile] = {
    # TPU v5p chip: 459 TFLOP/s bf16, 95 GB HBM2e @ 2765 GB/s, 6 ICI
    # links at ~100 GB/s per direction
    "v5p": HwProfile(
        name="v5p",
        description="TPU v5p chip (bf16 MXU peak, HBM2e, 3D-torus ICI)",
        peak_flops=459e12, hbm_gbps=2765.0, ici_gbps=100.0,
        hbm_gb=95.0, vmem_mb=128.0),
    # the CI box: one x86 core under XLA:CPU.  Peak/bandwidth are
    # CALIBRATED numbers (tools/bench_cost_calibration.py ties them to
    # tracewire-measured compute phases within the documented ≤2× band),
    # not datasheet numbers — XLA:CPU reaches nowhere near vector peak
    # on the small calibration shapes.
    "cpu-testbed": HwProfile(
        name="cpu-testbed",
        description="1-core x86 CI testbed under XLA:CPU (calibrated)",
        peak_flops=2.0e10, hbm_gbps=8.0, ici_gbps=4.0,
        hbm_gb=4.0, vmem_mb=1.0),
}


def hw_profile(name: str) -> HwProfile:
    """Profile lookup.  ``auto`` resolves from the attached devices
    (TPU v5 → ``v5p``, anything else → ``cpu-testbed``); an unknown
    name is a clean error listing the table, never a KeyError."""
    if name == "auto":
        kind = ""
        try:
            import jax

            kind = jax.devices()[0].device_kind.lower()
        except (ImportError, IndexError, RuntimeError):
            pass  # no backend attached: the CPU-testbed default stands
        return HW_PROFILES["v5p" if "v5" in kind and "lite" not in kind
                           else "cpu-testbed"]
    try:
        return HW_PROFILES[name]
    except KeyError:
        raise ValueError(
            f"unknown --hw_profile {name!r}: known profiles are "
            f"{', '.join(sorted(HW_PROFILES))} (or 'auto')") from None


# -- per-equation FLOP / byte charging ------------------------------------------

_LAYOUT_PRIMS = frozenset({
    "broadcast_in_dim", "transpose", "reshape", "squeeze", "slice",
    "rev", "expand_dims", "copy", "concatenate", "pad", "iota",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
})
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce_window_sum", "reduce_window_max", "reduce_window_min",
    "cumsum", "cummax", "cummin", "cumprod", "cumlogsumexp", "sort",
})
_GATHER_PRIMS = frozenset({
    "gather", "scatter", "scatter_add", "scatter_mul", "scatter_min",
    "scatter_max", "dynamic_slice", "dynamic_update_slice", "take",
    "select_and_scatter_add", "select_and_gather_add",
})
# control flow / call wrappers: descend into the body, charge nothing
_WRAPPER_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "checkpoint",
    "remat2", "cond", "while", "custom_lin",
})

OP_CLASSES = ("matmul", "conv", "elementwise", "reduce", "gather",
              "layout")


def _prod(xs) -> int:
    n = 1
    for x in xs:
        n *= int(x)
    return n


def _aval_elems(v) -> int:
    aval = getattr(v, "aval", None)
    return _prod(getattr(aval, "shape", ())) if aval is not None else 0


def _eqn_bytes(eqn) -> int:
    from paddle_tpu.analysis.memory import _aval_bytes

    return (sum(_aval_bytes(v) for v in eqn.invars)
            + sum(_aval_bytes(v) for v in eqn.outvars))


def _dot_flops(eqn) -> int:
    """2 · (batch · M · N) · K for a ``dot_general``: every output
    element is a length-K fused multiply-add chain."""
    (lhs_contract, _rhs_contract), _batch = eqn.params["dimension_numbers"]
    lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
    k = _prod(lhs_shape[d] for d in lhs_contract)
    return 2 * _aval_elems(eqn.outvars[0]) * max(k, 1)


def _conv_flops(eqn) -> int:
    """2 · out_elems · (kernel window · in_channels): each output
    element contracts one kernel's worth of inputs."""
    rhs_shape = getattr(eqn.invars[1].aval, "shape", ())
    dn = eqn.params.get("dimension_numbers")
    out_ch_dim = dn.rhs_spec[0] if dn is not None else 0
    out_ch = rhs_shape[out_ch_dim] if rhs_shape else 1
    window = _prod(rhs_shape) // max(out_ch, 1)
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    return 2 * _aval_elems(eqn.outvars[0]) * max(window // max(groups, 1), 1)


def classify_eqn(eqn) -> tuple[str, int]:
    """(op class, FLOPs) for one equation — the charging rule of the
    whole model.  Layout ops are 0-FLOP (bytes still count); reduces
    charge one op per *input* element; everything else charges one op
    per output element."""
    p = eqn.primitive.name
    if p == "dot_general":
        return "matmul", _dot_flops(eqn)
    if p == "conv_general_dilated":
        return "conv", _conv_flops(eqn)
    if p in _LAYOUT_PRIMS:
        return "layout", 0
    if p in _GATHER_PRIMS:
        return "gather", 0
    if p in _REDUCE_PRIMS or p.startswith("reduce_"):
        return "reduce", sum(_aval_elems(v) for v in eqn.invars)
    return "elementwise", sum(_aval_elems(v) for v in eqn.outvars)


# -- the jaxpr walk -------------------------------------------------------------


def _blank_classes() -> dict:
    return {c: {"flops": 0, "bytes": 0} for c in OP_CLASSES}


def _accumulate(jx, mult: int, classes: dict, pallas: list,
                collectives: list) -> None:
    from paddle_tpu.analysis.program import (_JAXPR_COLLECTIVES,
                                             inner_jaxprs)

    for eqn in jx.eqns:
        p = eqn.primitive.name
        if p == "scan":
            trips = int(eqn.params.get("length", 1) or 1)
            for sub in inner_jaxprs(eqn):
                _accumulate(sub, mult * trips, classes, pallas,
                            collectives)
            continue
        if p == "pallas_call":
            pallas.append(_pallas_cost(eqn, mult))
            continue
        if p in _JAXPR_COLLECTIVES:
            from paddle_tpu.analysis.memory import _aval_bytes

            payload = sum(_aval_bytes(v) for v in eqn.invars)
            collectives.append({"kind": _JAXPR_COLLECTIVES[p],
                                "payload_bytes": payload * mult})
            continue
        subs = list(inner_jaxprs(eqn))
        if p in _WRAPPER_PRIMS or subs:
            # wrappers and anything else carrying a body: the body is
            # the cost, the wrapper eqn itself is bookkeeping
            for sub in subs:
                _accumulate(sub, mult, classes, pallas, collectives)
            continue
        cls, flops = classify_eqn(eqn)
        classes[cls]["flops"] += flops * mult
        classes[cls]["bytes"] += _eqn_bytes(eqn) * mult


def _pallas_cost(eqn, mult: int) -> dict:
    """FLOPs (kernel body × grid points), streamed bytes (operands and
    results cross HBM once) and the VMEM-resident block footprint of
    one ``pallas_call``."""
    from paddle_tpu.analysis.memory import (_aval_bytes,
                                            _shape_dtype_bytes)

    label = str(eqn.params.get("name_and_src_info", "pallas_call"))
    label = label.split(" ")[0].split("(")[0] or "pallas_call"
    gm = eqn.params.get("grid_mapping")
    grid = _prod(getattr(gm, "grid", ()) or (1,))
    body = eqn.params.get("jaxpr")
    inner_classes = _blank_classes()
    if body is not None and hasattr(body, "eqns"):
        _accumulate(body, 1, inner_classes, [], [])
    flops = sum(c["flops"] for c in inner_classes.values()) * grid * mult
    streamed = (sum(_aval_bytes(v) for v in eqn.invars)
                + sum(_aval_bytes(v) for v in eqn.outvars)) * mult
    vmem = 0
    for bm in getattr(gm, "block_mappings", ()) or ():
        shape = [d if isinstance(d, int) else 1
                 for d in getattr(bm, "block_shape", ())]
        sd = getattr(bm, "array_shape_dtype", None)
        vmem += _shape_dtype_bytes(shape, getattr(sd, "dtype", None))
    return {"kernel": label, "flops": flops, "bytes": streamed,
            "vmem_bytes": vmem, "grid": grid}


# -- collective wire model ------------------------------------------------------

# ring-algorithm wire bytes per device, as a multiple of the payload
_RING_FACTOR = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "collective_permute": lambda n: 1.0,
}


def collective_wire_bytes(kind: str, payload_bytes: int, n: int) -> float:
    """Wire bytes one device moves for one collective over ``n`` ranks
    under the ring algorithm (the bandwidth-optimal schedule both ICI
    tori and gloo rings implement)."""
    if n <= 1:
        return 0.0
    return float(payload_bytes) * _RING_FACTOR.get(
        kind, lambda _n: 1.0)(n)


def zero_collective_bytes(params_bytes: int, dp: int,
                          zero: int) -> list[dict]:
    """Analytic per-step collective schedule of the data-parallel
    gradient flow, for traces that carry no collective primitives (the
    GSPMD path only materializes them post-partitioning): zero=0
    all-reduces the full gradient; zero>=1 reduce-scatters the gradient
    and all-gathers the updated params."""
    if dp <= 1:
        return []
    if zero >= 1:
        return [{"kind": "reduce_scatter", "payload_bytes": params_bytes},
                {"kind": "all_gather", "payload_bytes": params_bytes}]
    return [{"kind": "all_reduce", "payload_bytes": params_bytes}]


# -- the report -----------------------------------------------------------------


def cost_report(fn_or_jaxpr=None, *args, profile: HwProfile | str = "auto",
                mesh=None, zero: int = 0, params_bytes: int = 0,
                lowered=None, compiled=None, axis: str = "data") -> dict:
    """Static roofline estimate of one step under ``profile``.

    ``fn_or_jaxpr``/``args`` drive the jaxpr walk (required);
    ``lowered`` (a ``jax.stages.Lowered``) refines the FLOP/byte totals
    with XLA's own per-signature ``cost_analysis()`` when the backend
    reports one (pass ``compiled`` too when the caller already compiled
    — the fallback then reuses it instead of compiling a second time);
    ``mesh``/``zero``/``params_bytes`` parameterize the collective
    model (``params_bytes`` feeds the analytic ZeRO schedule when the
    trace itself carries no collectives)."""
    from paddle_tpu.analysis.memory import _has_prim
    from paddle_tpu.analysis.program import jaxpr_of

    if isinstance(profile, str):
        profile = hw_profile(profile)
    mesh_obj = getattr(mesh, "mesh", mesh)
    dp = 1
    if mesh_obj is not None:
        dp = int(dict(mesh_obj.shape).get(axis, 1))

    jx = jaxpr_of(fn_or_jaxpr, *args)
    classes = _blank_classes()
    pallas: list[dict] = []
    collectives: list[dict] = []
    _accumulate(jx.jaxpr, 1, classes, pallas, collectives)

    # the GSPMD/jit lowering traces GLOBAL shapes; per-device work is
    # 1/dp of it.  The explicit shard_map lowering already traces
    # per-shard shapes (same rule as GL-P-MEM's activation walk).
    if dp > 1 and not _has_prim(jx.jaxpr, "shard_map"):
        for c in classes.values():
            c["flops"] //= dp
            c["bytes"] //= dp
        for p in pallas:
            p["flops"] //= dp
            p["bytes"] //= dp

    flops_total = (sum(c["flops"] for c in classes.values())
                   + sum(p["flops"] for p in pallas))
    bytes_total = (sum(c["bytes"] for c in classes.values())
                   + sum(p["bytes"] for p in pallas))
    flops_source = "jaxpr-walk"
    if lowered is not None or compiled is not None:
        xla = _xla_cost_totals(lowered, compiled)
        if xla and xla.get("flops", 0) > 0:
            scale_f = xla["flops"] / max(flops_total, 1)
            scale_b = (xla["bytes"] / max(bytes_total, 1)
                       if xla.get("bytes", 0) > 0 else 1.0)
            # keep the walk's class proportions, adopt XLA's totals
            # (XLA sees fusion the walk cannot; class split is ours)
            for c in classes.values():
                c["flops"] = int(c["flops"] * scale_f)
                c["bytes"] = int(c["bytes"] * scale_b)
            for p in pallas:
                p["flops"] = int(p["flops"] * scale_f)
                p["bytes"] = int(p["bytes"] * scale_b)
            flops_total = int(flops_total * scale_f)
            bytes_total = int(bytes_total * scale_b)
            flops_source = "xla-cost-analysis"

    peak = profile.peak_flops
    hbm_bw = profile.hbm_gbps * 1e9
    by_class = {}
    compute_s = 0.0
    for name in OP_CLASSES:
        c = classes[name]
        t_flops = c["flops"] / peak
        t_bytes = c["bytes"] / hbm_bw
        t = max(t_flops, t_bytes)
        compute_s += t
        by_class[name] = {
            "flops": c["flops"], "bytes": c["bytes"],
            "time_ms": t * 1e3,
            "bound": "memory" if t_bytes > t_flops else "compute"}
    for p in pallas:
        t = max(p["flops"] / peak, p["bytes"] / hbm_bw)
        p["time_ms"] = t * 1e3
        compute_s += t

    if not collectives:
        collectives = zero_collective_bytes(params_bytes, dp, zero)
    ici_bw = profile.ici_gbps * 1e9
    comm_s = 0.0
    for c in collectives:
        wire = collective_wire_bytes(c["kind"], c["payload_bytes"], dp)
        c["wire_bytes"] = wire
        c["time_ms"] = wire / ici_bw * 1e3
        comm_s += wire / ici_bw

    step_s = max(compute_s, comm_s)
    mfu_pct = (flops_total / (step_s * peak) * 100.0) if step_s > 0 else 0.0
    vmem_budget = profile.vmem_mb * 1e6
    spilled = [p for p in pallas if p["vmem_bytes"] > vmem_budget > 0]
    if spilled:
        worst = max(spilled, key=lambda p: p["vmem_bytes"])
        bottleneck = f"vmem-spill:{worst['kernel']}"
    elif comm_s > compute_s:
        bottleneck = "collective-bound"
    else:
        dominant = max(by_class.items(), key=lambda kv: kv[1]["time_ms"])
        bottleneck = (f"{dominant[1]['bound']}-bound:{dominant[0]}"
                      if dominant[1]["time_ms"] > 0 else "compute-bound")

    return {
        "profile": profile.name,
        "dp": dp, "zero": int(zero),
        "flops": flops_total, "hbm_bytes": bytes_total,
        "flops_source": flops_source,
        "by_class": by_class,
        "pallas": pallas,
        "collectives": collectives,
        "compute_ms": compute_s * 1e3,
        "comm_ms": comm_s * 1e3,
        "step_ms": step_s * 1e3,
        "overlap_headroom_ms": (compute_s - comm_s) * 1e3,
        "mfu_pct": mfu_pct,
        "bottleneck": bottleneck,
    }


def _xla_cost_totals(lowered, compiled=None) -> dict | None:
    """{"flops", "bytes"} from XLA's per-signature cost analysis, the
    same best-effort dance StepTelemetry.cost_for does: prefer the
    pre-compile estimate, fall back to the compiled one (reusing the
    caller's executable when given — never compile twice), normalize
    the older list-of-dict return shape."""
    from paddle_tpu.core import logger as log

    for getter in (lambda: lowered.cost_analysis(),
                   lambda: (compiled if compiled is not None
                            else lowered.compile()).cost_analysis()):
        try:
            ca = getter()
        except Exception as e:
            log.debug("xla cost_analysis unavailable (%s); "
                      "jaxpr-walk totals stand", e)
            continue
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            return {"flops": float(ca.get("flops", 0.0) or 0.0),
                    "bytes": float(ca.get("bytes accessed", 0.0) or 0.0)}
    return None


# -- the budget pass ------------------------------------------------------------


def cost_budget_pass(report: dict, name: str = "train_step", *,
                     mfu_floor: float = 0.0) -> list[Finding]:
    """GL-P-COST finding when the predicted MFU falls below
    ``--mfu_floor`` percent (0 = report only, no gate), naming the
    bottleneck the report identified so the failure is actionable."""
    findings: list[Finding] = []
    floor = float(mfu_floor)
    mfu = float(report.get("mfu_pct", 0.0))
    if floor > 0 and mfu < floor:
        bottleneck = report.get("bottleneck", "unknown")
        findings.append(Finding(
            "GL-P-COST", _pname(name), 0, "mfu-floor",
            f"predicted MFU {mfu:.1f}% under the {report.get('profile')} "
            f"profile falls below the --mfu_floor {floor:.1f}% "
            f"(predicted step {report.get('step_ms', 0.0):.2f} ms, "
            f"compute {report.get('compute_ms', 0.0):.2f} ms, comm "
            f"{report.get('comm_ms', 0.0):.2f} ms); bottleneck: "
            f"{bottleneck} — "
            + _remedy(bottleneck)))
    return finalize(findings)


def _remedy(bottleneck: str) -> str:
    if bottleneck.startswith("vmem-spill"):
        return ("shrink the kernel's block shapes or deepen its grid "
                "so the blocks fit VMEM")
    if bottleneck == "collective-bound":
        return ("grow per-device work (bigger batch/sequence), drop the "
                "zero mode, or shrink the data axis until compute "
                "covers the collectives")
    if bottleneck.startswith("memory-bound"):
        return ("fuse or widen the flagged op class (bigger matmul "
                "tiles, fused kernels) — it streams more HBM bytes "
                "than its FLOPs cover")
    return ("raise arithmetic intensity (bigger batch, fused kernels) "
            "or accept the floor does not fit this model")
