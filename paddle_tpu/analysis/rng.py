"""GL-RNG — PRNG key-discipline passes (AST + jaxpr).

The repo's randomness convention is the reference's per-thread streams
rebuilt functionally: ONE seed enters at the edge (``core/rng.seed``,
``ServingConfig.seed``), and every consumer derives its stream with
``fold_in`` — per-layer (``LayerContext.key_for``), per-timestep
(``recurrent_group``), per-replica (the explicit ZeRO lowering folds
``axis_index("data")``), per-request/per-token (``serving/sampling``).
Two failure classes silently break it:

- **key reuse** — the same key drawn from twice yields correlated
  samples (dropout masks equal across layers, every sampled token the
  same draw).  The AST check flags a key name passed to two
  ``jax.random`` draws with no re-derivation between them (branch-
  aware: draws on mutually exclusive ``if``/``else`` arms share a key
  legitimately).
- **raw key literals** — ``jax.random.key(0)``/``PRNGKey(0)`` feeding
  a draw inside library code pins every run/replica to the same
  stream.  Literal keys are fine as *seeds* (CLI entry points,
  deterministic eval forwards that never draw); a literal that reaches
  a draw is the defect.

The jaxpr side (:func:`rng_fold_pass`) checks the per-replica
discipline the explicit data-parallel lowering established: a
``shard_map`` region over the data axis that DRAWS random bits without
folding ``axis_index`` into its key gives every replica the same
dropout mask — a silent effective-batch reduction no test notices.

Scope: the modules that own the fold-in convention
(:data:`RNG_MODULES`), the GL-THREAD model of scoping.
"""

from __future__ import annotations

import ast

from paddle_tpu.analysis.core import Finding, finalize

# the subsystems that own key derivation/consumption
RNG_MODULES = (
    "paddle_tpu/core/rng.py",
    "paddle_tpu/layers/api.py",
    "paddle_tpu/layers/base.py",
    "paddle_tpu/layers/recurrent_group.py",
    "paddle_tpu/ops/nn.py",
    "paddle_tpu/serving/sampling.py",
    "paddle_tpu/serving/engine.py",
    "paddle_tpu/resilience/chaos.py",
    "paddle_tpu/trainer/step.py",
)

# jax.random samplers that CONSUME a key (fold_in/split DERIVE and are
# the sanctioned re-use points)
_DRAW_FNS = frozenset({
    "normal", "uniform", "bernoulli", "categorical", "randint",
    "truncated_normal", "gumbel", "choice", "permutation", "exponential",
    "laplace", "gamma", "beta", "poisson", "bits", "rademacher",
})
_KEY_CTORS = frozenset({"key", "PRNGKey"})


def _is_random_attr(fn: ast.AST, names: frozenset) -> bool:
    """``jax.random.<name>`` / bare ``random.<name>`` (the
    ``from jax import random`` spelling) call heads — NOT
    ``np.random.<name>``: numpy samplers take distribution params, not
    keys, so matching them would read a mean/sigma as a reused key."""
    if not (isinstance(fn, ast.Attribute) and fn.attr in names):
        return False
    base = fn.value
    if isinstance(base, ast.Name):
        return base.id == "random"
    if isinstance(base, ast.Attribute):
        return (base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id == "jax")
    return False


def _is_literal_key_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _is_random_attr(node.func, _KEY_CTORS)
            and node.args
            and all(isinstance(a, ast.Constant) for a in node.args))


def _draw_key_expr(call: ast.Call) -> ast.AST | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _paths_exclusive(pa: tuple, pb: tuple) -> bool:
    """True when two branch paths sit on mutually exclusive arms of the
    same conditional — the one legitimate same-key-two-draws shape."""
    for a, b in zip(pa, pb):
        if a == b:
            continue
        return a[0] == b[0]  # same If node, different arms: exclusive
    return False


class _FnRng(ast.NodeVisitor):
    """RNG events of ONE function body (nested defs/lambdas are their
    own units): key-name assignments, draw uses with their branch path,
    names bound to literal keys."""

    def __init__(self):
        self.assigns: list[tuple[str, int]] = []
        self.uses: list[tuple[str, int, tuple]] = []   # (name, line, path)
        self.literal_draws: list[int] = []             # draw lines
        self.litkey_lines: dict[str, int] = {}         # name -> bind line
        self._path: tuple = ()

    # nested functions/lambdas are separate units
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        pass

    def visit_If(self, node: ast.If):
        self.visit(node.test)
        base = self._path
        self._path = base + ((id(node), "if"),)
        for stmt in node.body:
            self.visit(stmt)
        self._path = base + ((id(node), "else"),)
        for stmt in node.orelse:
            self.visit(stmt)
        self._path = base

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.assigns.append((node.id, node.lineno))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if _is_literal_key_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.litkey_lines[t.id] = node.lineno
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if _is_random_attr(node.func, _DRAW_FNS):
            kexpr = _draw_key_expr(node)
            if kexpr is not None and _is_literal_key_call(kexpr):
                self.literal_draws.append(node.lineno)
            elif isinstance(kexpr, ast.Name):
                self.uses.append((kexpr.id, node.lineno, self._path))
        self.generic_visit(node)


def _function_units(tree: ast.AST):
    """(qualname, body statements) per function, nested defs collapsed
    out of their parents (each body analyzed exactly once)."""
    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield ".".join(stack + [child.name]), child
                yield from walk(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, stack + [child.name])
            else:
                yield from walk(child, stack)

    yield from walk(tree, [])


def pass_rng_discipline(corpus, root,
                        modules: tuple = RNG_MODULES) -> list[Finding]:
    findings = []
    for rel in modules:
        if rel not in corpus:
            continue
        _src, tree = corpus[rel]
        for qual, fn in _function_units(tree):
            v = _FnRng()
            for stmt in fn.body:
                v.visit(stmt)
            for line in v.literal_draws:
                findings.append(Finding(
                    "GL-RNG", rel, line, qual,
                    "a literal-seeded key (jax.random.key/PRNGKey of a "
                    "constant) feeds a random draw — every run and "
                    "every replica gets the same stream; derive the key "
                    "from the step/request key via fold_in"))
            used_lit: set[str] = set()
            for name, line, _path in v.uses:
                bind = v.litkey_lines.get(name)
                if bind is not None and bind < line and name not in used_lit:
                    used_lit.add(name)
                    findings.append(Finding(
                        "GL-RNG", rel, line, qual,
                        f"key `{name}` is bound to a literal seed and "
                        f"then drawn from — a fixed stream in library "
                        f"code; thread the caller's key in and fold_in "
                        f"a scope instead"))
            reused: set[str] = set()
            uses_by_name: dict[str, list] = {}
            for name, line, path in sorted(v.uses, key=lambda u: u[1]):
                uses_by_name.setdefault(name, []).append((line, path))
            for name, uses in uses_by_name.items():
                if name in used_lit:
                    continue
                for (l1, p1), (l2, p2) in zip(uses, uses[1:]):
                    if name in reused:
                        break
                    if any(l1 < al <= l2 for an, al in v.assigns
                           if an == name):
                        continue  # re-derived between the draws
                    if _paths_exclusive(p1, p2):
                        continue  # if/else arms: only one executes
                    reused.add(name)
                    findings.append(Finding(
                        "GL-RNG", rel, l2, qual,
                        f"key `{name}` is consumed by two random draws "
                        f"(lines {l1} and {l2}) without an intervening "
                        f"split/fold_in — the draws are identical/"
                        f"correlated; derive a fresh subkey per draw"))
    return findings


# -- jaxpr side: the per-replica fold-in discipline -----------------------------

_DRAW_PRIMS = frozenset({"random_bits", "threefry2x32", "random_gamma"})
_FOLD_PRIMS = frozenset({"random_fold_in"})


def _inner_jaxprs(eqn):
    from paddle_tpu.analysis.program import inner_jaxprs

    return inner_jaxprs(eqn)


def _is_lit(v) -> bool:
    return hasattr(v, "val")


def _fold_sees_axis(jx, tainted: set | None = None) -> bool:
    """True when some ``random_fold_in`` consumes a value derived from
    ``axis_index`` — the per-replica key derivation.  Taint propagates
    through every eqn that touches a tainted var, including positionally
    into sub-jaxpr bodies (pjit-wrapped fold_in helpers)."""
    tainted = set(tainted or ())
    for eqn in jx.eqns:
        pname = eqn.primitive.name
        if pname == "axis_index":
            tainted.update(eqn.outvars)
            continue
        hit = any((not _is_lit(v)) and v in tainted for v in eqn.invars)
        if not hit:
            continue
        if pname in _FOLD_PRIMS:
            return True
        for sub in _inner_jaxprs(eqn):
            if len(sub.invars) == len(eqn.invars):
                inner_tainted = {sub.invars[i]
                                 for i, v in enumerate(eqn.invars)
                                 if (not _is_lit(v)) and v in tainted}
            else:  # unknown calling convention: taint everything
                inner_tainted = set(sub.invars)
            if _fold_sees_axis(sub, inner_tainted):
                return True
        tainted.update(eqn.outvars)
    return False


def rng_fold_pass(fn_or_jaxpr, *args, name: str = "step",
                  axis: str = "data") -> list[Finding]:
    """Flag a ``shard_map`` region over ``axis`` that draws random bits
    without folding ``axis_index(axis)`` into its key: every replica
    draws the SAME dropout mask/noise, silently collapsing the
    independent per-replica streams the reference's per-thread RNGs
    (and the explicit ZeRO lowering) guarantee."""
    from paddle_tpu.analysis.program import _walk_eqns, jaxpr_of

    jaxpr = jaxpr_of(fn_or_jaxpr, *args)
    findings = []
    for eqn in _walk_eqns(jaxpr.jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params.get("mesh")
        axis_names = tuple(getattr(mesh, "axis_names", ()) or ())
        if axis_names and axis not in axis_names:
            continue
        body = next(iter(_inner_jaxprs(eqn)), None)
        if body is None:
            continue
        draws = any(e.primitive.name in _DRAW_PRIMS
                    for e in _walk_eqns(body))
        if draws and not _fold_sees_axis(body):
            findings.append(Finding(
                "GL-RNG", f"<program:{name}>", 0, "shard-fold",
                f"a shard_map region over '{axis}' draws random bits "
                f"without folding axis_index('{axis}') into its key — "
                f"every replica draws the SAME mask/noise; fold the "
                f"replica index in (trainer/step.py's explicit-lowering "
                f"convention)"))
    return finalize(findings)
