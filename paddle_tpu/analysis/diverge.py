"""GL-P-DIVERGE — cross-rank program-divergence detection.

A multi-host fleet only works if every rank traced the SAME program:
a rank whose config drift (env override, version skew, different auto-
resolved lowering) produced a different HLO issues its collectives in a
different order and the whole fleet deadlocks in the first one — with
no error, no log line, and a hardware hold until someone pages.

The fix is the one the GL-P-COLL dual-lowering check applies within a
process, lifted across ranks: every rank fingerprints its lowered
program (canonicalized so SSA numbering/metadata churn doesn't count as
divergence), publishes the fingerprint at a filesystem rendezvous
(``distributed.launch``'s shared directory model — the same medium as
the elastic membership file), waits for its peers, and ABORTS preflight
with a named diff when any rank disagrees — instead of hanging in the
first collective of step one.

The fingerprint keeps the canonical op-kind sequence alongside the
hash, so a mismatch names the first divergent operation
(``op[37]: all-gather vs reduce-scatter``), not just "hashes differ".
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time

from paddle_tpu.analysis.core import Finding, finalize


def _pname(name: str) -> str:
    return f"<program:{name}>"


_METADATA_RE = re.compile(r"metadata=\{[^}]*\}")
_SSA_RE = re.compile(r"%[\w.\-#]+")
_LOC_RE = re.compile(r"loc\([^)]*\)")
_WS_RE = re.compile(r"\s+")
# opcode of one canonicalized line: `%_ = stablehlo.add %_, %_ : ...`
# or HLO `%_ = f32[8]{0} add(f32[8]{0} %_, ...)`
_OP_RE = re.compile(r"^%_ =\s*(?:[\w\[\]{},]+\s+)?([\w.\-]+)")


def canonical_lines(program_text: str) -> list[str]:
    """Program text with SSA ids, source metadata and whitespace
    normalized away — two builds of the same program canonicalize
    identically even across process restarts."""
    out = []
    for line in program_text.splitlines():
        s = line.strip()
        if not s or s.startswith(("//", "#")):
            continue
        s = _METADATA_RE.sub("", s)
        s = _LOC_RE.sub("", s)
        s = _SSA_RE.sub("%_", s)
        s = _WS_RE.sub(" ", s).strip()
        out.append(s)
    return out


def _op_kinds(lines: list[str]) -> list[str]:
    kinds = []
    for s in lines:
        m = _OP_RE.match(s)
        if m:
            kinds.append(m.group(1))
    return kinds


def program_fingerprint(program_text: str, *, rank: int = 0,
                        label: str = "") -> dict:
    """Canonical fingerprint of a lowered program: a hash over the
    canonical text plus the op-kind sequence AND the canonical lines —
    both ride along so a mismatch can name the divergent instruction
    even when the op-kind sequences agree (shape-only drift: same ops,
    different batch/seq dims)."""
    lines = canonical_lines(program_text)
    digest = hashlib.sha256("\n".join(lines).encode()).hexdigest()
    return {"hash": digest, "ops": _op_kinds(lines), "lines": lines,
            "n_lines": len(lines), "rank": int(rank), "label": label}


def _fp_path(rendezvous_dir: str, epoch: int, rank: int) -> str:
    return os.path.join(rendezvous_dir,
                        f"preflight-fp-e{epoch}-rank{rank}.json")


def publish_fingerprint(fp: dict, rendezvous_dir: str, rank: int, *,
                        epoch: int = 0) -> str:
    """Atomically write this rank's fingerprint into the rendezvous
    directory (tmp + rename, the membership-file discipline)."""
    os.makedirs(rendezvous_dir, exist_ok=True)
    path = _fp_path(rendezvous_dir, epoch, rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(fp, f)
    os.replace(tmp, path)
    return path


def exchange_fingerprints(fp: dict, rendezvous_dir: str, rank: int,
                          nproc: int, *, epoch: int = 0,
                          timeout_s: float = 120.0,
                          poll_s: float = 0.05) -> dict[int, dict]:
    """Publish this rank's fingerprint and collect every peer's.
    Raises TimeoutError naming the ranks that never published — a rank
    that cannot even build its program is itself the divergence.

    ``rendezvous_dir`` must be unique per launch (``distributed.launch``
    stamps a pid-suffixed directory): reusing a directory across
    launches would let stale files from a previous fleet vouch for a
    rank that died before publishing."""
    publish_fingerprint(fp, rendezvous_dir, rank, epoch=epoch)
    deadline = time.monotonic() + timeout_s
    fps: dict[int, dict] = {int(rank): fp}
    while True:
        missing = []
        for r in range(nproc):
            if r in fps:
                continue
            try:
                with open(_fp_path(rendezvous_dir, epoch, r)) as f:
                    fps[r] = json.load(f)
            except (OSError, json.JSONDecodeError):
                missing.append(r)
        if not missing:
            return fps
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"preflight rendezvous: rank(s) {missing} published no "
                f"program fingerprint within {timeout_s:.0f}s")
        time.sleep(poll_s)


def _first_diff(a: list[str], b: list[str]) -> tuple[int, str, str] | None:
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return i, x, y
    if len(a) == len(b):
        return None
    i = min(len(a), len(b))
    return (i, a[i] if i < len(a) else "<end-of-program>",
            b[i] if i < len(b) else "<end-of-program>")


def _named_diff(fp_a: dict, fp_b: dict) -> str:
    """Human-readable first divergence between two fingerprints: try
    the op-kind sequences, and when those agree (shape-only drift —
    same ops, different dims) fall back to the canonical LINES so the
    actual mismatching instruction is still named."""
    d = _first_diff(list(fp_a.get("ops") or []), list(fp_b.get("ops") or []))
    if d is not None:
        i, theirs, ours = d
        return f"op[{i}]: {theirs} vs {ours}"
    d = _first_diff(list(fp_a.get("lines") or []),
                    list(fp_b.get("lines") or []))
    if d is not None:
        i, theirs, ours = d
        return f"line[{i}]: {theirs[:80]} vs {ours[:80]}"
    return "op kinds agree — divergence is in canonicalized text not " \
           "captured line-wise (constants/attributes)"


def divergence_pass(fps: dict[int, dict],
                    name: str = "train_step") -> list[Finding]:
    """Compare every rank's fingerprint; one finding per divergent hash
    group, named by the first op where it parts from the majority
    program (ties break toward the lowest-rank group — rank 0 is the
    reference the launcher seeded)."""
    by_hash: dict[str, list[int]] = {}
    for r, fp in fps.items():
        by_hash.setdefault(str(fp.get("hash")), []).append(int(r))
    if len(by_hash) <= 1:
        return []
    ref_hash = max(by_hash, key=lambda h: (len(by_hash[h]),
                                           -min(by_hash[h])))
    ref_rank = min(by_hash[ref_hash])
    findings = []
    for h, ranks in sorted(by_hash.items(), key=lambda kv: min(kv[1])):
        if h == ref_hash:
            continue
        low = min(ranks)
        named = _named_diff(fps[low], fps[ref_rank])
        ranks_s = ",".join(str(r) for r in sorted(ranks))
        findings.append(Finding(
            "GL-P-DIVERGE", _pname(name), 0, f"rank-{low}",
            f"rank(s) {ranks_s} traced a DIFFERENT program than rank "
            f"{ref_rank} (hash {h[:12]} vs {ref_hash[:12]}; first "
            f"divergence at {named}) — a fleet mixing these programs "
            f"deadlocks in its first collective; align configs/env "
            f"before launch"))
    return finalize(findings)
