"""GL-KERNEL — the kernel reference-twin rule, as a graftlint pass.

Every Pallas kernel module must ship a pure-jnp reference twin
(``<entry>_reference``) and an interpret-mode parity test — the
Compare2Function discipline the reference applied to its CUDA kernels
(``paddle/function/FunctionTest.h``).  Concretely, for every module
under ``paddle_tpu/ops/pallas/`` (recursively, ``__init__`` excluded)
that calls ``pallas_call``:

1. the module defines at least one public ``<entry>_reference`` function
   whose base name ``<entry>`` is also defined in the module;
2. for each such pair, some file under ``tests/`` mentions BOTH the
   entry name and its reference name (the parity test — kernel vs
   oracle in interpret mode).

This absorbed ``tools/check_kernel_parity.py`` (PR 7); that script is
now a thin shim over :func:`audit` / :func:`main` so the existing
tier-1 wiring (``tests/test_kernel_parity.py``) is unchanged.
"""

from __future__ import annotations

import ast
import os

from paddle_tpu.analysis.core import Finding, repo_root


def _kernel_modules(repo: str) -> list[str]:
    pallas = os.path.join(repo, "paddle_tpu", "ops", "pallas")
    out = []
    for root, _dirs, files in os.walk(pallas):
        for f in sorted(files):
            if f.endswith(".py") and f != "__init__.py":
                out.append(os.path.join(root, f))
    return out


def _module_defs(path: str) -> list[str]:
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    return [n.name for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _uses_pallas(path: str) -> bool:
    with open(path) as fh:
        return "pallas_call" in fh.read()


def _tests_corpus(repo: str) -> str:
    tests = os.path.join(repo, "tests")
    chunks = []
    for f in sorted(os.listdir(tests)):
        if f.endswith(".py"):
            with open(os.path.join(tests, f)) as fh:
                chunks.append(fh.read())
    return "\n".join(chunks)


def kernel_parity_findings(repo: str | None = None) -> list[Finding]:
    repo = repo or repo_root()
    corpus = _tests_corpus(repo)
    findings = []
    for path in _kernel_modules(repo):
        rel = os.path.relpath(path, repo)
        if not _uses_pallas(path):
            continue
        defs = _module_defs(path)
        pairs = [(n[: -len("_reference")], n) for n in defs
                 if n.endswith("_reference") and not n.startswith("_")]
        pairs = [(base, ref) for base, ref in pairs if base in defs]
        if not pairs:
            findings.append(Finding(
                "GL-KERNEL", rel, 0, "<module>",
                "no public <entry>/<entry>_reference pair — every kernel "
                "module needs a jnp oracle"))
            continue
        for base, ref in pairs:
            if base not in corpus or ref not in corpus:
                missing = [n for n in (base, ref) if n not in corpus]
                findings.append(Finding(
                    "GL-KERNEL", rel, 0, base,
                    f"{base!r} has no interpret-mode parity test under "
                    f"tests/ ({', '.join(missing)} never referenced)"))
    return findings


def audit(repo: str | None = None) -> list[str]:
    """Violation strings (empty = pass) — the historical
    ``check_kernel_parity.audit`` contract the tools shim re-exports."""
    return [f"{f.path}: {f.message}" for f in kernel_parity_findings(repo)]


def main(repo: str | None = None) -> int:
    repo = repo or repo_root()
    violations = audit(repo)
    mods = [m for m in _kernel_modules(repo) if _uses_pallas(m)]
    if violations:
        print(f"check_kernel_parity: {len(violations)} violation(s) over "
              f"{len(mods)} kernel modules:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(f"check_kernel_parity: OK — {len(mods)} kernel modules, every "
          f"entry has a jnp reference and a tests/ parity mention")
    return 0
