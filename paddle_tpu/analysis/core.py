"""Finding/baseline machinery shared by every graftlint pass.

A :class:`Finding` is one violation.  Its identity (:attr:`Finding.fid`)
is ``RULE:path:anchor[#ordinal]`` — the anchor is the enclosing
qualified name (``Class.method``, a function, or ``<module>``), NOT a
line number, so IDs survive unrelated edits and the checked-in baseline
(``baseline.json`` next to this file) stays stable.  Multiple findings
of one rule in one anchor get ``#2``, ``#3``… ordinals in source order.

The baseline maps fid -> reason string.  A finding whose fid appears in
the baseline is *suppressed* (reported separately, never a failure); a
baseline entry matching nothing in a full run is *stale* and reported
so dead suppressions get cleaned up.
"""

from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass
class Finding:
    """One static-analysis violation."""

    rule: str       # stable pass/rule id, e.g. "GL-EXCEPT"
    path: str       # repo-relative path, or "<program:NAME>" for program passes
    line: int       # 1-based line (0 for whole-file / program findings)
    anchor: str     # enclosing qualified name ("Class.method", "<module>")
    message: str
    ordinal: int = 1  # disambiguates same rule+path+anchor; set by finalize

    @property
    def fid(self) -> str:
        base = f"{self.rule}:{self.path}:{self.anchor}"
        return base if self.ordinal == 1 else f"{base}#{self.ordinal}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{self.fid}\n    {loc} — {self.message}"


def finalize(findings: list[Finding]) -> list[Finding]:
    """Assign ordinals to findings sharing a (rule, path, anchor) so
    every fid is unique; order (source order) is preserved."""
    seen: dict[tuple, int] = {}
    for f in findings:
        key = (f.rule, f.path, f.anchor)
        seen[key] = seen.get(key, 0) + 1
        f.ordinal = seen[key]
    return findings


def repo_root() -> str:
    """The repository root — the directory holding ``paddle_tpu/``."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str | None = None) -> dict[str, str]:
    """{fid: reason}.  A missing file is an empty baseline."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    sup = data.get("suppressions", data)
    if not isinstance(sup, dict):
        raise ValueError(f"baseline {path}: 'suppressions' must be a dict")
    return {str(k): str(v) for k, v in sup.items()}


def apply_baseline(findings: list[Finding], baseline: dict[str, str],
                   full_run: bool = True,
                   ) -> tuple[list[Finding], list[Finding], list[str]]:
    """(unsuppressed, suppressed, stale baseline fids).

    ``full_run=False`` (``--changed`` scoping) skips the stale check —
    a subset run cannot tell a stale entry from an out-of-scope one."""
    unsup, sup = [], []
    hit: set[str] = set()
    for f in findings:
        if f.fid in baseline:
            hit.add(f.fid)
            sup.append(f)
        else:
            unsup.append(f)
    stale = sorted(set(baseline) - hit) if full_run else []
    return unsup, sup, stale
