"""Codebase passes — static analysis over the repo's own AST.

Five passes share one corpus (every ``.py`` under the scanned roots,
parsed once):

- ``GL-EXCEPT``    swallow-all ``except`` detector: a broad handler
  (bare / ``Exception`` / ``BaseException``) that neither re-raises nor
  logs nor routes through ``telemetry.safe_inc`` silently eats the
  error — the PR 4 ``safe_inc`` regression class.
- ``GL-THREAD``    cross-thread attribute audit of the threaded
  subsystems: an attribute written outside ``__init__`` and touched
  from more than one thread domain (worker-thread entry points vs the
  public API) must hold the class's declared lock at every access.
- ``GL-LOCKORDER`` lock-order-cycle detection from the per-module lock
  registry built by the same audit (lock A held while acquiring B and
  elsewhere B while acquiring A = a deadlock waiting for contention).
- ``GL-ENV``       env-var reads without a ``core/flags`` registration:
  every literal ``os.environ``/``os.getenv`` read must name either a
  defined flag's ``PADDLE_TPU_<NAME>`` override or an explicitly
  declared env passthrough (``flags.declare_env``).
- ``GL-SCHEMA``    telemetry record-kind drift: every ``kind`` a record
  carries (``emit(..., kind=...)`` or a ``{"kind": ...}`` literal) must
  be listed in ``telemetry.registry.RECORD_KINDS``, and every listed
  kind must actually be produced somewhere.

Thread-domain model (GL-THREAD): worker entries are methods passed as
``threading.Thread(target=self.m)`` (or a nested function passed as
``target=``/a ``signal.signal`` handler — both run asynchronously to
the caller); the consumer domain is the public API (public methods and
dunders).  A private helper reachable from both counts in both.
Attributes whose ``__init__`` value is itself a synchronization-safe
type (``queue.Queue``, ``threading.Event``/``Lock``/…) are exempt;
mutations through container methods (``append``/``clear``/…),
subscript stores and augmented assignment count as writes.
"""

from __future__ import annotations

import ast
import os

from paddle_tpu.analysis.core import Finding, finalize, repo_root

# -- corpus ---------------------------------------------------------------------

DEFAULT_ROOTS = ("paddle_tpu", "tools", "bench.py")

# the threaded subsystems under the GL-THREAD / GL-LOCKORDER audit
THREADED_MODULES = (
    "paddle_tpu/reader/prefetch.py",
    "paddle_tpu/serving/engine.py",
    "paddle_tpu/serving/dense.py",
    "paddle_tpu/serving/fleet.py",
    "paddle_tpu/serving/router.py",
    "paddle_tpu/serving/health.py",
    "paddle_tpu/resilience/elastic.py",
    "paddle_tpu/resilience/supervisor.py",
    "paddle_tpu/deploy/controller.py",
    "paddle_tpu/deploy/autoscaler.py",
    "paddle_tpu/deploy/arbiter.py",
    "paddle_tpu/trainer/checkpoint.py",
    "paddle_tpu/telemetry/tracing.py",
    "paddle_tpu/telemetry/introspect.py",
    "paddle_tpu/telemetry/goodput.py",
)


def iter_corpus(root: str | None = None, files: list[str] | None = None,
                roots: tuple = DEFAULT_ROOTS) -> dict[str, tuple[str, ast.AST]]:
    """{repo-relative path: (source, parsed tree)} for every scanned
    ``.py`` file.  ``files`` (repo-relative) restricts the corpus (the
    ``--changed`` mode); unparseable files are skipped (syntax errors
    are the interpreter's job, not the linter's)."""
    root = root or repo_root()
    paths: list[str] = []
    if files is not None:
        # a subset still only covers the lintable roots: tests/ etc.
        # legitimately break package rules (broad excepts in fixtures)
        def in_roots(f: str) -> bool:
            return any(f == r or f.startswith(r.rstrip("/") + "/")
                       for r in roots)

        paths = [f for f in files if f.endswith(".py") and in_roots(f)
                 and os.path.exists(os.path.join(root, f))]
    else:
        for r in roots:
            full = os.path.join(root, r)
            if os.path.isfile(full):
                paths.append(r)
                continue
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        paths.append(os.path.relpath(
                            os.path.join(dirpath, f), root))
    corpus: dict[str, tuple[str, ast.AST]] = {}
    for rel in sorted(set(paths)):
        try:
            with open(os.path.join(root, rel)) as fh:
                src = fh.read()
            corpus[rel] = (src, ast.parse(src, filename=rel))
        except (OSError, SyntaxError, ValueError):
            continue
    return corpus


def _qualname_index(tree: ast.AST) -> dict[ast.AST, str]:
    """node -> enclosing qualified name ("Class.method", "fn.<locals>.g"
    collapsed to "fn.g", or "<module>")."""
    out: dict[ast.AST, str] = {}

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            s = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                s = stack + [child.name]
            out[child] = ".".join(s) if s else "<module>"
            walk(child, s)

    out[tree] = "<module>"
    walk(tree, [])
    return out


# -- GL-EXCEPT: swallow-all except detector -------------------------------------

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def _is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = []
    for n in ([t] if not isinstance(t, ast.Tuple) else t.elts):
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in _BROAD for n in names)


def _handler_records(h: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, logs, routes through a
    ``safe_*`` accounting helper, or *uses the caught exception value*
    (``except ... as e`` with ``e`` referenced — the propagate-to-
    consumer pattern, e.g. ``_ProducerError(e)`` or ``self._err = e``)
    — i.e. the swallow is not silent."""
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = None
            if isinstance(fn, ast.Attribute):
                name = fn.attr
            elif isinstance(fn, ast.Name):
                name = fn.id
            if name in _LOG_METHODS or (name or "").startswith("safe_"):
                return True
        if h.name and isinstance(node, ast.Name) and node.id == h.name \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


def pass_swallow_except(corpus, root) -> list[Finding]:
    findings = []
    for rel, (_src, tree) in corpus.items():
        qn = _qualname_index(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and not _handler_records(node):
                caught = ("bare except" if node.type is None
                          else ast.unparse(node.type))
                findings.append(Finding(
                    "GL-EXCEPT", rel, node.lineno, qn.get(node, "<module>"),
                    f"broad `except {caught}` swallows the error silently "
                    f"(no raise / log / safe_* accounting) — narrow the "
                    f"types, log it, or route through telemetry.safe_inc"))
    return findings


# -- GL-ENV: env reads without a core/flags registration ------------------------


def _env_read_name(node: ast.AST) -> tuple[str, int] | None:
    """Literal env-var name of an ``os.environ.get/[]`` / ``os.getenv``
    read, or None for writes / non-literal names."""
    if isinstance(node, ast.Call):
        fn = node.func
        # os.environ.get("X") / environ.get("X")
        if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                and isinstance(fn.value, (ast.Attribute, ast.Name))):
            base = (fn.value.attr if isinstance(fn.value, ast.Attribute)
                    else fn.value.id)
            if base == "environ" and node.args and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str):
                return node.args[0].value, node.lineno
        # os.getenv("X")
        if (isinstance(fn, ast.Attribute) and fn.attr == "getenv"
                or isinstance(fn, ast.Name) and fn.id == "getenv"):
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value, node.lineno
    # os.environ["X"] — loads only (ctx Store/Del = launcher-style writes)
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "environ" or \
                isinstance(v, ast.Name) and v.id == "environ":
            s = node.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                return s.value, node.lineno
    return None


def registered_env_names() -> set[str]:
    from paddle_tpu.core import flags

    return flags.known_env_names()


def pass_env_registration(corpus, root,
                          registered: set[str] | None = None) -> list[Finding]:
    if registered is None:
        registered = registered_env_names()
    findings = []
    for rel, (_src, tree) in corpus.items():
        if not rel.startswith("paddle_tpu"):
            continue  # tools/tests read ad-hoc env by design
        qn = _qualname_index(tree)
        for node in ast.walk(tree):
            got = _env_read_name(node)
            if got is None:
                continue
            name, line = got
            if name not in registered:
                findings.append(Finding(
                    "GL-ENV", rel, line, qn.get(node, "<module>"),
                    f"env var {name!r} read without a core/flags "
                    f"registration — define a flag (PADDLE_TPU_* "
                    f"override) or flags.declare_env({name!r}, ...)"))
    return findings


# -- GL-SCHEMA: telemetry record-kind drift -------------------------------------


def known_record_kinds() -> frozenset:
    from paddle_tpu.telemetry.registry import RECORD_KINDS

    return frozenset(RECORD_KINDS)


def _dict_kind(node: ast.Dict) -> str | None:
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and k.value == "kind" \
                and isinstance(v, ast.Constant) and isinstance(v.value, str):
            return v.value
    return None


def _emitted_kinds(tree: ast.AST) -> list[tuple[str, int, ast.AST]]:
    """(kind literal, line, call node) for every record the module
    emits: ``.emit(..., kind="x")`` kwargs, ``.emit({..."kind": "x"...})``
    dict-literal args, and ``rec = {...}; .emit(rec)`` / ``.emit(
    dict(rec))`` one-hop dataflow.  Dict literals that never reach an
    emit call are NOT records (layer attrs etc.) and are ignored."""
    named: dict[str, tuple[str, int]] = {}   # var -> (kind, dict line)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                and isinstance(node.value, ast.Dict):
            kind = _dict_kind(node.value)
            if kind is not None:
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        named[t.id] = (kind, node.value.lineno)
    out: list[tuple[str, int, ast.AST]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "emit"):
            continue
        for kw in node.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                out.append((kw.value.value, node.lineno, node))
        for arg in node.args:
            if isinstance(arg, ast.Dict):
                kind = _dict_kind(arg)
                if kind is not None:
                    out.append((kind, node.lineno, node))
            elif isinstance(arg, ast.Name) and arg.id in named:
                out.append((named[arg.id][0], node.lineno, node))
            elif isinstance(arg, ast.Call) and isinstance(
                    arg.func, ast.Name) and arg.func.id == "dict" \
                    and arg.args and isinstance(arg.args[0], ast.Name) \
                    and arg.args[0].id in named:
                out.append((named[arg.args[0].id][0], node.lineno, node))
    return out


def pass_schema_kinds(corpus, root, known: frozenset | None = None,
                      full_corpus: bool = True) -> list[Finding]:
    if known is None:
        known = known_record_kinds()
    findings = []
    produced: set[str] = set()
    for rel, (_src, tree) in corpus.items():
        if not (rel.startswith("paddle_tpu") or rel == "bench.py"):
            continue  # offline renderers (tools/) only consume kinds
        qn = _qualname_index(tree)
        for kind, line, node in _emitted_kinds(tree):
            produced.add(kind)
            if kind not in known:
                findings.append(Finding(
                    "GL-SCHEMA", rel, line, qn.get(node, "<module>"),
                    f"record kind {kind!r} is not listed in "
                    f"telemetry.registry.RECORD_KINDS — bump the "
                    f"SCHEMA changelog and register it"))
    if full_corpus:  # a file subset can't prove a kind is unproduced
        for kind in sorted(known - produced):
            findings.append(Finding(
                "GL-SCHEMA", "paddle_tpu/telemetry/registry.py", 0,
                "RECORD_KINDS",
                f"record kind {kind!r} is registered but nothing in the "
                f"scanned tree produces it — stale schema entry"))
    return findings


# -- GL-THREAD / GL-LOCKORDER: threaded-subsystem audit -------------------------

_LOCK_TYPES = {"Lock", "RLock", "Condition"}
_SAFE_TYPES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
               "Event", "Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "Barrier", "local"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "clear", "pop", "popleft", "remove", "discard", "add",
             "update", "setdefault", "popitem", "sort"}


class _Access:
    __slots__ = ("attr", "write", "line", "locks")

    def __init__(self, attr, write, line, locks):
        self.attr = attr
        self.write = write
        self.line = line
        self.locks = frozenset(locks)


def _self_attr(node) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _UnitVisitor(ast.NodeVisitor):
    """Collect self-attribute accesses (with held-lock context), self
    method calls, lock acquisitions and thread/signal targets of ONE
    code unit (a method body or a nested function)."""

    def __init__(self, lock_attrs: set[str]):
        self.lock_attrs = lock_attrs
        self.accesses: list[_Access] = []
        self.calls: list[tuple[str, frozenset]] = []   # (method, locks held)
        self.acquired: list[tuple[str, frozenset]] = []  # (lock, held before)
        self.thread_targets: list[str] = []   # self.<m> Thread targets
        self.local_targets: list[str] = []    # nested-function targets
        self._held: list[str] = []

    # -- lock scoping ----------------------------------------------------------
    def visit_With(self, node: ast.With):
        entered = []
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a in self.lock_attrs:
                self.acquired.append((a, frozenset(self._held)))
                self._held.append(a)
                entered.append(a)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for a in entered:
            self._held.remove(a)

    # -- nested functions are separate units -----------------------------------
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Attribute(self, node: ast.Attribute):
        a = _self_attr(node)
        if a is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.accesses.append(_Access(a, write, node.lineno, self._held))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        a = _self_attr(node.value)
        if a is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            self.accesses.append(_Access(a, True, node.lineno, self._held))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        a = _self_attr(node.target)
        if a is not None:
            self.accesses.append(_Access(a, True, node.lineno, self._held))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        # self.m(...) — intra-class call edge
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self":
            self.calls.append((fn.attr, frozenset(self._held)))
        # self.attr.mutator(...) — counts as a write to attr
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            a = _self_attr(fn.value)
            if a is not None:
                self.accesses.append(
                    _Access(a, True, node.lineno, self._held))
        # threading.Thread(target=...)
        if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    t = _self_attr(kw.value)
                    if t is not None:
                        self.thread_targets.append(t)
                    elif isinstance(kw.value, ast.Name):
                        self.local_targets.append(kw.value.id)
        # signal.signal(sig, handler) — handler runs asynchronously
        if isinstance(fn, ast.Attribute) and fn.attr == "signal" \
                and len(node.args) >= 2:
            h = node.args[1]
            t = _self_attr(h)
            if t is not None:
                self.thread_targets.append(t)
            elif isinstance(h, ast.Name):
                self.local_targets.append(h.id)
        self.generic_visit(node)


class _ClassAudit:
    """Thread-domain model of one class (see module docstring)."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.methods: dict[str, ast.FunctionDef] = {}
        for n in cls.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[n.name] = n
        self.lock_attrs: set[str] = set()
        self.safe_attrs: set[str] = set()
        self._find_attr_types()
        # units: method name or "method.<nested>" -> visitor
        self.units: dict[str, _UnitVisitor] = {}
        self.worker_entries: set[str] = set()
        self._visit_units()

    def _find_attr_types(self):
        for m in self.methods.values():
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                v = node.value
                if not (isinstance(v, ast.Call)
                        and isinstance(v.func, (ast.Attribute, ast.Name))):
                    continue
                ctor = (v.func.attr if isinstance(v.func, ast.Attribute)
                        else v.func.id)
                for tgt in node.targets:
                    a = _self_attr(tgt)
                    if a is None:
                        continue
                    if ctor in _LOCK_TYPES:
                        self.lock_attrs.add(a)
                    if ctor in _SAFE_TYPES:
                        self.safe_attrs.add(a)

    def _visit_units(self):
        for name, m in self.methods.items():
            uv = _UnitVisitor(self.lock_attrs)
            for stmt in m.body:
                uv.visit(stmt)
            self.units[name] = uv
            for t in uv.thread_targets:
                if t in self.methods:
                    self.worker_entries.add(t)
            # nested functions used as thread/signal targets
            nested = {n.name: n for n in ast.walk(m)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
            for t in uv.local_targets:
                if t in nested:
                    unit = f"{name}.{t}"
                    nv = _UnitVisitor(self.lock_attrs)
                    for stmt in nested[t].body:
                        nv.visit(stmt)
                    self.units[unit] = nv
                    self.worker_entries.add(unit)

    def _reachable(self, entries: set[str]) -> set[str]:
        seen = set()
        todo = [e for e in entries if e in self.units]
        while todo:
            u = todo.pop()
            if u in seen:
                continue
            seen.add(u)
            for callee, _held in self.units[u].calls:
                if callee in self.units and callee not in seen:
                    todo.append(callee)
        return seen

    def domains(self) -> dict[str, set[str]]:
        """{unit: set of domains} — "worker" and/or "consumer"."""
        worker = self._reachable(self.worker_entries)
        consumer_entries = {
            n for n in self.units
            if "." not in n and n not in self.worker_entries
            and (not n.startswith("_") or (n.startswith("__")
                                           and n.endswith("__")))
            and n != "__init__"}
        consumer = self._reachable(consumer_entries)
        out: dict[str, set[str]] = {}
        for u in self.units:
            if u == "__init__":
                continue
            d = set()
            if u in worker:
                d.add("worker")
            if u in consumer:
                d.add("consumer")
            if d:
                out[u] = d
        return out

    def findings(self, rel: str) -> list[Finding]:
        if not self.worker_entries:
            return []
        per_attr: dict[str, dict] = {}
        for unit, doms in self.domains().items():
            for acc in self.units[unit].accesses:
                if acc.attr in self.safe_attrs or acc.attr in self.lock_attrs:
                    continue
                rec = per_attr.setdefault(acc.attr, {
                    "domains": set(), "write": False,
                    "unlocked": None, "line": acc.line})
                rec["domains"] |= doms
                rec["write"] |= acc.write
                if not acc.locks and rec["unlocked"] is None:
                    rec["unlocked"] = (unit, acc.line)
        out = []
        for attr, rec in sorted(per_attr.items()):
            if len(rec["domains"]) < 2 or not rec["write"] \
                    or rec["unlocked"] is None:
                continue
            unit, line = rec["unlocked"]
            lock = (f"`self.{sorted(self.lock_attrs)[0]}`"
                    if self.lock_attrs else "a lock (none declared!)")
            out.append(Finding(
                "GL-THREAD", rel, line, f"{self.cls.name}.{attr}",
                f"attribute `self.{attr}` is shared between the worker "
                f"and consumer thread domains with a write outside "
                f"__init__, but `{unit}` touches it without holding "
                f"{lock}"))
        return out

    def lock_order_edges(self) -> set[tuple[str, str]]:
        """(held, acquired) pairs: direct `with` nesting plus one level
        of self-call propagation (calling a method that acquires B while
        holding A)."""
        edges: set[tuple[str, str]] = set()
        for uv in self.units.values():
            for lock, held in uv.acquired:
                for h in held:
                    if h != lock:
                        edges.add((h, lock))
            for callee, held in uv.calls:
                if not held or callee not in self.units:
                    continue
                for lock, _ in self.units[callee].acquired:
                    for h in held:
                        if h != lock:
                            edges.add((h, lock))
        return edges


def _find_cycle(edges: set[tuple[str, str]]) -> list[str] | None:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    state: dict[str, int] = {}
    stack: list[str] = []

    def dfs(n):
        state[n] = 1
        stack.append(n)
        for m in graph.get(n, ()):
            if state.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if state.get(m, 0) == 0:
                c = dfs(m)
                if c:
                    return c
        state[n] = 2
        stack.pop()
        return None

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            c = dfs(n)
            if c:
                return c
    return None


def _audit_modules(corpus, modules) -> dict[str, list[_ClassAudit]]:
    out = {}
    for rel in modules:
        if rel not in corpus:
            continue
        _src, tree = corpus[rel]
        out[rel] = [_ClassAudit(n) for n in tree.body
                    if isinstance(n, ast.ClassDef)]
    return out


def pass_thread_safety(corpus, root,
                       modules: tuple = THREADED_MODULES) -> list[Finding]:
    findings = []
    for rel, audits in _audit_modules(corpus, modules).items():
        for a in audits:
            findings.extend(a.findings(rel))
    return findings


def pass_lock_order(corpus, root,
                    modules: tuple = THREADED_MODULES) -> list[Finding]:
    findings = []
    for rel, audits in _audit_modules(corpus, modules).items():
        for a in audits:
            cycle = _find_cycle(a.lock_order_edges())
            if cycle:
                findings.append(Finding(
                    "GL-LOCKORDER", rel, a.cls.lineno, a.cls.name,
                    f"lock-order cycle {' -> '.join(cycle)} — two code "
                    f"paths acquire these locks in opposite order; under "
                    f"contention they deadlock"))
    return findings


def lock_registry(root: str | None = None,
                  modules: tuple = THREADED_MODULES) -> dict:
    """{module: {class: sorted lock attrs}} — the per-module lock
    registry the lock-order pass works from (exposed for tests and the
    CLI's --locks listing)."""
    corpus = iter_corpus(root, files=list(modules))
    return {rel: {a.cls.name: sorted(a.lock_attrs)
                  for a in audits if a.lock_attrs}
            for rel, audits in _audit_modules(corpus, modules).items()}


# -- GL-KERNEL rides in from kernel_parity (registered here) --------------------


def pass_kernel_parity(corpus, root) -> list[Finding]:
    from paddle_tpu.analysis.kernel_parity import kernel_parity_findings

    return kernel_parity_findings(root)


# -- GL-RNG rides in from analysis/rng (registered here) ------------------------


def pass_rng(corpus, root) -> list[Finding]:
    from paddle_tpu.analysis.rng import pass_rng_discipline

    return pass_rng_discipline(corpus, root)


CODEBASE_PASSES = {
    "except": pass_swallow_except,
    "thread": pass_thread_safety,
    "lockorder": pass_lock_order,
    "env": pass_env_registration,
    "schema": pass_schema_kinds,
    "kernel": pass_kernel_parity,
    "rng": pass_rng,
}


def run_codebase(root: str | None = None, files: list[str] | None = None,
                 passes: list[str] | None = None) -> list[Finding]:
    """Run the codebase passes over the repo (or a ``files`` subset);
    returns finalized findings in (pass, path, line) order."""
    root = root or repo_root()
    corpus = iter_corpus(root, files=files)
    selected = passes or list(CODEBASE_PASSES)
    findings: list[Finding] = []
    for name in selected:
        if name == "kernel" and files is not None:
            # the parity rule is corpus-global (tests/ must mention the
            # pair) — a changed-files subset can't evaluate it
            continue
        if name == "schema":
            findings.extend(pass_schema_kinds(
                corpus, root, full_corpus=files is None))
            continue
        findings.extend(CODEBASE_PASSES[name](corpus, root))
    findings.sort(key=lambda f: (f.rule, f.path, f.line))
    return finalize(findings)
