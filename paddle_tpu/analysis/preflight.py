"""Preflight — run the program passes over the ACTUAL configured train
step before a single optimizer step executes (``trainer --preflight``),
the way the reference's ``config_parser.py`` rejected a bad config
before any kernel ran.

:func:`trainer_preflight` builds the same jitted step ``cmd_train``
would train with (same topology/optimizer/mesh/zero mode/compute
dtype), lowers it once, and runs:

- ``GL-P-SYNC``    over the step's jaxpr (host callbacks compiled in);
- ``GL-P-DONATE``  over the lowered StableHLO (un-donated update-size
  buffers);
- ``GL-P-UPCAST``  over the jaxpr when the run declared bf16 compute;
- ``GL-P-COLL``    when ``zero >= 2`` on a multi-device pure-data mesh:
  both ZeRO lowerings (explicit shard_map and GSPMD constraints) are
  built and their collective sequences compared — the multi-host
  deadlock class;
- ``GL-P-RECOMPILE`` over the probe-signature set (the step's own feed
  signature plus any caller-supplied set, e.g. a resumed run's
  ``SGD._compiled_sigs``).

``inject`` (the ``preflight_inject`` flag; TESTING ONLY) seeds a
deterministic defect — ``host_sync`` wraps the step with a host
callback, ``collective_mismatch`` perturbs the GSPMD sequence — so the
regression tests can prove each check fires through the real CLI.

One ``kind="preflight"`` telemetry record (schema /7) is emitted per
run with the per-rule counts and unsuppressed finding ids.
"""

from __future__ import annotations

from paddle_tpu.analysis.core import (
    Finding,
    apply_baseline,
    load_baseline,
)
from paddle_tpu.analysis.core import finalize as finalize_build
from paddle_tpu.analysis.program import (
    collective_sequence_from_hlo_text,
    collective_sequence_from_jaxpr,
    compare_collective_lowerings,
    donation_pass,
    f32_upcast_pass,
    host_sync_pass,
    recompile_hazard_pass,
)


def _feed_signature(feed: dict) -> tuple:
    from paddle_tpu.trainer.trainer import _feed_signature as sig

    return sig(feed)


def trainer_preflight(topology, optimizer, feed, mesh=None, *,
                      zero: int = 0, compute_dtype=None,
                      sync_period: int | None = None,
                      signatures=None, inject: str = "",
                      name: str = "train_step",
                      min_donate_bytes: int = 1 << 20) -> list[Finding]:
    """Build the configured train step and run every applicable program
    pass; returns the raw findings (caller applies the baseline)."""
    import jax

    from paddle_tpu.core import parameters as _params_mod
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.trainer.step import build_train_step

    if inject not in ("", "host_sync", "collective_mismatch"):
        raise ValueError(f"unknown preflight_inject {inject!r}")
    mesh = mesh if mesh is not None else mesh_mod.get_mesh()
    dp = mesh.mesh.shape.get("data", 1)
    specs = {s.name: s for s in topology.param_specs()}
    params = _params_mod.create(topology).as_dict()
    opt_state = optimizer.init(params, specs)
    states = topology.init_states()
    key = jax.random.key(0)

    step = build_train_step(topology, optimizer, mesh,
                            compute_dtype=compute_dtype, zero=zero)
    args = (params, opt_state, states, feed, key)

    probe = step
    if inject == "host_sync":
        def probe(*a):  # noqa: F811 - the injected twin of the step
            jax.debug.callback(lambda: None)
            return step(*a)

    findings: list[Finding] = []
    try:
        findings += host_sync_pass(probe, *args, name=name,
                                   sync_period=sync_period)
    except Exception as e:
        # the config_parser-style rejection: a program that cannot even
        # trace must be fixed before anything runs (commonly: provider
        # input_types unreachable, so the probe feed mistypes a layer)
        findings.append(Finding(
            "GL-P-BUILD", f"<program:{name}>", 0, "trace",
            f"train step failed to trace ({type(e).__name__}: {e}) — "
            f"the configured program cannot be built"))
        return finalize_build(findings)
    try:
        lowered_text = step.lower(*args).as_text()
    except Exception as e:
        findings.append(Finding(
            "GL-P-DONATE", f"<program:{name}>", 0, "lowering",
            f"step failed to lower for the donation check ({e}) — the "
            f"program cannot be statically audited"))
        lowered_text = None
    if lowered_text is not None:
        findings += donation_pass(lowered_text, name=name,
                                  min_bytes=min_donate_bytes)
    bf16 = compute_dtype is not None and "bfloat16" in str(compute_dtype)
    if bf16:
        findings += f32_upcast_pass(step, *args, name=name)

    sigs = list(signatures or [])
    sigs.append(_feed_signature(feed))
    findings += recompile_hazard_pass(sigs, name=name)

    from paddle_tpu.parallel import zero as zero_mod

    if zero >= 2 and dp > 1 and zero_mod.explicit_lowering_ok(mesh.mesh):
        explicit_step = build_train_step(
            topology, optimizer, mesh, compute_dtype=compute_dtype,
            zero=zero, lowering="explicit")
        seq_a = collective_sequence_from_jaxpr(explicit_step, *args)
        gspmd_step = build_train_step(
            topology, optimizer, mesh, compute_dtype=compute_dtype,
            zero=zero, lowering="gspmd")
        hlo = gspmd_step.lower(*args).compile().as_text()
        seq_b = collective_sequence_from_hlo_text(hlo)
        if inject == "collective_mismatch":
            # drop every gradient reduction from one side: the seeded
            # config-drift defect (one host's program never reduces)
            seq_b = [k for k in seq_b
                     if k not in ("all_reduce", "reduce_scatter")]
        findings += compare_collective_lowerings(
            seq_a, seq_b, name=name, label_a="shard_map", label_b="gspmd")
    elif inject == "collective_mismatch":
        # the seeded defect must fire even where the mesh has no second
        # lowering to compare (dp == 1): perturb the explicit sequence
        # against itself so the CLI wiring is still provable end-to-end
        seq = ["reduce_scatter", "all_gather"]
        findings += compare_collective_lowerings(
            seq, ["all_gather"], name=name,
            label_a="shard_map", label_b="gspmd")
    return findings


def emit_preflight_record(findings, suppressed, *, registry=None,
                          run: str = "preflight", config: str = "") -> dict:
    """One schema/7 ``kind="preflight"`` record: per-rule counts, the
    unsuppressed finding ids, clean flag — rendered by
    ``tools/metrics_to_md.py``'s Preflight table."""
    from paddle_tpu import metrics as metrics_mod

    reg = registry or metrics_mod.get_registry()
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        reg.counter("preflight_findings",
                    "unsuppressed preflight/analysis findings").inc(
            1.0, rule=f.rule)
    rec = {
        "run": run, "config": config, "clean": not findings,
        "findings": len(findings), "suppressed": len(suppressed),
        "by_rule": by_rule,
        "ids": [f.fid for f in findings[:32]],
    }
    if reg.active:
        return reg.emit(rec, kind="preflight")
    return rec


def run_preflight(topology, optimizer, feed, mesh=None, *,
                  zero: int = 0, compute_dtype=None,
                  sync_period: int | None = None, inject: str = "",
                  baseline_path: str | None = None, registry=None,
                  config: str = "", name: str = "train_step",
                  ) -> tuple[list[Finding], list[Finding]]:
    """The full `trainer --preflight` flow: build + analyze + baseline +
    telemetry.  Returns (unsuppressed, suppressed)."""
    raw = trainer_preflight(
        topology, optimizer, feed, mesh, zero=zero,
        compute_dtype=compute_dtype, sync_period=sync_period,
        inject=inject, name=name)
    unsup, sup, _stale = apply_baseline(
        raw, load_baseline(baseline_path), full_run=False)
    emit_preflight_record(unsup, sup, registry=registry, config=config)
    return unsup, sup
