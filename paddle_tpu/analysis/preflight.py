"""Preflight — run the program passes over the ACTUAL configured train
step before a single optimizer step executes (``trainer --preflight``),
the way the reference's ``config_parser.py`` rejected a bad config
before any kernel ran.

:func:`trainer_preflight` builds the same jitted step ``cmd_train``
would train with (same topology/optimizer/mesh/zero mode/compute
dtype), lowers it once, and runs:

- ``GL-P-SYNC``    over the step's jaxpr (host callbacks compiled in);
- ``GL-P-DONATE``  over the lowered StableHLO (un-donated update-size
  buffers);
- ``GL-P-UPCAST``  over the jaxpr when the run declared bf16 compute;
- ``GL-P-MEM``     static per-device memory accounting (params +
  optimizer slots under the active zero mode + activation liveness
  from the jaxpr, refined by XLA's ``memory_analysis`` when the step
  compiles) against the ``--hbm_gb`` budget, plus per-``pallas_call``
  VMEM footprints against ``--vmem_mb``;
- ``GL-P-SHARD``   sharding-flow over the lowering when the data axis
  is live: large ``{replicated}`` pins and partitioner-inserted
  all-gathers that are not donated-parameter types;
- ``GL-RNG``       the per-replica fold-in discipline of shard_map
  regions that draw random bits;
- ``GL-P-COLL``    when ``zero >= 2`` on a multi-device pure-data mesh:
  both ZeRO lowerings (explicit shard_map and GSPMD constraints) are
  built and their collective sequences compared — the multi-host
  deadlock class;
- ``GL-P-DIVERGE`` when launched as one rank of a fleet (``nproc > 1``
  with a rendezvous directory): the canonicalized-HLO fingerprint is
  exchanged with every peer and a rank that traced a different program
  aborts preflight with a named diff instead of deadlocking in the
  first collective;
- ``GL-P-COST``    static roofline estimate (per-op-class FLOPs/bytes,
  pallas VMEM compute, collective wire model) under the ``--hw_profile``
  machine table — predicted step_ms / MFU%% land in the telemetry
  record and a config under ``--mfu_floor`` fails with a named
  bottleneck;
- ``GL-P-RECOMPILE`` over the probe-signature set (the step's own feed
  signature plus any caller-supplied set, e.g. a resumed run's
  ``SGD._compiled_sigs``);
- the same SYNC/BUILD checks over the EVAL step (``build_eval_step``)
  — eval programs can host-sync or fail to build independently of the
  train step.

``inject`` (the ``preflight_inject`` flag; TESTING ONLY) seeds a
deterministic defect — ``host_sync`` wraps the train step with a host
callback, ``host_sync_eval`` wraps the eval step, ``collective_
mismatch`` perturbs the GSPMD sequence, ``rank_divergence`` perturbs
every non-zero rank's program fingerprint — so the regression tests
can prove each check fires through the real CLI.

One ``kind="preflight"`` telemetry record (schema /13) is emitted per
run with the per-rule counts, the unsuppressed finding ids, the
GL-P-MEM memory report and the GL-P-COST cost report (rendered as
budget / static-cost tables by ``tools/metrics_to_md.py``).
"""

from __future__ import annotations

from paddle_tpu.analysis.core import (
    Finding,
    apply_baseline,
    load_baseline,
)
from paddle_tpu.analysis.core import finalize as finalize_build
from paddle_tpu.analysis.diverge import (
    divergence_pass,
    exchange_fingerprints,
    program_fingerprint,
)
from paddle_tpu.analysis.cost import (
    cost_budget_pass,
    cost_report,
)
from paddle_tpu.analysis.memory import (
    memory_budget_pass,
    memory_report,
)
from paddle_tpu.analysis.program import (
    collective_sequence_from_hlo_text,
    collective_sequence_from_jaxpr,
    compare_collective_lowerings,
    donation_pass,
    f32_upcast_pass,
    host_sync_pass,
    recompile_hazard_pass,
)
from paddle_tpu.analysis.rng import rng_fold_pass
from paddle_tpu.analysis.sharding import sharding_flow_pass

_INJECT_KINDS = ("", "host_sync", "host_sync_eval", "collective_mismatch",
                 "rank_divergence")


def _feed_signature(feed: dict) -> tuple:
    from paddle_tpu.trainer.trainer import _feed_signature as sig

    return sig(feed)


def trainer_preflight(topology, optimizer, feed, mesh=None, *,
                      zero: int = 0, compute_dtype=None,
                      sync_period: int | None = None,
                      signatures=None, inject: str = "",
                      name: str = "train_step",
                      min_donate_bytes: int = 1 << 20,
                      hbm_gb: float = 0.0, vmem_mb: float = 128.0,
                      hw_profile: str = "auto", mfu_floor: float = 0.0,
                      shard_min_bytes: int = 1 << 20,
                      include_eval: bool = True,
                      rendezvous_dir: str = "", rank: int = 0,
                      nproc: int = 1, rendezvous_epoch: int = 0,
                      report_out: dict | None = None,
                      cost_out: dict | None = None) -> list[Finding]:
    """Build the configured train step and run every applicable program
    pass; returns the raw findings (caller applies the baseline).
    ``report_out`` (a dict) receives the GL-P-MEM memory report and
    ``cost_out`` the GL-P-COST roofline report for the telemetry
    record."""
    import jax

    from paddle_tpu.core import parameters as _params_mod
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.trainer.step import build_eval_step, build_train_step

    if inject not in _INJECT_KINDS:
        raise ValueError(f"unknown preflight_inject {inject!r}")
    mesh = mesh if mesh is not None else mesh_mod.get_mesh()
    dp = mesh.mesh.shape.get("data", 1)
    specs = {s.name: s for s in topology.param_specs()}
    params = _params_mod.create(topology).as_dict()
    opt_state = optimizer.init(params, specs)
    states = topology.init_states()
    key = jax.random.key(0)

    step = build_train_step(topology, optimizer, mesh,
                            compute_dtype=compute_dtype, zero=zero)
    args = (params, opt_state, states, feed, key)

    probe = step
    if inject == "host_sync":
        def probe(*a):  # noqa: F811 - the injected twin of the step
            jax.debug.callback(lambda: None)
            return step(*a)

    findings: list[Finding] = []
    try:
        findings += host_sync_pass(probe, *args, name=name,
                                   sync_period=sync_period)
    except Exception as e:
        # the config_parser-style rejection: a program that cannot even
        # trace must be fixed before anything runs (commonly: provider
        # input_types unreachable, so the probe feed mistypes a layer)
        findings.append(Finding(
            "GL-P-BUILD", f"<program:{name}>", 0, "trace",
            f"train step failed to trace ({type(e).__name__}: {e}) — "
            f"the configured program cannot be built"))
        return finalize_build(findings)
    # trace ONCE: every jaxpr-level pass below accepts the pre-made
    # ClosedJaxpr (jaxpr_of pass-through) — retracing a big step per
    # pass would multiply seconds of pure tracing 3-4x per preflight
    from paddle_tpu.analysis.program import jaxpr_of

    step_jx = jaxpr_of(step, *args)
    lowered = None
    lowered_text = None
    try:
        lowered = step.lower(*args)
        lowered_text = lowered.as_text()
    except Exception as e:
        findings.append(Finding(
            "GL-P-DONATE", f"<program:{name}>", 0, "lowering",
            f"step failed to lower for the donation check ({e}) — the "
            f"program cannot be statically audited"))
    if lowered_text is not None:
        findings += donation_pass(lowered_text, name=name,
                                  min_bytes=min_donate_bytes)
    bf16 = compute_dtype is not None and "bfloat16" in str(compute_dtype)
    if bf16:
        findings += f32_upcast_pass(step_jx, name=name)

    # GL-RNG: the per-replica fold-in discipline of any shard_map region
    # that draws (dropout under the explicit ZeRO lowering)
    findings += rng_fold_pass(step_jx, name=name)

    # GL-P-MEM: the static budget.  The compile (for XLA's own temp-size
    # accounting and the GL-P-SHARD reshard scan) is best-effort — a
    # backend that cannot compile here still gets the jaxpr-walk numbers.
    compiled = None
    compiled_text = None
    if lowered is not None:
        try:
            compiled = lowered.compile()
            compiled_text = compiled.as_text()
        except Exception as e:
            from paddle_tpu.core import logger as log

            log.debug("preflight compile unavailable (%s); jaxpr-level "
                      "checks stand", e)
            compiled = None
    # per-param base sharding (e.g. row-sharded embedding tables) feeds
    # the params/slots byte model — aligned leaf-for-leaf with params
    from jax.sharding import PartitionSpec as _P

    base_specs = {
        n: (_P(*specs[n].sharding)
            if n in specs and getattr(specs[n], "sharding", None) else _P())
        for n in params}
    report = memory_report(params, opt_state, states, feed, mesh,
                           zero=zero, param_specs=base_specs,
                           step=step_jx, args=(), compiled=compiled)
    if report_out is not None:
        report_out.update(report)
    findings += memory_budget_pass(report, name=name, hbm_gb=hbm_gb,
                                   vmem_mb=vmem_mb)

    # GL-P-COST: the static roofline.  Reuses the one trace (step_jx),
    # the GL-P-MEM params accounting (the analytic ZeRO collective
    # schedule needs the gradient payload) and — when the lowering
    # succeeded — XLA's own per-signature cost analysis.
    try:
        cost = cost_report(step_jx, profile=hw_profile, mesh=mesh,
                           zero=zero,
                           params_bytes=report.get("params_bytes", 0),
                           lowered=lowered, compiled=compiled)
    except ValueError as e:  # unknown --hw_profile: a config error
        cost = None
        findings.append(Finding(
            "GL-P-COST", f"<program:{name}>", 0, "hw-profile",
            str(e)))
    if cost is not None:
        if cost_out is not None:
            cost_out.update(cost)
        findings += cost_budget_pass(cost, name=name,
                                     mfu_floor=mfu_floor)

    # GL-P-SHARD: sharding flow of the program that will actually run —
    # only meaningful with a live data axis (dp == 1 has no resharding)
    if dp > 1:
        findings += sharding_flow_pass(lowered_text, compiled_text,
                                       name=name,
                                       min_bytes=shard_min_bytes)

    sigs = list(signatures or [])
    sigs.append(_feed_signature(feed))
    findings += recompile_hazard_pass(sigs, name=name)

    # the EVAL program is built/compiled independently of the train step
    # (trainer.test, declared evaluators) and can host-sync on its own
    if include_eval:
        eval_step = build_eval_step(topology, mesh)
        eval_args = (params, states, feed)
        eval_probe = eval_step
        if inject == "host_sync_eval":
            def eval_probe(*a):  # noqa: F811
                jax.debug.callback(lambda: None)
                return eval_step(*a)
        try:
            findings += host_sync_pass(eval_probe, *eval_args,
                                       name="eval_step",
                                       sync_period=sync_period)
        except Exception as e:
            findings.append(Finding(
                "GL-P-BUILD", "<program:eval_step>", 0, "trace",
                f"eval step failed to trace ({type(e).__name__}: {e}) "
                f"— trainer.test / the declared evaluators would die "
                f"on their first batch"))

    from paddle_tpu.parallel import zero as zero_mod

    if zero >= 2 and dp > 1 and zero_mod.explicit_lowering_ok(mesh.mesh):
        explicit_step = build_train_step(
            topology, optimizer, mesh, compute_dtype=compute_dtype,
            zero=zero, lowering="explicit")
        seq_a = collective_sequence_from_jaxpr(explicit_step, *args)
        gspmd_step = build_train_step(
            topology, optimizer, mesh, compute_dtype=compute_dtype,
            zero=zero, lowering="gspmd")
        hlo = gspmd_step.lower(*args).compile().as_text()
        seq_b = collective_sequence_from_hlo_text(hlo)
        if inject == "collective_mismatch":
            # drop every gradient reduction from one side: the seeded
            # config-drift defect (one host's program never reduces)
            seq_b = [k for k in seq_b
                     if k not in ("all_reduce", "reduce_scatter")]
        findings += compare_collective_lowerings(
            seq_a, seq_b, name=name, label_a="shard_map", label_b="gspmd")
    elif inject == "collective_mismatch":
        # the seeded defect must fire even where the mesh has no second
        # lowering to compare (dp == 1): perturb the explicit sequence
        # against itself so the CLI wiring is still provable end-to-end
        seq = ["reduce_scatter", "all_gather"]
        findings += compare_collective_lowerings(
            seq, ["all_gather"], name=name,
            label_a="shard_map", label_b="gspmd")

    # GL-P-DIVERGE: fingerprint this rank's program and rendezvous with
    # every peer — a fleet must agree on the program BEFORE the first
    # collective, not deadlock inside it
    if nproc > 1 and rendezvous_dir:
        fp_text = (lowered_text if lowered_text is not None
                   else str(step_jx))
        fp = program_fingerprint(fp_text, rank=rank, label=name)
        if inject == "rank_divergence" and rank != 0:
            # the seeded config-drift defect: this rank's program
            # carries one extra op nobody else traced
            fp["ops"] = fp["ops"] + ["chaos.divergence"]
            fp["hash"] = f"chaos-{fp['hash'][:32]}-r{rank}"
        try:
            fps = exchange_fingerprints(fp, rendezvous_dir, rank, nproc,
                                        epoch=rendezvous_epoch)
            findings += divergence_pass(fps, name=name)
        except TimeoutError as e:
            findings.append(Finding(
                "GL-P-DIVERGE", f"<program:{name}>", 0, "rendezvous",
                f"{e} — a rank that cannot publish its program is "
                f"itself the divergence; do not launch"))
    return findings


def emit_preflight_record(findings, suppressed, *, registry=None,
                          run: str = "preflight", config: str = "",
                          memory: dict | None = None,
                          cost: dict | None = None) -> dict:
    """One schema/13 ``kind="preflight"`` record: per-rule counts, the
    unsuppressed finding ids, clean flag — plus the GL-P-MEM ``memory``
    budget report and the GL-P-COST ``cost`` roofline (predicted
    step_ms / MFU%% / bottleneck) — rendered by
    ``tools/metrics_to_md.py``'s Preflight / Static cost tables."""
    from paddle_tpu import metrics as metrics_mod

    reg = registry or metrics_mod.get_registry()
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        reg.counter("preflight_findings",
                    "unsuppressed preflight/analysis findings").inc(
            1.0, rule=f.rule)
    rec = {
        "run": run, "config": config, "clean": not findings,
        "findings": len(findings), "suppressed": len(suppressed),
        "by_rule": by_rule,
        "ids": [f.fid for f in findings[:32]],
    }
    if memory:
        rec["memory"] = dict(memory)
    if cost:
        rec["cost"] = dict(cost)
    if reg.active:
        return reg.emit(rec, kind="preflight")
    return rec


def run_preflight(topology, optimizer, feed, mesh=None, *,
                  zero: int = 0, compute_dtype=None,
                  sync_period: int | None = None, inject: str = "",
                  baseline_path: str | None = None, registry=None,
                  config: str = "", name: str = "train_step",
                  hbm_gb: float = 0.0, vmem_mb: float = 128.0,
                  hw_profile: str = "auto", mfu_floor: float = 0.0,
                  include_eval: bool = True,
                  rendezvous_dir: str = "", rank: int = 0, nproc: int = 1,
                  rendezvous_epoch: int = 0,
                  cost_out: dict | None = None,
                  ) -> tuple[list[Finding], list[Finding]]:
    """The full `trainer --preflight` flow: build + analyze + baseline +
    telemetry.  Returns (unsuppressed, suppressed)."""
    report: dict = {}
    cost: dict = {}
    raw = trainer_preflight(
        topology, optimizer, feed, mesh, zero=zero,
        compute_dtype=compute_dtype, sync_period=sync_period,
        inject=inject, name=name, hbm_gb=hbm_gb, vmem_mb=vmem_mb,
        hw_profile=hw_profile, mfu_floor=mfu_floor,
        include_eval=include_eval, rendezvous_dir=rendezvous_dir,
        rank=rank, nproc=nproc, rendezvous_epoch=rendezvous_epoch,
        report_out=report, cost_out=cost)
    if cost_out is not None:
        cost_out.update(cost)
    unsup, sup, _stale = apply_baseline(
        raw, load_baseline(baseline_path), full_run=False)
    emit_preflight_record(unsup, sup, registry=registry, config=config,
                          memory=report, cost=cost)
    return unsup, sup
