"""GL-P-SHARD — sharding-flow analysis over the GSPMD lowering.

The GSPMD partitioner (the machinery arxiv 2004.13336's weight-update
sharding directs with constraints) is free to satisfy a program's
sharding annotations by materializing replicated copies or inserting
resharding collectives the author never asked for.  Small ones are
noise; big ones are exactly the residency/traffic ZeRO-3 parameter
sharding exists to remove.  This pass statically flags, over the
lowered StableHLO (pre-partitioning, where ``mhlo.sharding``
annotations live) and the compiled HLO (post-partitioning, where the
inserted collectives live):

- ``replicated:<type>``  an intermediate explicitly constrained
  ``{replicated}`` of at least ``min_bytes`` whose type is NOT one of
  the donated entry arguments (params/opt-state flowing through are
  *sanctioned* replicated until ZeRO-3 exists — they are the donation
  pass's business, not this one's);
- ``reshard:<type>``     an ``all-gather`` the partitioner inserted
  whose output is at least ``min_bytes`` and is neither a donated-
  parameter type (the ZeRO param all-gather) nor on the caller's
  ``allowlist`` — an implicit resharding of activations/intermediates
  that multiplies step traffic without appearing anywhere in the
  source program.

Both checks are byte-gated (default 1 MiB, like GL-P-DONATE): on test-
sized programs the partitioner's small boundary gathers are healthy;
at model scale the same pattern is the regression this pass exists to
catch before the step runs.
"""

from __future__ import annotations

import re

from paddle_tpu.analysis.core import Finding, finalize
from paddle_tpu.analysis.program import (
    _DTYPE_BYTES,
    _parse_main_args,
    _tensor_bytes,
)


def _pname(name: str) -> str:
    return f"<program:{name}>"


# stablehlo tensor type "64x128xf32" -> normalized "f32[64,128]"
def _normalize_tensor(ty: str) -> str:
    parts = ty.split("x")
    return f"{parts[-1]}[{','.join(parts[:-1])}]"


_REPLICATED_CC_RE = re.compile(
    r'@Sharding\(%[\w.#]+\)\s*(\{[^\n]*?mhlo\.sharding\s*=\s*'
    r'"\{replicated\}"[^\n]*?\})\s*:\s*\([^)]*\)\s*->\s*tensor<([^>]+)>')

# compiled-HLO all-gather ops, sync `%x = f32[64,128]{1,0} all-gather(`
# AND async-start `%x = (f32[64,16], f32[64,128]) all-gather-start(` —
# TPU HLO emits the async pair by default, and `-done` lines reference
# the same result so only the defining op is counted
_HLO_AG_OP_RE = re.compile(r"\sall-gather(?:-start)?\(")
_HLO_TYPE_RE = re.compile(
    r"([a-z]+[0-9]*(?:e[0-9]+m[0-9]+(?:fn)?)?)\[([0-9,]*)\]")

_HLO_DTYPE_BYTES = dict(_DTYPE_BYTES, pred=1, s64=8, s32=4, s16=2, s8=1,
                        u64=8, u32=4, u16=2, u8=1)


def donated_entry_types(stablehlo_text: str) -> set[str]:
    """Normalized types (``f32[64,128]``) of @main arguments marked
    donated (``tf.aliasing_output``/``jax.buffer_donor``) — the
    update-in-place params/opt-state whose replication is sanctioned
    pre-ZeRO-3."""
    main = stablehlo_text.split("func.func public @main", 1)
    if len(main) < 2:
        return set()
    sig = main[1].split("\n", 1)[0]
    out = set()
    for _idx, ty, attrs in _parse_main_args(sig):
        if "tf.aliasing_output" in attrs or "jax.buffer_donor" in attrs:
            out.add(_normalize_tensor(ty))
    return out


def replicated_intermediates(stablehlo_text: str,
                             min_bytes: int) -> list[tuple[str, int]]:
    """(normalized type, bytes) per ``{replicated}`` sharding constraint
    of at least ``min_bytes`` — explicit replication pins in the traced
    program."""
    out = []
    for m in _REPLICATED_CC_RE.finditer(stablehlo_text):
        ty = m.group(2)
        nbytes = _tensor_bytes(ty)
        if nbytes >= min_bytes:
            out.append((_normalize_tensor(ty), nbytes))
    return out


def inserted_gathers(compiled_text: str,
                     min_bytes: int) -> list[tuple[str, int]]:
    """(normalized type, bytes) per ``all-gather`` in the compiled HLO
    whose output is at least ``min_bytes`` — the partitioner's
    materialization points."""
    out = []
    for line in compiled_text.splitlines():
        m = _HLO_AG_OP_RE.search(line)
        if not m or "=" not in line[:m.start()]:
            continue
        # result type(s) sit between `=` and the op name; the async
        # start form is a tuple (operand alias, gathered result) — the
        # materialized output is the LARGEST element
        head = line[line.index("=") + 1:m.start()]
        best: tuple[str, int] | None = None
        for dtype, dims in _HLO_TYPE_RE.findall(head):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes = n * _HLO_DTYPE_BYTES.get(dtype, 4)
            if best is None or nbytes > best[1]:
                best = (f"{dtype}[{dims}]", nbytes)
        if best is not None and best[1] >= min_bytes:
            out.append(best)
    return out


def sharding_flow_pass(stablehlo_text: str | None,
                       compiled_text: str | None = None,
                       name: str = "train_step", *,
                       min_bytes: int = 1 << 20,
                       allowlist: tuple = ()) -> list[Finding]:
    """Run both sharding-flow checks; either text may be None (the
    corresponding check is skipped).  ``allowlist`` entries are
    normalized type strings (``f32[1024,4096]``) the operator has
    reviewed and accepted."""
    findings: list[Finding] = []
    allowed: set[str] = set(allowlist)
    if stablehlo_text:
        allowed |= donated_entry_types(stablehlo_text)
        seen: set[str] = set()
        for ty, nbytes in replicated_intermediates(stablehlo_text,
                                                   min_bytes):
            if ty in allowed or ty in seen:
                continue
            seen.add(ty)
            findings.append(Finding(
                "GL-P-SHARD", _pname(name), 0, f"replicated:{ty}",
                f"intermediate {ty} ({nbytes / 1e6:.1f} MB) is pinned "
                f"{{replicated}} on every device — a full copy per "
                f"rank; shard it along a mesh axis or allowlist the "
                f"type with a reason"))
    if compiled_text:
        seen = set()
        for ty, nbytes in inserted_gathers(compiled_text, min_bytes):
            if ty in allowed or ty in seen:
                continue
            seen.add(ty)
            findings.append(Finding(
                "GL-P-SHARD", _pname(name), 0, f"reshard:{ty}",
                f"the partitioner inserted an all-gather materializing "
                f"{ty} ({nbytes / 1e6:.1f} MB) that is not a donated "
                f"parameter type — an implicit resharding the source "
                f"program never asked for; align the producer/consumer "
                f"shardings or allowlist the type with a reason"))
    return finalize(findings)
