"""graftlint — the jaxpr/HLO preflight + codebase static-analysis suite.

The reference framework front-loads correctness into static validation:
``config_parser.py`` rejects a bad config before a kernel runs, and a
Fluid ``ProgramDesc`` is a statically checkable program of ops.  This
package is the TPU-era equivalent, following the graph-analysis framing
of GDP (arxiv 1910.01578): analyze the dataflow program (and the repo
that builds it), don't just run it and wait for the bench to regress.

Two pass families share one finding/baseline machinery (:mod:`core`):

- **Program passes** (:mod:`program`, :mod:`memory`, :mod:`sharding`,
  :mod:`diverge`) run over the jaxpr / lowered HLO of a built train or
  serve step: host-sync points inside the deferred-fence window,
  per-signature recompilation hazards, large non-donated update-step
  buffers, collective-sequence mismatch between the two ZeRO lowerings
  (the multi-host deadlock class), silent f32 upcasts in bf16
  programs, static per-device memory accounting vs an HBM/VMEM budget
  (GL-P-MEM), sharding-flow audit of the GSPMD lowering (GL-P-SHARD),
  and cross-rank program-fingerprint divergence (GL-P-DIVERGE).
  ``trainer --preflight`` drives them over the actual configured train
  AND eval steps (:mod:`preflight`).
- **Codebase passes** (:mod:`codebase`, :mod:`kernel_parity`,
  :mod:`rng`) run over the repo's own AST: thread-safety of the
  threaded subsystems (cross-thread attributes without the declared
  lock, lock-order cycles), swallow-all ``except`` blocks, the kernel
  reference-twin rule, telemetry record-kind drift vs SCHEMA, env-var
  reads without a ``core/flags`` registration, and PRNG key discipline
  (reused keys, literal-seeded draws) over the fold-in-convention
  subsystems.

Findings carry stable IDs (``RULE:path:anchor``) so the checked-in
baseline (``baseline.json``) survives line drift; the repo-wide suite
runs in tier-1 (``tests/test_analysis.py``) and must come up clean.

CLI: ``python -m paddle_tpu.analysis`` (or ``tools/lint.py``, which
adds ``--changed`` git-diff scoping).
"""

from paddle_tpu.analysis.core import (  # noqa: F401
    Finding,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    repo_root,
)
from paddle_tpu.analysis.codebase import (  # noqa: F401
    CODEBASE_PASSES,
    lock_registry,
    run_codebase,
)
from paddle_tpu.analysis.program import (  # noqa: F401
    collective_bytes_from_jaxpr,
    collective_sequence_from_hlo_text,
    collective_sequence_from_jaxpr,
    compare_collective_lowerings,
    donation_pass,
    f32_upcast_pass,
    host_sync_pass,
    recompile_hazard_pass,
)
from paddle_tpu.analysis.cost import (  # noqa: F401
    HW_PROFILES,
    HwProfile,
    cost_budget_pass,
    cost_report,
    hw_profile,
    zero_collective_bytes,
)
from paddle_tpu.analysis.memory import (  # noqa: F401
    activation_peak_bytes,
    memory_budget_pass,
    memory_report,
    opt_state_bytes_per_device,
    pallas_vmem_estimates,
    serving_budget_pass,
    serving_memory_report,
)
from paddle_tpu.analysis.sharding import (  # noqa: F401
    sharding_flow_pass,
)
from paddle_tpu.analysis.diverge import (  # noqa: F401
    divergence_pass,
    exchange_fingerprints,
    program_fingerprint,
)
from paddle_tpu.analysis.rng import (  # noqa: F401
    RNG_MODULES,
    pass_rng_discipline,
    rng_fold_pass,
)
from paddle_tpu.analysis.preflight import (  # noqa: F401
    emit_preflight_record,
    run_preflight,
    trainer_preflight,
)
