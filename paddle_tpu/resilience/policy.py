"""Bounded-retry policy with exponential backoff and deterministic jitter.

The reference retried transient cluster faults ad hoc in three places
(Go master client redial loop, pserver etcd lease re-acquire, dataset
``download`` loop in ``python/paddle/v2/dataset/common.py``); this is the
one reusable policy all of those call sites share here — dataset
downloads (:func:`paddle_tpu.dataset.common.download`), ``MasterClient``
reconnects (:mod:`paddle_tpu.distributed.master`) and checkpoint I/O
(:class:`paddle_tpu.trainer.checkpoint.AsyncCheckpointer`).

Jitter is *deterministic*: the delay sequence is a pure function of
``(seed, scope)``, so a replayed run waits the same milliseconds and a
fault-injection test can assert the exact schedule.  Each retry bumps the
``retries`` telemetry counter (labeled by scope) so recoverable flakiness
is visible, not silent.
"""

from __future__ import annotations

import random
import time
from typing import Callable

from paddle_tpu.core import logger as log


class RetryPolicy:
    """Retry ``fn`` up to ``max_attempts`` times on the listed exception
    classes, sleeping an exponentially growing, deterministically
    jittered delay between attempts.

    :param max_attempts: total attempts (1 = no retries).
    :param base_delay_s: delay before the first retry.
    :param max_delay_s: backoff ceiling (pre-jitter).
    :param multiplier: exponential growth factor.
    :param jitter: +- fraction applied to each delay (0 disables).
    :param seed: jitter seed; same (seed, scope) -> same delay sequence.
    :param retry_on: exception classes that are retried; anything else
        propagates immediately (per-exception-class filter).
    :param scope: label for logs/telemetry ("download", "master", ...).
    :param sleep: injection point for tests (default ``time.sleep``).
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.1,
                 max_delay_s: float = 30.0, multiplier: float = 2.0,
                 jitter: float = 0.25, seed: int = 0,
                 retry_on: tuple = (OSError, ConnectionError, TimeoutError),
                 scope: str = "", sleep: Callable[[float], None] | None = None,
                 registry=None):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.seed = seed
        self.retry_on = tuple(retry_on)
        self.scope = scope
        self._sleep = sleep if sleep is not None else time.sleep
        self._registry = registry

    # -- the deterministic schedule -------------------------------------------
    def delays(self) -> list[float]:
        """The exact sleep sequence a full retry cycle would use — a pure
        function of the policy's parameters, recomputed fresh per call so
        every ``call()`` waits the same schedule."""
        rnd = random.Random(f"{self.seed}/{self.scope}")
        out, d = [], self.base_delay_s
        for _ in range(self.max_attempts - 1):
            j = 1.0 + self.jitter * (2.0 * rnd.random() - 1.0)
            out.append(max(min(d, self.max_delay_s) * j, 0.0))
            d *= self.multiplier
        return out

    def should_retry(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retry_on)

    # -- execution -------------------------------------------------------------
    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying per the policy.  The final
        attempt's exception propagates unwrapped."""
        delays = self.delays()
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if attempt >= self.max_attempts - 1 or not self.should_retry(e):
                    raise
                self._count_retry()
                log.warning("%s: attempt %d/%d failed (%s: %s); retrying "
                            "in %.2fs", self.scope or "retry", attempt + 1,
                            self.max_attempts, type(e).__name__, e,
                            delays[attempt])
                self._sleep(delays[attempt])
        raise AssertionError("unreachable")

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form of :meth:`call`."""
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    def _count_retry(self) -> None:
        from paddle_tpu.telemetry import safe_inc

        safe_inc("retries", "retried transient faults",
                 registry=self._registry, scope=self.scope or "unscoped")
