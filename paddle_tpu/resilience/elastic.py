"""Elastic fleet: live resharding on host loss and scale events.

The reference survived a dying trainer because its Go master re-queued
the dead trainer's task and the pservers kept the authoritative state
(``go/master``, ``go/pserver``); the fleet shrank, the job went on.  The
TPU-native trainer has no parameter server — the mesh IS the state
holder — so losing a host means losing 1/n of every ZeRO shard and every
collective's partner.  PR 4's supervisor answers that with a full
restart-and-resume; this module answers it WITHOUT the restart: a
membership change becomes a *live mesh rebuild* at a batch boundary.

:class:`ElasticCoordinator` is the control plane.  Detection sources
post :class:`ElasticEvent`\\ s onto its queue — a stale peer heartbeat
(``multihost.Membership`` / ``HeartbeatWatchdog(on_stale=coord.on_stale)``),
a membership file rewritten by ``distributed.launch --elastic``
(:meth:`watch_membership` / :meth:`arm_signal`), or a chaos injector
(``host_loss@k`` / ``scale_up@k`` in a :class:`~paddle_tpu.resilience.
chaos.ChaosSchedule`).  ``SGD.train(elastic=coord)`` polls the queue at
every batch boundary (the drain point) and, when an event is pending:

1. **drain** — flush the deferred-fence backlog so every dispatched step
   is retired on the old mesh, and (when a checkpoint dir is armed)
   write a cursor checkpoint at the drain boundary: the anchor a fresh
   run at the new degree would resume from, which is exactly what the
   bit-identity tests compare against;
2. **re-place** — materialize params / optimizer state / layer states on
   the host.  The *live* path gathers each ZeRO shard from the surviving
   devices (host-to-host transfer in a real fleet; the replicated params
   need no transfer at all).  When the lost host's shard is
   unrecoverable the *checkpoint* path restores the newest valid cursor
   checkpoint instead and hands the trainer a replay cursor — progress
   rolls back to that boundary, but the process lives on;
3. **rebuild** — a new mesh at the new data-parallel degree
   (``parallel.mesh.resize_data_axis``), fresh ZeRO grad/state specs for
   the new degree (``parallel/zero.py`` recomputes them from the new
   mesh; :func:`~paddle_tpu.parallel.zero.respec_report` records which
   leaves changed layout), and invalidation of everything that cached
   the old mesh: the jitted train/eval steps, the compiled-signature
   set, the per-signature XLA cost analyses behind the MFU numbers, and
   the feed pipeline's placement mesh (``DevicePrefetcher.rebind_mesh``
   re-places staged feeds so no reader batch is lost or replayed);
4. **resume** — the step function re-jits lazily on the next batch.  No
   process restarts; surviving hosts never leave the train loop.

Telemetry (schema /6): one ``kind="elastic_event"`` record per rebuild —
event kind, old→new dp degree, ``recovery_ms`` (drain→resume wall time),
``shard_source`` (``live`` | ``checkpoint``) and the zero-spec change
report — plus an ``elastic_events{kind}`` counter and the shared
``recovery_ms`` gauge (``run="elastic"``), rendered by
``tools/metrics_to_md.py``'s "Elastic events" table.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable

import numpy as np

from paddle_tpu.core import logger as log


class ElasticError(RuntimeError):
    """An elastic rebuild that cannot complete (no surviving shard
    source, no checkpoint to fall back to, an unshardable mesh).  A
    retryable worker fault to the :class:`~paddle_tpu.resilience.
    supervisor.Supervisor` — budget-bounded restart is the fallback of
    the fallback."""


@dataclasses.dataclass
class ElasticEvent:
    """One membership change.

    :param kind: ``"host_loss"`` or ``"scale_up"``.
    :param new_data_parallel: target size of the mesh ``data`` axis.
        For host loss it may be omitted when ``lost_ranks`` is given
        (survivor count is derived); for scale-up it is required.
    :param lost_ranks: data-axis indices of the lost host's devices
        (host loss only).  Survivors keep their relative order, so rank
        re-numbering is the dense renumbering of the survivors.
    :param devices: explicit device list for the new mesh (overrides the
        survivor/expansion derivation — multi-host callers pass the
        membership view's device set).
    :param shard_source: ``"live"`` re-places from the surviving device
        shards; ``"checkpoint"`` forces the cursor-checkpoint fallback
        (what a real fleet does when the dead host held the only copy
        of its ZeRO shard — the chaos injector uses this to exercise
        the path deterministically).
    :param reason: free-text provenance for the telemetry record.
    """

    kind: str
    new_data_parallel: int | None = None
    lost_ranks: tuple = ()
    devices: tuple | None = None
    shard_source: str = "live"
    reason: str = ""

    def __post_init__(self):
        if self.kind not in ("host_loss", "scale_up"):
            raise ValueError(f"unknown elastic event kind {self.kind!r}")
        if self.shard_source not in ("live", "checkpoint"):
            raise ValueError(
                f"shard_source must be 'live' or 'checkpoint', got "
                f"{self.shard_source!r}")
        if self.kind == "scale_up" and self.new_data_parallel is None:
            raise ValueError("scale_up needs new_data_parallel")
        if (self.kind == "host_loss" and self.new_data_parallel is None
                and not self.lost_ranks):
            raise ValueError(
                "host_loss needs new_data_parallel or lost_ranks")


@dataclasses.dataclass
class ElasticOutcome:
    """What :meth:`ElasticCoordinator.apply` hands back to the train
    loop: the re-placed state and, on the checkpoint-fallback path, the
    cursor the loop must replay from (None = continue in place)."""

    params: dict
    opt_state: object
    states: dict
    replay_cursor: dict | None
    shard_source: str
    event: ElasticEvent


class ElasticCoordinator:
    """Queue + rebuild engine for live mesh resharding.

    Thread-safe: detection sources post from watchdog/watcher threads
    and signal handlers; the train loop consumes at batch boundaries.
    One coordinator serves one trainer for the duration of a ``train()``
    call (``SGD.train(elastic=...)`` binds it).
    """

    def __init__(self, checkpoint_dir: str | None = None, registry=None,
                 devices_per_rank: int = 1):
        self.checkpoint_dir = checkpoint_dir
        self._registry = registry
        # membership-file ranks are HOSTS; the mesh counts devices
        self.devices_per_rank = max(int(devices_per_rank), 1)
        self._events: collections.deque[ElasticEvent] = collections.deque()
        # RLock: observe_membership posts (re-acquiring) under the lock,
        # and runs from both the watcher thread and a signal handler
        self._lock = threading.RLock()
        self.epoch = 0
        self.applied: list[dict] = []  # one record per completed rebuild
        self._watcher: threading.Thread | None = None
        self._watcher_stop = threading.Event()
        self._last_membership_epoch: int | None = None
        self._last_dp: int | None = None

    # -- detection sources -----------------------------------------------------
    def post(self, event: ElasticEvent) -> None:
        with self._lock:
            self._events.append(event)
        log.warning("elastic: %s event queued (%s)", event.kind,
                    event.reason or "unattributed")

    def post_host_loss(self, new_data_parallel: int | None = None,
                       lost_ranks: tuple = (), shard_source: str = "live",
                       devices=None, reason: str = "") -> None:
        self.post(ElasticEvent(
            "host_loss", new_data_parallel=new_data_parallel,
            lost_ranks=tuple(lost_ranks), shard_source=shard_source,
            devices=tuple(devices) if devices is not None else None,
            reason=reason))

    def post_scale_up(self, new_data_parallel: int, devices=None,
                      reason: str = "") -> None:
        self.post(ElasticEvent(
            "scale_up", new_data_parallel=new_data_parallel,
            devices=tuple(devices) if devices is not None else None,
            reason=reason))

    def on_stale(self, age: float, dump_path: str | None = None,
                 lost_ranks: tuple = ()) -> None:
        """``HeartbeatWatchdog(on_stale=coord.on_stale)`` hook: a peer's
        heartbeat going stale is a host loss.  Rank attribution is
        REQUIRED (bind it with ``functools.partial(coord.on_stale,
        lost_ranks=(k,))`` per watched peer): guessing a rank would
        evict a healthy host while keeping the dead one in the mesh.
        Without attribution this only logs — an unattributed stall is
        the membership file's (or the launcher's) call to make."""
        if not lost_ranks:
            log.error(
                "elastic: heartbeat stale %.1fs but no rank attribution "
                "— not posting a host_loss (bind lost_ranks, or rely on "
                "the membership file); flight dump: %s", age, dump_path)
            return
        self.post_host_loss(
            lost_ranks=tuple(lost_ranks),
            reason=f"heartbeat stale {age:.1f}s (flight: {dump_path})")

    def watch_membership(self, path: str, poll_s: float = 0.25,
                         ) -> "ElasticCoordinator":
        """Poll a ``distributed.launch --elastic`` membership file; an
        epoch bump posts the matching event (fewer ranks → host_loss,
        more → scale_up) with ``new_data_parallel = len(ranks) *
        devices_per_rank``.  Idempotent per epoch."""
        from paddle_tpu.distributed.multihost import Membership

        def watch():
            while not self._watcher_stop.wait(poll_s):
                try:
                    m = Membership.read(path)
                except (OSError, ValueError):
                    continue  # mid-rewrite / not yet written
                self.observe_membership(m)

        if self._watcher is None:
            self._watcher = threading.Thread(
                target=watch, name="paddle-tpu-elastic-watch", daemon=True)
            self._watcher.start()
        return self

    def seed_membership(self, epoch: int, rank_count: int) -> None:
        """Anchor the baseline view to the membership this process
        JOINED under (the launcher's ``PADDLE_TPU_RENDEZVOUS_EPOCH`` /
        ``PADDLE_TPU_NPROC``).  Without a seed the first file read
        becomes the baseline — and a rank that died before that first
        read would be silently absorbed into it instead of posting the
        host_loss the survivors are waiting on."""
        with self._lock:
            self._last_membership_epoch = int(epoch)
            self._last_dp = int(rank_count) * self.devices_per_rank

    def observe_membership(self, membership) -> bool:
        """Compare a :class:`~paddle_tpu.distributed.multihost.Membership`
        view against the last one seen; post the delta event.  Returns
        True when an event was posted.  Thread-safe (the polling
        watcher and the SIGUSR1 handler race on the same file; the
        epoch check-and-set under the lock posts each epoch once)."""
        with self._lock:
            last = self._last_membership_epoch
            if last is not None and membership.epoch <= last:
                return False
            first = last is None
            prev_dp = self._last_dp if not first else None
            self._last_membership_epoch = membership.epoch
            new_dp = len(membership.ranks) * self.devices_per_rank
            self._last_dp = new_dp
            if first or prev_dp == new_dp:
                return False  # unseeded baseline, or a no-op epoch bump
            if new_dp < prev_dp:
                self.post_host_loss(
                    new_data_parallel=new_dp,
                    reason=f"membership epoch {membership.epoch}: "
                           f"ranks {membership.ranks}")
            else:
                self.post_scale_up(
                    new_data_parallel=new_dp,
                    reason=f"membership epoch {membership.epoch}: "
                           f"ranks {membership.ranks}")
            return True

    def arm_signal(self, membership_path: str, signum=None) -> None:
        """Install a signal handler (default SIGUSR1 — the notice
        ``distributed.launch --elastic`` delivers to survivors) that
        re-reads the membership file and posts the delta event."""
        import signal as _signal

        from paddle_tpu.distributed.multihost import Membership

        signum = _signal.SIGUSR1 if signum is None else signum

        def handler(sig, frame):
            try:
                self.observe_membership(Membership.read(membership_path))
            except (OSError, ValueError):
                log.warning("elastic: membership file %s unreadable on "
                            "signal %s", membership_path, sig)

        _signal.signal(signum, handler)

    def stop(self) -> None:
        self._watcher_stop.set()
        t, self._watcher = self._watcher, None
        if t is not None:
            t.join(timeout=5.0)

    # -- train-loop side -------------------------------------------------------
    def pending(self) -> bool:
        # _events is appended from watcher threads and signal handlers;
        # every access holds _lock (the GL-THREAD audited contract)
        with self._lock:
            return bool(self._events)

    def reset_pending(self) -> None:
        """Drop queued events — the supervisor calls this between restart
        attempts so a stale pre-crash event does not re-fire into the
        freshly restored run."""
        with self._lock:
            self._events.clear()

    def bind(self, trainer, checkpoint_dir: str | None) -> None:
        """Called by ``SGD.train``: adopt the run's checkpoint dir unless
        the coordinator was built with its own."""
        if self.checkpoint_dir is None:
            self.checkpoint_dir = checkpoint_dir

    def _pop(self) -> ElasticEvent | None:
        with self._lock:
            return self._events.popleft() if self._events else None

    def _registry_or_default(self):
        if self._registry is not None:
            return self._registry
        from paddle_tpu.telemetry import get_default_registry

        return get_default_registry()

    def _resolve_devices(self, event: ElasticEvent, mesh):
        """(devices tuple, new_dp) for the rebuilt mesh."""
        import jax

        current = list(mesh.devices.flat)
        if event.devices is not None:
            return tuple(event.devices), len(event.devices)
        if event.kind == "host_loss":
            if event.lost_ranks:
                lost = set(event.lost_ranks)
                survivors = [d for i, d in enumerate(current)
                             if i not in lost]
            else:
                survivors = current[:event.new_data_parallel]
            if event.new_data_parallel is not None and \
                    len(survivors) != event.new_data_parallel:
                survivors = survivors[:event.new_data_parallel]
            if not survivors:
                raise ElasticError("host loss left no surviving devices")
            return tuple(survivors), len(survivors)
        # scale_up: keep survivors' order, extend with fresh devices
        n = int(event.new_data_parallel)
        pool = current + [d for d in jax.devices() if d not in current]
        if len(pool) < n:
            raise ElasticError(
                f"scale_up to {n} needs {n} devices; only {len(pool)} "
                "are attached")
        return tuple(pool[:n]), n

    def _gather_live(self, params, opt_state, states):
        """Host copies of the full state from the live device shards —
        the single-controller spelling of the host-to-host shard
        transfer (every un-lost shard is addressable here; a multi-host
        fleet would all-gather over the survivors' DCN links first).
        Raises ElasticError when any shard is unreachable, triggering
        the checkpoint fallback."""
        import jax

        try:
            for leaf in jax.tree.leaves(opt_state):
                if not getattr(leaf, "is_fully_addressable", True):
                    raise ElasticError(
                        "optimizer-state shard not addressable from the "
                        "survivors")
            host_params = {k: np.asarray(v) for k, v in params.items()}
            host_opt = jax.tree.map(np.asarray, opt_state)
            host_states = {k: np.asarray(v) for k, v in states.items()}
        except ElasticError:
            raise
        except Exception as e:  # a dead device raises backend errors
            raise ElasticError(f"live shard gather failed: {e}") from e
        return host_params, host_opt, host_states

    def apply(self, trainer, params, opt_state, states, pass_id: int,
              batch_id: int, drain_checkpoint: Callable | None = None,
              ) -> ElasticOutcome | None:
        """Consume ONE pending event and rebuild the trainer around the
        new mesh.  Called by the train loop at a drain point (deferred
        fences already flushed).  Returns None when no event is queued.

        ``drain_checkpoint`` (trainer-provided, None when no checkpoint
        dir is armed) writes the cursor checkpoint at this exact
        boundary; it is skipped on the checkpoint-fallback path — if the
        live shards were recoverable enough to checkpoint, they were
        recoverable enough to reshard.
        """
        event = self._pop()
        if event is None:
            return None
        from paddle_tpu.distributed import multihost as mh
        from paddle_tpu.parallel import mesh as mesh_mod
        from paddle_tpu.parallel import zero as zero_mod
        from paddle_tpu.telemetry.tracing import get_tracer

        tracer = get_tracer()
        tk_elastic = tracer.begin("elastic", cat="elastic",
                                  kind=event.kind)
        t0 = time.perf_counter()
        old_mesh = trainer.mesh.mesh
        old_dp = old_mesh.shape.get("data", 1)
        for a in old_mesh.axis_names:
            if a != "data" and old_mesh.shape[a] > 1:
                raise ElasticError(
                    f"live resharding supports pure data-parallel meshes; "
                    f"axis {a!r} has size {old_mesh.shape[a]}")
        devices, new_dp = self._resolve_devices(event, old_mesh)
        log.warning("elastic: %s at pass %d batch %d — resharding "
                    "data %d -> %d (%s shards)", event.kind, pass_id,
                    batch_id, old_dp, new_dp, event.shard_source)
        mh.flight_recorder().heartbeat("elastic_rebuild", kind=event.kind,
                                       pass_id=pass_id, batch_id=batch_id)

        source = event.shard_source
        host_state = None
        if source == "live":
            try:
                with tracer.span("gather", cat="elastic"):
                    host_state = self._gather_live(params, opt_state,
                                                   states)
            except ElasticError as e:
                log.warning("elastic: live re-placement unavailable (%s); "
                            "falling back to the newest cursor "
                            "checkpoint", e)
                source = "checkpoint"
        if source == "live" and drain_checkpoint is not None:
            # persist the drain boundary BEFORE the risky rebuild: a
            # crash mid-reshard resumes here instead of losing the pass
            # (the trainer's callback opens its own "drain" span)
            drain_checkpoint(host_state[0], host_state[1], host_state[2])

        # the mesh swap: every cached-mesh consumer is invalidated here
        with tracer.span("reshard", cat="elastic", old_dp=int(old_dp),
                         new_dp=int(new_dp)):
            new_ctx = mesh_mod.resize_data_axis(trainer.mesh, new_dp,
                                                devices=devices)
            respec = zero_mod.respec_report(
                opt_state, old_mesh, new_ctx.mesh) if trainer.zero else {}
            trainer.mesh = new_ctx
            mesh_mod.set_mesh(new_ctx)
            trainer._train_step = None
            trainer._eval_step = None
            trainer._compiled_sigs.clear()
            trainer._telemetry_costs.clear()  # per-sig MFU/census costs
        with tracer.span("rebuild", cat="elastic"):
            trainer._ensure_built()

        replay_cursor = None
        if source == "live":
            host_params, host_opt, host_states = host_state
            for name, arr in host_params.items():
                if name in trainer.parameters:
                    trainer.parameters[name] = arr
            new_params = new_ctx.replicate(host_params)
            new_opt = trainer._place_opt_state(host_opt)
            new_states = new_ctx.replicate(host_states)
        else:
            from paddle_tpu.trainer import checkpoint as ckpt

            if not self.checkpoint_dir:
                raise ElasticError(
                    "live shards unrecoverable and no checkpoint_dir "
                    "armed — nothing to rebuild from")
            found = ckpt.latest_checkpoint(self.checkpoint_dir)
            if found is None:
                raise ElasticError(
                    f"live shards unrecoverable and no valid checkpoint "
                    f"under {self.checkpoint_dir}")
            new_params, new_opt, new_states = \
                trainer._restore_checkpoint_state(found, opt_state, states)
            replay_cursor = dict(found[1].get(
                "cursor", {"pass_id": found[1]["pass_id"] + 1,
                           "batch_id": 0}))

        self.epoch += 1
        recovery_ms = (time.perf_counter() - t0) * 1e3
        rec = {
            "kind": "elastic_event", "event": event.kind,
            "old_dp": int(old_dp), "new_dp": int(new_dp),
            "recovery_ms": round(recovery_ms, 2),
            "shard_source": source, "pass_id": int(pass_id),
            "batch_id": int(batch_id), "epoch": self.epoch,
            "reason": event.reason,
        }
        if replay_cursor is not None:
            rec["replay_cursor"] = replay_cursor
        if respec:
            rec["respec"] = respec
        self.applied.append(rec)
        from paddle_tpu.telemetry import swallow

        r = self._registry_or_default()
        with swallow("elastic_accounting", r):  # never blocks the rebuild
            r.counter("elastic_events",
                      "live mesh rebuilds taken").inc(1.0, kind=event.kind)
            r.gauge("recovery_ms",
                    "wall ms from fault to retraining").set(
                recovery_ms, run="elastic")
            if r.active:
                r.emit(dict(rec))
        tracer.end(tk_elastic, new_dp=int(new_dp), source=source)
        log.warning("elastic: mesh rebuilt data=%d (epoch %d) in %.1f ms; "
                    "%s", new_dp, self.epoch, recovery_ms,
                    "replaying from cursor %s" % (replay_cursor,)
                    if replay_cursor else "continuing in place")
        return ElasticOutcome(new_params, new_opt, new_states,
                              replay_cursor, source, event)
