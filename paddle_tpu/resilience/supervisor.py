"""Restart-budgeted run supervisor.

The reference's Go master re-queued a dead trainer's task and its
pserver recovered from the newest snapshot; with no parameter server,
the TPU-native equivalent is a supervisor AROUND the train loop: run the
training callable, and when it dies of a worker fault, run it again —
each attempt's ``SGD.train(resume=True)`` restores the newest VALID
checkpoint (``latest_checkpoint`` already falls back past corrupt ones)
and resumes from the manifest's exact ``(pass, batch)`` cursor, so the
retried run replays a bit-identical trajectory.

The budget is the safety valve: ``max_restarts`` faults are absorbed;
the one after that re-raises the original error (a run that cannot hold
a trajectory is a bug, not bad luck).  ``fatal`` exception classes are
never retried — user interrupts and deliberate shutdowns must win
immediately.

Telemetry (schema /3): ``restarts`` / ``faults_recovered`` counters, a
``recovery_ms`` gauge (fault-to-retraining wall time) and one
``kind="recovery"`` record per restart through the registry sinks, so
``tools/metrics_to_md.py`` can flag any run that did not fly clean.
"""

from __future__ import annotations

import time
from typing import Callable

from paddle_tpu.core import logger as log
from paddle_tpu.resilience.policy import RetryPolicy


class Supervisor:
    """Run a training callable under a restart budget.

    :param max_restarts: faults absorbed before giving up (0 = none).
    :param retry_on: exception classes that count as recoverable worker
        faults.
    :param fatal: never-retried classes (checked first; BaseExceptions
        outside ``retry_on`` — KeyboardInterrupt, SystemExit — always
        propagate).
    :param backoff: delay policy between restarts (default: short
        deterministic exponential backoff; its attempt bound is not
        used — ``max_restarts`` is the budget).
    :param run: telemetry label.

    ``run(train_fn)`` calls ``train_fn(attempt)`` (or ``train_fn()``
    when it takes no arguments) until it returns or the budget is
    spent.  ``train_fn`` must rebuild whatever the fault poisoned —
    typically: construct a fresh trainer and call ``train(...,
    checkpoint_dir=..., resume=True)``.

    Elastic integration: a live reshard that cannot complete raises
    :class:`~paddle_tpu.resilience.elastic.ElasticError` — a plain
    retryable worker fault here, so the restart budget is the fallback
    OF the elastic fallback (live shards → cursor checkpoint → full
    restart-and-resume).  Pass the run's coordinator as ``elastic=`` and
    each retry first drops its queued events: the membership change that
    killed the attempt is already reflected in the restored state, and
    replaying it into the fresh run would reshard twice.
    """

    def __init__(self, max_restarts: int = 3, retry_on: tuple = (Exception,),
                 fatal: tuple = (), backoff: RetryPolicy | None = None,
                 registry=None, run: str = "train", elastic=None):
        self.max_restarts = max(int(max_restarts), 0)
        self.retry_on = tuple(retry_on)
        self.fatal = tuple(fatal)
        self.backoff = backoff if backoff is not None else RetryPolicy(
            max_attempts=self.max_restarts + 1, base_delay_s=0.05,
            max_delay_s=5.0, scope="supervisor")
        if registry is None:
            from paddle_tpu.telemetry import get_default_registry

            registry = get_default_registry()
        self.registry = registry
        self.run_label = run
        self.elastic = elastic
        self.restarts = 0

    def _retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, self.fatal):
            return False
        return isinstance(exc, self.retry_on)

    def run(self, train_fn: Callable):
        """Execute ``train_fn`` under the restart budget; returns its
        result.  After budget exhaustion the ORIGINAL (final) error
        re-raises unwrapped."""
        import inspect

        try:
            takes_attempt = any(
                p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                           p.VAR_POSITIONAL)
                for p in inspect.signature(train_fn).parameters.values())
        except (TypeError, ValueError):
            takes_attempt = False
        delays = self.backoff.delays()
        attempt = 0
        while True:
            try:
                result = train_fn(attempt) if takes_attempt else train_fn()
            except BaseException as e:
                if not self._retryable(e) or self.restarts >= self.max_restarts:
                    if self._retryable(e):
                        log.error(
                            "supervisor: restart budget exhausted after %d "
                            "restarts; re-raising %s", self.restarts,
                            type(e).__name__)
                    raise
                self.restarts += 1
                t0 = time.perf_counter()
                delay = delays[min(self.restarts - 1, len(delays) - 1)] \
                    if delays else 0.0
                log.warning(
                    "supervisor: worker fault (%s: %s); restart %d/%d in "
                    "%.2fs", type(e).__name__, e, self.restarts,
                    self.max_restarts, delay)
                self.backoff._sleep(delay)
                # fault-to-retraining supervisor overhead; the restore
                # itself is timed by the trainer (checkpoint_restore_ms)
                recovery_ms = (time.perf_counter() - t0) * 1e3
                r = self.registry
                r.counter("restarts", "supervisor restarts taken").inc(
                    1.0, run=self.run_label)
                r.gauge("recovery_ms",
                        "wall ms from fault to retraining").set(
                    recovery_ms, run=self.run_label)
                if r.active:
                    r.emit({"kind": "recovery", "run": self.run_label,
                            "restart": self.restarts,
                            "error": f"{type(e).__name__}: {e}"[:200],
                            "recovery_ms": round(recovery_ms, 2)})
                if self.elastic is not None:
                    # the restored checkpoint already reflects the
                    # fleet the crash left behind; a queued pre-crash
                    # event re-firing would reshard a second time
                    self.elastic.reset_pending()
                attempt += 1
                continue
            if self.restarts:
                self.registry.counter(
                    "faults_recovered",
                    "worker faults absorbed by the supervisor").inc(
                    float(self.restarts), run=self.run_label)
                log.info("supervisor: run completed after %d restart(s)",
                         self.restarts)
            return result
