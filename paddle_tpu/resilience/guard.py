"""Numeric-health guard for the train loop.

The reference trapped FP faults process-wide (``feenableexcept``,
``TrainerMain.cpp:49``) — detection with no recovery: the run died.  The
guard turns a non-finite loss into a *policy*:

- ``nan_policy="skip"``: discard the poisoned update (the pre-step
  parameter/optimizer/state snapshot is restored), count it, tag the
  flight recorder and keep training.  The batch's RNG key stays
  consumed, so a later kill-and-resume replays the same trajectory.
- ``nan_policy="rollback"``: restore the newest valid checkpoint
  (parameters, optimizer slots, layer states AND the RNG stream), then
  train a rescue window of ``rescue_batches`` batches at
  ``rescue_scale``x the effective step size before returning to full
  speed.  Falls back to skip when no checkpoint exists yet.

Escalation: ``max_consecutive`` non-finite batches in a row raise
``FloatingPointError`` — a model whose every batch is NaN is dead, and
skipping forever would hide it.

The guard needs the PRE-step state to undo an update (the jitted step
donates its input buffers), so ``SGD.train`` keeps one device-side copy
of (params, opt_state, states) per batch while a policy is active, and
forces ``sync_period=1`` — the non-finite check must fence every batch
or later steps would be dispatched on poisoned parameters.  Both costs
are the price of the safety net and only paid when it's armed.

The rescue window scales the applied delta, not the optimizer's
internal ``lr`` constant: ``p' = p_prev + scale * (p_new - p_prev)``.
For every optimizer here the update delta is linear in the learning
rate while slot updates (momentum, Adam moments) are lr-independent, so
delta scaling IS learning-rate scaling — without recompiling the step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import logger as log

POLICIES = ("none", "skip", "rollback")


class NumericGuard:
    """Per-run non-finite-loss state machine driven by ``SGD.train``.

    The trainer calls, per batch: :meth:`snapshot` before the step,
    then either :meth:`handle_nonfinite` (restoring the returned state)
    or :meth:`after_finite_step` (which applies the rescue-window
    scaling and resets the consecutive-fault counter).
    """

    def __init__(self, policy: str = "skip", max_consecutive: int = 8,
                 rescue_batches: int = 8, rescue_scale: float = 0.1,
                 registry=None, flight=None, run: str = "train"):
        if policy not in ("skip", "rollback"):
            raise ValueError(
                f"nan_policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.max_consecutive = max(int(max_consecutive), 1)
        self.rescue_batches = max(int(rescue_batches), 0)
        self.rescue_scale = float(rescue_scale)
        self.run = run
        self._flight = flight
        if registry is None:
            from paddle_tpu.telemetry import get_default_registry

            registry = get_default_registry()
        self.registry = registry
        self._consecutive = 0
        self._rescue_left = 0
        self._blend = jax.jit(
            lambda old, new, s: jax.tree.map(
                lambda o, n: o + s * (n - o), old, new))

    # -- trainer hooks ---------------------------------------------------------
    def snapshot(self, params, opt_state, states):
        """Device-side copies of the step inputs, taken BEFORE dispatch —
        the donating step deletes the originals, so these copies are the
        only way back."""
        copy = lambda t: jax.tree.map(jnp.copy, t)  # noqa: E731
        return copy(params), copy(opt_state), copy(states)

    def handle_nonfinite(self, cost: float, pass_id: int, batch_id: int,
                         prev, restore_fn=None):
        """Apply the policy to one non-finite batch.  ``prev`` is the
        :meth:`snapshot` tuple; ``restore_fn`` (rollback only) loads the
        newest valid checkpoint and returns (params, opt_state, states)
        or None.  Returns the state tuple to continue training from."""
        self._consecutive += 1
        if self._consecutive >= self.max_consecutive:
            raise FloatingPointError(
                f"non-finite cost {cost} for {self._consecutive} "
                f"consecutive batches (pass {pass_id} batch {batch_id}) — "
                f"nan_policy={self.policy!r} gave up")
        restored = None
        action = self.policy
        if self.policy == "rollback" and restore_fn is not None:
            restored = restore_fn()
        if restored is None:
            # no checkpoint yet (or skip policy): undo just this update
            if action == "rollback":
                log.warning("nan_policy=rollback: no valid checkpoint to "
                            "restore; skipping the batch instead")
                action = "skip"
            restored = prev
        if action == "rollback" and self.rescue_batches:
            self._rescue_left = self.rescue_batches
        self._count(action, cost, pass_id, batch_id)
        return restored

    def after_finite_step(self, prev_params, new_params):
        """Called after every finite batch: applies the rescue-window
        step-size reduction (while active) and resets the consecutive-
        fault counter.  Returns the params to carry forward."""
        self._consecutive = 0
        if self._rescue_left <= 0:
            return new_params
        self._rescue_left -= 1
        return self._blend(prev_params, new_params,
                           jnp.float32(self.rescue_scale))

    @property
    def in_rescue(self) -> bool:
        return self._rescue_left > 0

    # -- accounting ------------------------------------------------------------
    def _count(self, action: str, cost: float, pass_id: int,
               batch_id: int) -> None:
        r = self.registry
        if action == "skip":
            r.counter("batches_skipped",
                      "non-finite batches skipped by the guard").inc(
                1.0, run=self.run)
        else:
            r.counter("rollbacks",
                      "checkpoint rollbacks taken by the guard").inc(
                1.0, run=self.run)
        log.warning("numeric guard: non-finite cost %s at pass %d batch %d "
                    "-> %s", cost, pass_id, batch_id, action)
        if r.active:
            r.emit({"kind": "fault", "run": self.run,
                    "fault": f"nan_{action}", "pass_id": pass_id,
                    "batch_id": batch_id, "loss": float(cost)})
        flight = self._flight
        if flight is None:
            try:
                from paddle_tpu.distributed import multihost as mh

                flight = mh.flight_recorder()
            except Exception as e:
                log.debug("flight recorder unavailable for the guard "
                          "heartbeat (%s)", e)
                flight = None
        if flight is not None:
            flight.heartbeat(f"nan_{action}", pass_id=pass_id,
                             batch_id=batch_id)
