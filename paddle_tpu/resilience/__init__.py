"""Fault tolerance — the recovery *logic* the reference kept in its Go
master/pserver (etcd leases, task re-queue on trainer death, periodic
snapshot-and-recover, ``go/pserver/service.go`` / ``go/master``), rebuilt
for the TPU-native trainer where the trainer process itself is the state
holder:

- :mod:`policy` — :class:`RetryPolicy`: bounded attempts, exponential
  backoff with deterministic jitter, per-exception-class filters.  Shared
  by dataset downloads, ``MasterClient`` reconnects and checkpoint I/O.
- :mod:`guard` — :class:`NumericGuard`: non-finite loss handling inside
  ``SGD.train`` (skip the poisoned batch, or roll back to the last
  checkpoint with a reduced-LR rescue window).
- :mod:`supervisor` — :class:`Supervisor`: restart-budgeted wrapper
  around a train callable; restores the newest valid checkpoint (falling
  back past corrupt ones) and resumes mid-pass bit-identically.
- :mod:`chaos` — deterministic fault injectors (raise-at-step-k,
  NaN-at-step-k, simulated SIGTERM, corrupt-checkpoint writer, and the
  host-loss/scale-up elastic events) driven by a seeded schedule, so
  every recovery path is exercised in tests rather than hoped about.
- :mod:`elastic` — :class:`ElasticCoordinator`: live mesh resharding on
  membership change (host loss / scale-up) at a train-loop drain point;
  re-places params/opt-state from the surviving ZeRO shards, falling
  back to the newest cursor checkpoint — no process restart.
"""

from paddle_tpu.resilience.chaos import (  # noqa: F401
    ChaosError,
    ChaosSchedule,
    corrupt_newest_checkpoint,
    corrupt_servable,
    flaky,
    nan_poison_batch,
)
from paddle_tpu.resilience.elastic import (  # noqa: F401
    ElasticCoordinator,
    ElasticError,
    ElasticEvent,
)
from paddle_tpu.resilience.guard import NumericGuard  # noqa: F401
from paddle_tpu.resilience.policy import RetryPolicy  # noqa: F401
from paddle_tpu.resilience.supervisor import Supervisor  # noqa: F401
