"""Deterministic fault injection — chaos you can replay.

The reference validated its fault tolerance by actually killing trainers
and pservers in cluster tests; a unit suite needs the same coverage
without the cluster, so every injector here is a pure function of a
seeded schedule: the same spec + seed faults the same batch of the same
pass every run, which is what lets ``tests/test_resilience.py`` assert
*bit-identical* recovery trajectories.

A :class:`ChaosSchedule` is parsed from a spec string (the trainer CLI's
``--chaos`` flag uses the same syntax)::

    reader_error@3          raise ChaosError pulling batch 3
    nan@5                   poison every float of batch 5 with NaN
    step_error@4            raise ChaosError at BeginIteration 4
    step_error@4:always     ... on every restart, not just the first
    sigterm@7               deliver SIGTERM to this process at step 7
    host_loss@5:dp=4        post a host-loss elastic event at step 5
                            (mesh reshards to data=4 at the boundary)
    host_loss@5:dp=4:source=checkpoint
                            ... with the live shards declared
                            unrecoverable (checkpoint-fallback path)
    scale_up@8:dp=8         post a scale-up elastic event at step 8
    replica_loss@5:replica=1
                            kill serving replica 1 at fleet pump round 5
    replica_hang@5:replica=0
                            wedge replica 0 (alive but making no progress)
    servable_corrupt@1      corrupt the servable artifact before the
                            rolling weight swap's 2nd per-replica load

The elastic kinds need a coordinator: call :meth:`ChaosSchedule.
bind_elastic` with the run's ``ElasticCoordinator`` before training.
The serving-fleet kinds need a router: pass the schedule as
``FleetRouter(chaos=...)`` — the router polls
:meth:`take_fleet_fault` at its own pump-round / swap-load counters
(``serving/router.py``), so a serving chaos trace is as replayable as a
training one.

Batch/step indices are 0-based and cumulative over the schedule object's
lifetime (they keep counting across passes), so a fault lands at one
globally unique point.  Faults fire ONCE by default — a supervisor
restart replays past the fault cleanly — unless marked ``:always``
(restart-budget-exhaustion testing).  Every fired fault bumps the
``faults_injected`` telemetry counter (labeled by kind) and tags the
flight recorder, so an injected fault is distinguishable from a real one
in the post-mortem.
"""

from __future__ import annotations

import os
import signal as _signal

import numpy as np

from paddle_tpu.core import logger as log


class ChaosError(RuntimeError):
    """The injected worker fault (distinguishable from real errors)."""


class _Fault:
    __slots__ = ("kind", "step", "always", "fired", "params")

    def __init__(self, kind: str, step: int, always: bool = False,
                 params: dict | None = None):
        self.kind = kind
        self.step = step
        self.always = always
        self.fired = False
        self.params = params or {}


def nan_poison_batch(batch):
    """Replace every float array/scalar of a batch's samples with NaN —
    the poisoned feed yields a non-finite cost through the real forward
    pass, exercising the NumericGuard path end to end."""
    def poison_value(v):
        if isinstance(v, np.ndarray) and np.issubdtype(v.dtype, np.floating):
            return np.full_like(v, np.nan)
        if isinstance(v, float):
            return float("nan")
        return v

    out = []
    for sample in batch:
        if isinstance(sample, (tuple, list)):
            out.append(type(sample)(poison_value(v) for v in sample))
        else:
            out.append(poison_value(sample))
    return out


class ChaosSchedule:
    """Parsed fault schedule + the wrappers that arm it.

    ``wrap_reader`` arms ``reader_error``/``nan`` faults on the batch
    stream; ``wrap_event_handler`` arms ``step_error``/``sigterm`` on the
    event stream (``BeginIteration`` marks the step about to run).  One
    schedule object carries its fired-state across supervisor restarts —
    reuse the SAME instance for every attempt so once-faults stay once.
    """

    KINDS = ("reader_error", "nan", "step_error", "sigterm",
             "host_loss", "scale_up",
             "replica_loss", "replica_hang", "servable_corrupt")

    def __init__(self, spec: str = "", seed: int = 0, registry=None,
                 flight=None):
        self.seed = seed
        self._registry = registry
        self._flight = flight
        self._elastic = None  # ElasticCoordinator, via bind_elastic
        self._batches = 0   # batches pulled through wrap_reader, ever
        self._steps = 0     # BeginIteration events seen, ever
        self.faults: list[_Fault] = []
        for part in (p.strip() for p in spec.split(",") if p.strip()):
            kind, _, at = part.partition("@")
            if kind not in self.KINDS:
                raise ValueError(
                    f"unknown chaos fault {kind!r} (expected one of "
                    f"{self.KINDS})")
            # "5", "5:always", "5:dp=4:source=checkpoint", ...
            at, *extras = at.split(":")
            always, params = False, {}
            for ex in extras:
                if ex == "always":
                    always = True
                elif ex.startswith("dp="):
                    params["dp"] = int(ex[len("dp="):])
                elif ex.startswith("replica="):
                    params["replica"] = int(ex[len("replica="):])
                elif ex.startswith("source="):
                    src = ex[len("source="):]
                    if src not in ("live", "checkpoint"):
                        raise ValueError(
                            f"chaos {kind}: source must be live|"
                            f"checkpoint, got {src!r}")
                    params["source"] = src
                else:
                    raise ValueError(
                        f"unknown chaos fault option {ex!r} in {part!r}")
            if kind in ("host_loss", "scale_up") and "dp" not in params:
                raise ValueError(
                    f"chaos {kind} needs a :dp=<degree> target "
                    f"(got {part!r})")
            self.faults.append(_Fault(kind, int(at), always, params))

    def bind_elastic(self, coordinator) -> "ChaosSchedule":
        """Give host_loss/scale_up faults their target: the run's
        :class:`~paddle_tpu.resilience.elastic.ElasticCoordinator`."""
        self._elastic = coordinator
        return self

    def take_fleet_fault(self, kind: str, index: int) -> dict | None:
        """Serving-fleet injection point (``FleetRouter`` polls this):
        if a ``replica_loss``/``replica_hang``/``servable_corrupt``
        fault is due at ``index`` (the router's own pump-round or
        swap-load counter), fire it and return its params (e.g.
        ``{"replica": 1}``); else None.  The router applies the effect —
        the schedule only decides WHEN, so the same spec replays the
        same fault at the same deterministic point."""
        f = self._due(kind, index)
        if f is None:
            return None
        self._fire(f, f"fleet {kind} @{index}")
        return dict(f.params)

    def reset_counters(self) -> None:
        """Re-base the batch/step indexes to 0 for a new supervisor
        attempt WITHOUT clearing fired-state: once-faults stay fired
        (replay passes them cleanly), while ``:always`` faults re-fire
        at the same per-attempt position — call this at the top of each
        attempt when testing restart-budget exhaustion."""
        self._batches = 0
        self._steps = 0

    # -- internals -------------------------------------------------------------
    def _due(self, kind: str, index: int) -> _Fault | None:
        for f in self.faults:
            if f.kind == kind and f.step == index and (f.always or
                                                       not f.fired):
                return f
        return None

    def _fire(self, fault: _Fault, where: str) -> None:
        fault.fired = True
        log.warning("chaos: injecting %s at %s", fault.kind, where)
        from paddle_tpu.telemetry import safe_inc, swallow

        safe_inc("faults_injected", "chaos faults fired",
                 registry=self._registry, kind=fault.kind)
        with swallow("chaos_heartbeat"):  # never blocks the injection
            flight = self._flight
            if flight is None:
                from paddle_tpu.distributed import multihost as mh

                flight = mh.flight_recorder()
            flight.heartbeat(f"chaos:{fault.kind}", **{"at": where})

    # -- wrappers --------------------------------------------------------------
    def wrap_reader(self, reader):
        """Arm reader_error/nan faults on a batch reader (the
        ``paddle.batch(...)`` output ``SGD.train`` consumes)."""
        def wrapped():
            for batch in reader():
                i = self._batches
                self._batches += 1
                f = self._due("reader_error", i)
                if f is not None:
                    self._fire(f, f"reader batch {i}")
                    raise ChaosError(f"injected reader fault at batch {i}")
                f = self._due("nan", i)
                if f is not None:
                    self._fire(f, f"reader batch {i}")
                    batch = nan_poison_batch(batch)
                yield batch

        return wrapped

    def wrap_event_handler(self, handler=None):
        """Arm step_error/sigterm faults on the trainer event stream."""
        from paddle_tpu.trainer import event as v2_event

        def wrapped(e):
            if isinstance(e, v2_event.BeginIteration):
                i = self._steps
                self._steps += 1
                f = self._due("sigterm", i)
                if f is not None:
                    self._fire(f, f"step {i}")
                    os.kill(os.getpid(), _signal.SIGTERM)
                for kind in ("host_loss", "scale_up"):
                    f = self._due(kind, i)
                    if f is None:
                        continue
                    if self._elastic is None:
                        raise ValueError(
                            f"chaos {kind} fault armed but no "
                            "ElasticCoordinator bound — call "
                            "schedule.bind_elastic(coordinator)")
                    self._fire(f, f"step {i}")
                    # posted here, consumed by the trainer at the NEXT
                    # batch boundary (after this step completes) — the
                    # drain point elastic resharding is defined at
                    if kind == "host_loss":
                        self._elastic.post_host_loss(
                            new_data_parallel=f.params["dp"],
                            shard_source=f.params.get("source", "live"),
                            reason=f"chaos host_loss@{i}")
                    else:
                        self._elastic.post_scale_up(
                            new_data_parallel=f.params["dp"],
                            reason=f"chaos scale_up@{i}")
                f = self._due("step_error", i)
                if f is not None:
                    self._fire(f, f"step {i}")
                    raise ChaosError(f"injected worker fault at step {i}")
            if handler is not None:
                handler(e)

        return wrapped


def corrupt_newest_checkpoint(ckpt_dir: str, seed: int = 0,
                              registry=None) -> str:
    """Append seeded garbage to the newest checkpoint's payload so its
    manifest sha256 no longer matches — the corrupt-checkpoint writer
    recovery tests use to prove ``latest_checkpoint`` falls back past it.
    Returns the corrupted payload path."""
    from paddle_tpu.trainer import checkpoint as ckpt

    entries = ckpt.checkpoint_entries(ckpt_dir)
    if not entries:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    newest = entries[-1]
    target = os.path.join(newest, "params.npz")
    rnd = np.random.default_rng(seed)
    with open(target, "ab") as f:
        f.write(rnd.integers(0, 256, size=64, dtype=np.uint8).tobytes())
    log.warning("chaos: corrupted checkpoint payload %s", target)
    from paddle_tpu.telemetry import safe_inc

    safe_inc("faults_injected", "chaos faults fired", registry=registry,
             kind="corrupt_ckpt")
    return target


def corrupt_servable(path: str, seed: int = 0) -> str:
    """Append seeded garbage to a servable's payload so its manifest
    sha256 no longer matches — ``load_servable`` must then refuse it,
    which is what proves the rolling weight swap's verify-then-swap
    order and its rollback path.  The ``servable_corrupt`` schedule
    entry that triggered this already counted the fault
    (``take_fleet_fault``), so this helper does not count again.
    Returns the corrupted payload path."""
    target = os.path.join(path, "params.npz")
    if not os.path.exists(target):
        raise FileNotFoundError(f"no servable payload at {target}")
    rnd = np.random.default_rng(seed)
    with open(target, "ab") as f:
        f.write(rnd.integers(0, 256, size=64, dtype=np.uint8).tobytes())
    log.warning("chaos: corrupted servable payload %s", target)
    return target


def flaky(fn, fail_times: int = 2, exc=ConnectionError):
    """A callable that raises ``exc`` for its first ``fail_times`` calls,
    then delegates to ``fn`` — the canonical transient fault for
    RetryPolicy tests and flaky-download simulation."""
    state = {"n": 0}

    def wrapped(*args, **kwargs):
        if state["n"] < fail_times:
            state["n"] += 1
            raise exc(f"injected transient fault {state['n']}/{fail_times}")
        return fn(*args, **kwargs)

    return wrapped
