"""Pipeline parallelism over the ``pipe`` mesh axis.

The reference's closest capability is per-layer device placement
(``ParallelNeuralNetwork.h:34-105``: layers pinned to deviceId, one worker
thread per device) — a capability this upgrades to a real GPipe schedule:
identical-shaped stages (e.g. transformer blocks) hold their stage's
parameters (stacked pytree leading axis sharded over ``pipe``), microbatches
stream into stage 0, activations hand off stage-to-stage via
``lax.ppermute`` (ICI collective-permute), and autodiff reverses the
schedule for the backward pass.  Bubble fraction = (S-1)/(M+S-1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu import compat
from paddle_tpu.compat import shard_map
from paddle_tpu.parallel import collective


def _stage_loop(stage_fn, n_micro: int, axis_name: str, params, x_mb):
    """Runs inside shard_map: params is this stage's slice (leading dim 1);
    x_mb is [n_micro, mb, ...] microbatches (replicated)."""
    stage = lax.axis_index(axis_name)
    n_stages = compat.axis_size(axis_name)
    params = jax.tree.map(lambda p: p[0], params)
    total = n_micro + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]  # forward handoff chain

    mb_shape = jax.tree.map(lambda a: a[0], x_mb)
    state = jax.tree.map(jnp.zeros_like, mb_shape)  # activation in flight
    outs = jax.tree.map(
        lambda a: jnp.zeros_like(a), x_mb
    )  # collected at the last stage

    def step(t, carry):
        state, outs = carry
        # stage 0 ingests microbatch t (or zeros once drained)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        fresh = jax.tree.map(lambda a: a[mb_idx], x_mb)
        ingest = (stage == 0) & (t < n_micro)
        cur = jax.tree.map(
            lambda f, s: jnp.where(ingest, f, s), fresh, state
        )
        y = stage_fn(params, cur)
        # last stage commits finished microbatch t-(S-1)
        out_idx = t - (n_stages - 1)
        commit = (stage == n_stages - 1) & (out_idx >= 0)
        outs = jax.tree.map(
            lambda o, yy: o.at[jnp.clip(out_idx, 0, n_micro - 1)].set(
                jnp.where(commit, yy, o[jnp.clip(out_idx, 0, n_micro - 1)])
            ),
            outs, y,
        )
        # stage handoff via the observability-wrapped collective (trace
        # annotation + per-step comm-bytes accounting)
        state = jax.tree.map(
            lambda a: collective.permute(a, axis_name, perm), y
        )
        return state, outs

    _, outs = lax.fori_loop(0, total, step, (state, outs))
    # only the last stage holds real outputs; share them ring-wide
    outs = jax.tree.map(
        lambda o: lax.psum(
            jnp.where(stage == n_stages - 1, o, jnp.zeros_like(o)), axis_name
        ),
        outs,
    )
    return outs


def pipeline_apply(
    stage_fn,
    stacked_params,
    x,
    n_microbatches: int,
    mesh,
    axis_name: str = "pipe",
):
    """Apply ``n_stages`` sequential stages (same shape in/out) to ``x``.

    stacked_params: pytree with leading dim = n_stages (sharded over
    ``axis_name``); x: [B, ...] batch, split into ``n_microbatches``.
    Returns stage_{S-1}(...stage_0(x)) exactly (GPipe semantics).
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])

    p_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = shard_map(
        functools.partial(_stage_loop, stage_fn, n_microbatches, axis_name),
        mesh=mesh,
        in_specs=(p_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    outs = fn(stacked_params, x_mb)
    return outs.reshape((b,) + outs.shape[2:])
