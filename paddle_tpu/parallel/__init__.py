"""Parallelism over the device mesh — the replacement for the reference's
entire distribution stack: ``MultiGradientMachine`` (intra-node DP threads +
software ring all-reduce, ``MultiGradientMachine.h:44-98``), the C++ pserver
(``paddle/pserver``), the Go cloud runtime (``go/pserver``, ``go/master``),
and Fluid's NCCL ops (``operators/nccl_op.cc:66``).

On TPU all of it becomes shardings on a ``jax.sharding.Mesh``: batch-sharded
inputs give data parallelism with XLA-inserted ICI all-reduce; weight-sharded
params give tensor parallelism; ``shard_map`` + ``ppermute`` give pipeline and
ring-attention sequence parallelism.  See ``paddle_tpu.parallel.collectives``
for the op-level surface matching ``NCCLAllReduce``/``Reduce``/``Bcast``."""

from paddle_tpu.parallel.mesh import MeshContext, get_mesh, make_mesh  # noqa: F401
