"""Embedding parallelism — tables sharded over a mesh axis, the TPU-native
replacement for the reference's sparse parameter-server path (dedicated
sparse pserver ports + ``SparseRemoteParameterUpdater`` + row prefetch,
``RemoteParameterUpdater.h``, ``SparseRowMatrix.h:204``): instead of
prefetching touched rows from a remote host, rows live sharded across the
mesh and the gather's collective runs over ICI (SURVEY §2.3 row 4).

Two ways to get the same layout, both wrapped by :class:`ShardedEmbedding`:

1. Declarative (``path="gspmd"``, preferred): give the embedding parameter
   ``sharding=("model", None)`` and let pjit place it — XLA inserts the
   all-gather/psum around the gather automatically.
2. Explicit (``path="shard_map"``): routines that make the communication
   pattern visible and testable — each shard gathers its local rows and the
   partial one-hot results psum over the axis.  GL-P-COLL's dual-lowering
   compare holds the two paths to the same collective sequence.

Vocab sizes that don't divide the axis are row-padded
(:func:`pad_vocab`); ids outside the *logical* vocab clamp-and-zero —
they never read the pad rows, and they contribute no gradient."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce


def pad_vocab(vocab: int, k: int) -> int:
    """Smallest multiple of ``k`` >= ``vocab`` — the padded row count a
    [V, D] table needs to row-shard ``k`` ways."""
    return -(-int(vocab) // int(k)) * int(k)


def shard_table(table: jax.Array, mesh, axis: str = "model") -> jax.Array:
    """Place a [V, D] table row-sharded over ``axis``."""
    enforce(table.shape[0] % mesh.shape[axis] == 0,
            f"table rows {table.shape[0]} not divisible by mesh axis "
            f"{axis}={mesh.shape[axis]}")
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def _valid_ids(ids: jax.Array, vocab: int | None):
    """int32 ids + the in-logical-vocab mask (None when no clamp asked)."""
    ids = ids.astype(jnp.int32)
    if vocab is None:
        return ids, None
    return ids, (ids >= 0) & (ids < vocab)


def sharded_lookup(table: jax.Array, ids: jax.Array, mesh,
                   axis: str = "model", vocab: int | None = None) -> jax.Array:
    """Gather from a row-sharded table: every device looks up the ids that
    fall in its shard, others contribute zeros, psum combines.  ids are
    replicated over ``axis`` (they're usually data-sharded on 'data').
    Returns [..., D] with the same sharding as ids.

    ``vocab`` is the *logical* row count when the table carries pad rows
    (``pad_vocab``): ids outside ``[0, vocab)`` clamp-and-zero instead of
    reading a pad row.  Duplicate ids transpose to exact scatter-add
    gradients (each shard accumulates its own rows' cotangents locally —
    the 'sparse update stays on the shard' behavior the reference got
    from dedicated sparse pservers)."""
    k = mesh.shape[axis]
    v = table.shape[0]
    enforce(v % k == 0, "table rows must divide the mesh axis")
    rows_per = v // k
    ids, ok = _valid_ids(ids, vocab)

    def body(tbl_shard, ids_local, ok_local):
        idx = lax.axis_index(axis)
        offset = idx * rows_per
        local = ids_local - offset
        in_shard = (local >= 0) & (local < rows_per)
        if ok_local is not None:
            in_shard = in_shard & ok_local
        safe = jnp.clip(local, 0, rows_per - 1)
        got = jnp.take(tbl_shard, safe, axis=0)
        got = jnp.where(in_shard[..., None], got, 0.0)
        return lax.psum(got, axis)

    fn = shard_map(body, mesh=mesh, in_specs=(P(axis, None), P(), P()),
                   out_specs=P(), check_vma=False)
    return fn(table, ids, ok)


def replicated_lookup_sharded_grad(table: jax.Array, ids: jax.Array,
                                   mesh, axis: str = "model",
                                   vocab: int | None = None) -> jax.Array:
    """Convenience jit-level alternative: constrain the table's sharding and
    let XLA pick the collective (path 1 in the module docstring).  Same
    clamp-and-zero contract as :func:`sharded_lookup`."""
    t = jax.lax.with_sharding_constraint(
        table, NamedSharding(mesh, P(axis, None)))
    ids, ok = _valid_ids(ids, vocab)
    got = jnp.take(t, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)
    if ok is not None:
        got = jnp.where(ok[..., None], got, 0.0)
    return got


class ShardedEmbedding:
    """A production row-sharded embedding table over one mesh axis.

    Owns the layout math (vocab padding, per-shard row count, per-device
    bytes) and dispatches lookups through either lowering path.  The
    table itself stays a plain array in the caller's param tree — this
    node is the layout + lookup contract, not a parameter store, so it
    composes with ``parameters``/checkpointing/ZeRO untouched.

    >>> emb = ShardedEmbedding(vocab=10, dim=4, mesh=mesh, axis="model")
    >>> table = emb.place(dense_table)       # [10,4] -> padded [12,4], sharded
    >>> out = emb.lookup(table, ids)         # ids outside [0,10) -> zeros
    """

    def __init__(self, vocab: int, dim: int, mesh, axis: str = "model",
                 dtype=jnp.float32, path: str = "gspmd"):
        enforce(axis in mesh.shape,
                f"mesh has no axis {axis!r} (axes: {tuple(mesh.shape)})")
        enforce(path in ("gspmd", "shard_map"),
                f"path must be 'gspmd' or 'shard_map', got {path!r}")
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.mesh = mesh
        self.axis = axis
        self.dtype = jnp.dtype(dtype)
        self.path = path
        self.shards = int(mesh.shape[axis])
        self.padded_vocab = pad_vocab(self.vocab, self.shards)

    @property
    def rows_per_shard(self) -> int:
        return self.padded_vocab // self.shards

    def total_bytes(self) -> int:
        return self.padded_vocab * self.dim * self.dtype.itemsize

    def per_device_bytes(self) -> int:
        return self.rows_per_shard * self.dim * self.dtype.itemsize

    def init(self, key, scale: float = 0.01) -> jax.Array:
        """Fresh N(0, scale) table, pad rows zeroed, placed on the mesh."""
        dense = scale * jax.random.normal(
            key, (self.vocab, self.dim), dtype=self.dtype)
        return self.place(dense)

    def place(self, dense: jax.Array) -> jax.Array:
        """Pad a dense [vocab, dim] table to the sharded row count and
        place it P(axis, None).  Pad rows are zero."""
        enforce(dense.shape == (self.vocab, self.dim),
                f"expected [{self.vocab}, {self.dim}], got {dense.shape}")
        pad = self.padded_vocab - self.vocab
        if pad:
            dense = jnp.pad(dense, ((0, pad), (0, 0)))
        return shard_table(dense.astype(self.dtype), self.mesh, self.axis)

    def lookup(self, table: jax.Array, ids: jax.Array,
               path: str | None = None) -> jax.Array:
        """[..., dim] rows for ``ids``; out-of-vocab ids clamp-and-zero."""
        enforce(table.shape == (self.padded_vocab, self.dim),
                f"expected placed table [{self.padded_vocab}, {self.dim}], "
                f"got {table.shape}")
        path = self.path if path is None else path
        if path == "shard_map":
            return sharded_lookup(table, ids, self.mesh, self.axis,
                                  vocab=self.vocab)
        return replicated_lookup_sharded_grad(table, ids, self.mesh,
                                              self.axis, vocab=self.vocab)
