"""Embedding parallelism — tables sharded over a mesh axis, the TPU-native
replacement for the reference's sparse parameter-server path (dedicated
sparse pserver ports + ``SparseRemoteParameterUpdater`` + row prefetch,
``RemoteParameterUpdater.h``, ``SparseRowMatrix.h:204``): instead of
prefetching touched rows from a remote host, rows live sharded across the
mesh and the gather's collective runs over ICI (SURVEY §2.3 row 4).

Two ways to get the same layout:

1. Declarative (preferred): give the embedding parameter
   ``sharding=("model", None)`` and let pjit place it — XLA inserts the
   all-gather/psum around the gather automatically.
2. Explicit (this module): shard_map routines that make the communication
   pattern visible and testable — each shard gathers its local rows and the
   partial one-hot results psum over the axis."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce


def shard_table(table: jax.Array, mesh, axis: str = "model") -> jax.Array:
    """Place a [V, D] table row-sharded over ``axis``."""
    enforce(table.shape[0] % mesh.shape[axis] == 0,
            f"table rows {table.shape[0]} not divisible by mesh axis "
            f"{axis}={mesh.shape[axis]}")
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def sharded_lookup(table: jax.Array, ids: jax.Array, mesh,
                   axis: str = "model") -> jax.Array:
    """Gather from a row-sharded table: every device looks up the ids that
    fall in its shard, others contribute zeros, psum combines.  ids are
    replicated over ``axis`` (they're usually data-sharded on 'data').
    Returns [..., D] with the same sharding as ids.

    The backward pass (via shard_map transpose) scatter-adds each shard's
    cotangent rows locally — exactly the 'sparse update stays on the shard'
    behavior the reference got from dedicated sparse pservers."""
    k = mesh.shape[axis]
    v = table.shape[0]
    enforce(v % k == 0, "table rows must divide the mesh axis")
    rows_per = v // k

    def body(tbl_shard, ids_local):
        idx = lax.axis_index(axis)
        offset = idx * rows_per
        local = ids_local.astype(jnp.int32) - offset
        in_shard = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        got = jnp.take(tbl_shard, safe, axis=0)
        got = jnp.where(in_shard[..., None], got, 0.0)
        return lax.psum(got, axis)

    fn = shard_map(body, mesh=mesh, in_specs=(P(axis, None), P()),
                   out_specs=P(), check_vma=False)
    return fn(table, ids)


def replicated_lookup_sharded_grad(table: jax.Array, ids: jax.Array,
                                   mesh, axis: str = "model") -> jax.Array:
    """Convenience jit-level alternative: constrain the table's sharding and
    let XLA pick the collective (path 1 in the module docstring)."""
    t = jax.lax.with_sharding_constraint(
        table, NamedSharding(mesh, P(axis, None)))
    return jnp.take(t, ids.astype(jnp.int32), axis=0)
