"""Device mesh management — the TPU-native successor of trainer_count/
num_gradient_servers topology flags (``paddle/utils/Flags.h``) and the
pserver shard map (``ParameterServer2`` block hashing).

Axes convention (the scaling-book recipe):
- ``data``  — batch sharding (DP); gradients all-reduce over ICI here.
- ``model`` — weight sharding (TP); activations all-gather/reduce-scatter.
- ``pipe``  — pipeline stages (PP); collective-permute between stages.
- ``seq``   — sequence/context parallelism (ring attention / Ulysses).

A 1-axis all-``data`` mesh reproduces the reference's pure data-parallel
training; the other axes are capability upgrades the reference lacked."""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import flags
from paddle_tpu.core.enforce import enforce

AXES = ("data", "model", "pipe", "seq")


def make_mesh(
    shape: dict[str, int] | None = None, devices=None
) -> Mesh:
    """Build a mesh; default = all devices on the ``data`` axis.

    shape e.g. {"data": 4, "model": 2}.  Axis order follows AXES so that the
    innermost (fastest-varying, best-ICI-locality) axis is the model axis.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if not shape:
        cfg = flags.get("mesh_shape")
        if cfg:
            dims = [int(x) for x in cfg.split(",")]
            names = AXES[: len(dims)]
            shape = dict(zip(names, dims))
        else:
            shape = {"data": n}
    used = int(np.prod(list(shape.values())))
    enforce(used <= n, f"mesh {shape} needs {used} devices, have {n}")
    names = [a for a in AXES if a in shape] + [a for a in shape if a not in AXES]
    dims = [shape[a] for a in names]
    dev_array = np.asarray(devices[:used]).reshape(dims)
    return Mesh(dev_array, tuple(names))


_current: "MeshContext | None" = None


@dataclasses.dataclass
class MeshContext:
    """Holds the mesh + canonical shardings used by the train step."""

    mesh: Mesh

    @property
    def num_replicas(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names
                            if a == "data"])) or 1

    def data_sharding(self, ndim: int) -> NamedSharding:
        """Batch dim sharded over 'data' (and 'seq' handled separately)."""
        spec = P("data", *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def param_sharding(self, spec_axes: tuple | None, ndim: int) -> NamedSharding:
        """Parameter sharding from a ParamSpec.sharding tuple (model axes),
        default replicated — pure DP keeps whole weights everywhere like
        MultiGradientMachine's per-thread full copies."""
        if spec_axes is None:
            return self.replicated()
        # known axes absent from this mesh degrade to replicated (a
        # TP-annotated model still runs on a pure-DP mesh); unknown names are
        # errors, not silent replication
        present = set(self.mesh.axis_names)
        for a in spec_axes:
            enforce(
                a is None or a in present or a in AXES,
                f"unknown mesh axis {a!r} in param sharding {spec_axes}",
            )
        axes = [a if a in present else None for a in spec_axes]
        return NamedSharding(self.mesh, P(*axes))

    def shard_batch(self, tree, remainder: str = "error"):
        """Place a feed pytree with batch-dim sharding (device_put is async).

        ``remainder`` is the partial-batch policy: "error" (default)
        keeps the strict divisibility check below; "drop"/"pad" first run
        :func:`apply_remainder` so the last partial batch of a pass can't
        kill a multi-device run (opt-in — see that function's caveats).
        A batch that "drop" empties entirely raises here (a direct caller
        gets a clear error); the trainer's feed iterators
        (``reader/prefetch.py``) apply the policy themselves and SKIP
        such batches instead."""
        dp = self.mesh.shape.get("data", 1)
        if remainder != "error":
            # validated (and applied) even at dp=1, so a typo'd policy
            # fails on the dev box, not first on the pod
            adjusted = apply_remainder(tree, dp, remainder)
            enforce(
                adjusted is not None,
                f"batch smaller than the mesh data axis ({dp}) was fully "
                f"dropped by remainder='drop'; nothing left to shard",
            )
            tree = adjusted

        def place(x):
            if hasattr(x, "ndim") and x.ndim >= 1:
                enforce(
                    x.shape[0] % dp == 0,
                    f"batch size {x.shape[0]} is not divisible by the mesh "
                    f"data axis ({dp}); use a batch size that is a multiple "
                    f"of the replica count (drop_last=True in paddle.batch)",
                )
                return jax.device_put(x, self.data_sharding(x.ndim))
            return x

        return jax.tree.map(place, tree)

    def replicate(self, tree):
        sh = self.replicated()
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)

    def place_params(self, values: dict, specs: dict) -> dict:
        """Place each parameter per its ParamSpec.sharding (tensor parallel);
        unsharded params are replicated — the pure-DP layout that reproduces
        MultiGradientMachine's per-replica full copies."""
        out = {}
        for name, v in values.items():
            spec = specs.get(name)
            axes = getattr(spec, "sharding", None) if spec is not None else None
            out[name] = jax.device_put(v, self.param_sharding(axes, v.ndim))
        return out


def apply_remainder(tree, multiple: int, policy: str):
    """Make every batch-dim leaf of a feed pytree divisible by ``multiple``.

    - ``"drop"``: trim to the largest multiple, dropping tail samples.
      Returns None when nothing is left (callers skip the batch).
    - ``"pad"``: repeat the LAST sample up to the next multiple.  The
      padded rows are real duplicated samples, so the final partial batch
      of a pass weights its last sample slightly more in the loss — fine
      for throughput runs, wrong for exact-metric evaluation (use "drop"
      or full batches there).
    - ``"error"``: return the tree unchanged (shard_batch then enforces).

    Leaves without a leading batch dim (scalars) pass through; ragged
    pytrees (SequenceBatch data+length) stay consistent because every
    batch-dim leaf shares the same leading size.
    """
    if policy == "error":
        return tree
    enforce(policy in ("drop", "pad"),
            f"unknown batch remainder policy {policy!r} "
            "(expected 'error', 'drop' or 'pad')")
    batched = [x for x in jax.tree.leaves(tree)
               if hasattr(x, "ndim") and x.ndim >= 1]
    if not batched:
        return tree
    b = batched[0].shape[0]
    r = b % multiple
    if r == 0:
        return tree
    if policy == "drop":
        keep = b - r
        if keep == 0:
            return None
        return jax.tree.map(
            lambda x: x[:keep]
            if hasattr(x, "ndim") and x.ndim >= 1 else x, tree)
    pad = multiple - r

    def _pad(x):
        if not (hasattr(x, "ndim") and x.ndim >= 1):
            return x
        a = np.asarray(x)
        return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)

    return jax.tree.map(_pad, tree)


def resize_data_axis(ctx: MeshContext, new_dp: int,
                     devices=None) -> MeshContext:
    """A new MeshContext with the ``data`` axis resized to ``new_dp`` —
    the elastic-resharding mesh rebuild (``resilience/elastic.py``).

    Only pure data-parallel meshes resize live: a ``model``/``pipe``/
    ``seq`` axis > 1 would need its parameter shards re-laid-out too,
    which live resharding does not attempt.  ``devices`` selects the
    member devices explicitly (host-loss survivors keep their relative
    order); by default a shrink keeps the first ``new_dp`` of the
    current mesh and a grow extends with unattached devices.
    """
    old = ctx.mesh
    for a in old.axis_names:
        enforce(a == "data" or old.shape[a] == 1,
                f"resize_data_axis needs a pure data mesh; axis {a!r} "
                f"has size {old.shape[a]}")
    enforce(new_dp >= 1, f"new data degree must be >= 1, got {new_dp}")
    if devices is None:
        current = list(old.devices.flat)
        if new_dp <= len(current):
            devices = current[:new_dp]
        else:
            pool = current + [d for d in jax.devices()
                              if d not in current]
            enforce(len(pool) >= new_dp,
                    f"resize to data={new_dp} needs {new_dp} devices; "
                    f"only {len(pool)} attached")
            devices = pool[:new_dp]
    enforce(len(devices) == new_dp,
            f"{len(devices)} devices given for data={new_dp}")
    return MeshContext(mesh=make_mesh({"data": new_dp},
                                      devices=list(devices)))


def get_mesh(shape: dict[str, int] | None = None) -> MeshContext:
    global _current
    if _current is None or shape is not None:
        _current = MeshContext(mesh=make_mesh(shape))
    return _current


def set_mesh(ctx: MeshContext) -> None:
    global _current
    _current = ctx
