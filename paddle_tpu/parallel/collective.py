"""Collective ops — the op-level surface of the reference's three comm
backends (``paddle/operators/nccl_op.cc:66-191`` NCCLAllReduce/Reduce/Bcast,
the pserver scatter/gather of ``ParameterClient2``, and the Go pserver RPC),
expressed as XLA ICI collectives usable inside ``shard_map``.

Inside compiled programs these lower to ICI all-reduce / all-gather /
reduce-scatter / collective-permute; across slices XLA routes them over DCN.
No host-side transport exists or is needed — the "network" is the compiler's
problem, which is the whole point of the TPU-native redesign (SURVEY §2.3).

Observability: every wrapper below (a) runs under a ``jax.named_scope``
(``comm.<op>.<axis>``) so profiler traces attribute collective time to
the call site, and (b) reports its per-shard payload bytes through
``paddle_tpu.telemetry.record_comm`` while XLA traces the program —
shapes are static, so one trace of a program body gives that body's
per-execution payload.  ``SGD.train`` lowers its step under
``telemetry.capture_comm`` to attach exactly that program's bytes to
each step record (``comm_bytes``); outside a capture the global
``comm_bytes``/``comm_calls`` counters accumulate across traces.
Known limit: a collective inside a ``lax.scan``/``fori_loop`` body is
traced once but executes once per iteration, so loop-carried comm
(pipeline handoffs, ring attention) is undercounted by the trip count —
use the ``comm.<op>.<axis>`` trace annotations for exact loop timing.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu import compat
from paddle_tpu.compat import shard_map


def _comm_record(op: str, axis_name, x, divide: int = 1) -> None:
    """Account one traced collective call site (never raises — telemetry
    must not break compilation).  ``divide`` scales the recorded payload
    (reduce_scatter records the per-device OUTPUT shard, i.e. input
    bytes / axis size — the bytes each rank materializes and applies)."""
    from paddle_tpu.telemetry import record_comm, swallow

    with swallow("collective_census"):
        nbytes = 0
        for leaf in jax.tree.leaves(x):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            nbytes += n * jnp.dtype(dtype).itemsize
        axis = "+".join(axis_name) if isinstance(axis_name, (tuple, list)) \
            else str(axis_name)
        record_comm(op, axis, nbytes // max(int(divide), 1))


def _scope(op: str, axis_name):
    axis = "+".join(axis_name) if isinstance(axis_name, (tuple, list)) \
        else str(axis_name)
    return jax.named_scope(f"comm.{op}.{axis}")


def all_reduce(x, axis_name: str, op: str = "sum"):
    """≅ NCCLAllReduce (nccl_op.cc:66); the gradient-sync primitive that
    replaces ParameterServer2::addGradient + getParameter round-trips."""
    _comm_record("all_reduce", axis_name, x)
    with _scope("all_reduce", axis_name):
        if op == "sum":
            return lax.psum(x, axis_name)
        if op == "mean":
            return lax.pmean(x, axis_name)
        if op == "max":
            return lax.pmax(x, axis_name)
        if op == "min":
            return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` from every device on the mesh axis."""
    _comm_record("all_gather", axis_name, x)
    with _scope("all_gather", axis_name):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    """Sum-reduce then scatter shards — the ZeRO/“sharded grads” primitive.

    Census accounting records the per-device OUTPUT shard bytes (input /
    axis size): the reduce result a rank materializes is 1/n of what the
    equivalent all_reduce would hand it, which is exactly the ZeRO-2
    grad-reduce saving the census is meant to show."""
    try:
        n = compat.axis_size(axis_name)
    except Exception as e:
        # axis unbound in this trace (e.g. a pure-accounting probe
        # outside the mesh): record the undivided payload
        from paddle_tpu.core import logger as _log

        _log.debug("reduce_scatter census: axis size of %r unavailable "
                   "(%s); recording undivided bytes", axis_name, e)
        n = 1
    _comm_record("reduce_scatter", axis_name, x, divide=n)
    with _scope("reduce_scatter", axis_name):
        return lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int,
               tiled: bool = True):
    """≅ NCCL alltoall — the MoE token-exchange primitive (each shard
    sends slice i of ``split_axis`` to rank i, receiving along
    ``concat_axis``)."""
    _comm_record("all_to_all", axis_name, x)
    with _scope("all_to_all", axis_name):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def broadcast(x, axis_name: str, root: int = 0):
    """≅ NCCLBcast: every device gets root's value."""
    _comm_record("broadcast", axis_name, x)
    with _scope("broadcast", axis_name):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)


def permute(x, axis_name: str, perm: list[tuple[int, int]]):
    """≅ collective-permute (pipeline-stage handoff, ring rotation)."""
    _comm_record("permute", axis_name, x)
    with _scope("permute", axis_name):
        return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the mesh axis ring."""
    _comm_record("ring_shift", axis_name, x)
    with _scope("ring_shift", axis_name):
        n = compat.axis_size(axis_name)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, axis_name, perm)


def psum_tree(tree, axis_name: str):
    """All-reduce every leaf of a pytree (the whole-gradient sync)."""
    _comm_record("psum_tree", axis_name, tree)
    with _scope("psum_tree", axis_name):
        return jax.tree.map(lambda g: lax.psum(g, axis_name), tree)


def on_mesh(mesh, fn, in_specs, out_specs):
    """Run ``fn`` (which uses the collectives above) under shard_map."""
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


def pmean_tree(tree, axis_name: str):
    """Mean-all-reduce every leaf of a pytree."""
    return jax.tree.map(lambda g: lax.pmean(g, axis_name), tree)


def data_parallel_mean_grads(mesh, stacked_grads):
    """Eager mean of per-replica gradients (≅ MultiGradientMachine's ring
    gradient gather, `MultiGradientMachine.h:44-98`): every leaf must be
    stacked per-device on dim 0 with shape [n_data_devices, ...]; returns the
    tree of means with the device axis dropped.  Inside a jitted train step
    you never need this — XLA inserts the all-reduce from shardings."""
    n = mesh.shape["data"]
    for leaf in jax.tree.leaves(stacked_grads):
        if leaf.shape[0] != n:
            raise ValueError(
                f"data_parallel_mean_grads expects per-device stacked leaves "
                f"[{n}, ...]; got leading dim {leaf.shape[0]}")
    fn = shard_map(
        lambda tree: jax.tree.map(lambda g: lax.pmean(g, "data")[0], tree),
        mesh=mesh,
        in_specs=jax.tree.map(lambda _: P("data"), stacked_grads),
        out_specs=jax.tree.map(lambda _: P(), stacked_grads),
        check_vma=False,
    )
    return fn(stacked_grads)
