"""Collective ops — the op-level surface of the reference's three comm
backends (``paddle/operators/nccl_op.cc:66-191`` NCCLAllReduce/Reduce/Bcast,
the pserver scatter/gather of ``ParameterClient2``, and the Go pserver RPC),
expressed as XLA ICI collectives usable inside ``shard_map``.

Inside compiled programs these lower to ICI all-reduce / all-gather /
reduce-scatter / collective-permute; across slices XLA routes them over DCN.
No host-side transport exists or is needed — the "network" is the compiler's
problem, which is the whole point of the TPU-native redesign (SURVEY §2.3).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P


def all_reduce(x, axis_name: str, op: str = "sum"):
    """≅ NCCLAllReduce (nccl_op.cc:66); the gradient-sync primitive that
    replaces ParameterServer2::addGradient + getParameter round-trips."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` from every device on the mesh axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    """Sum-reduce then scatter shards — the ZeRO/“sharded grads” primitive."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x, axis_name: str, root: int = 0):
    """≅ NCCLBcast: every device gets root's value."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def permute(x, axis_name: str, perm: list[tuple[int, int]]):
    """≅ collective-permute (pipeline-stage handoff, ring rotation)."""
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the mesh axis ring."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def psum_tree(tree, axis_name: str):
    """All-reduce every leaf of a pytree (the whole-gradient sync)."""
    return jax.tree.map(lambda g: lax.psum(g, axis_name), tree)


def on_mesh(mesh, fn, in_specs, out_specs):
    """Run ``fn`` (which uses the collectives above) under shard_map."""
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


def pmean_tree(tree, axis_name: str):
    """Mean-all-reduce every leaf of a pytree."""
    return jax.tree.map(lambda g: lax.pmean(g, axis_name), tree)


def data_parallel_mean_grads(mesh, stacked_grads):
    """Eager mean of per-replica gradients (≅ MultiGradientMachine's ring
    gradient gather, `MultiGradientMachine.h:44-98`): every leaf must be
    stacked per-device on dim 0 with shape [n_data_devices, ...]; returns the
    tree of means with the device axis dropped.  Inside a jitted train step
    you never need this — XLA inserts the all-reduce from shardings."""
    n = mesh.shape["data"]
    for leaf in jax.tree.leaves(stacked_grads):
        if leaf.shape[0] != n:
            raise ValueError(
                f"data_parallel_mean_grads expects per-device stacked leaves "
                f"[{n}, ...]; got leading dim {leaf.shape[0]}")
    fn = shard_map(
        lambda tree: jax.tree.map(lambda g: lax.pmean(g, "data")[0], tree),
        mesh=mesh,
        in_specs=jax.tree.map(lambda _: P("data"), stacked_grads),
        out_specs=jax.tree.map(lambda _: P(), stacked_grads),
        check_vma=False,
    )
    return fn(stacked_grads)
