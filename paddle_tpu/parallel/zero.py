"""ZeRO-1/2: weight-update sharding over the ``data`` mesh axis.

The reference's parameter server IS sharded weight update: parameter
blocks hash across pservers and each server applies the update rule to
its shard only (``ParameterServer2.h:73-666``, ``addGradient:482`` →
server-side SGD; the Go path likewise splits parameters across pserver
indices, ``go/pserver/client/c/cclient.go``).  Rounds 2-4 replaced the
pserver wholesale with ICI all-reduce and *replicated* optimizer state;
this module restores the sharded-aggregation property in-mesh — the
transformation of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., PAPERS.md):

- **ZeRO-1**: every optimizer slot buffer (Adam ``m``/``v``, momentum
  velocity, …) is sharded 1/n per data-parallel rank, cutting optimizer
  memory from ~2x params to 2x/n per device; gradients stay all-reduced.
- **ZeRO-2**: the gradient all-reduce itself is replaced by
  reduce-scatter — each rank receives only the 1/n gradient shard its
  state shard needs, applies the optimizer there, and the updated
  parameters are all-gathered back.  Grad-reduce bytes/device drop to
  1/n of the all-reduce payload.

Two lowerings produce the same math:

- ``sync_grads``/``gather_params`` — the EXPLICIT lowering: called from
  inside/around a ``shard_map`` region over ``data`` (the trainer's
  zero-mode step), the gradient flow goes through the
  ``parallel/collective.py`` wrappers, so the telemetry census
  (``_comm_record``) proves the collective swap and the compiled program
  contains literal ``reduce-scatter``/``all-gather`` ops on every
  backend (including the CPU testbed).
- ``constrain_grads``/``constrain_opt_state``/``constrain_params`` — the
  GSPMD lowering: ``with_sharding_constraint`` annotations direct the
  SPMD partitioner to the same reduce-scatter + sharded-update +
  all-gather form (exactly the paper's automatic pass).  This composes
  with arbitrary forwards (TP ``model`` axes, the MoE ``expert`` axis,
  inner shard_maps), so it is the path for multi-axis meshes.  NOTE:
  the bytes these helpers record through ``record_comm`` are the
  payloads the partitioner is DIRECTED to move; a backend may lower
  differently (CPU XLA emits all-reduce + dynamic-slice where TPU XLA
  emits reduce-scatter).

Sharding choice per leaf: keep whatever axes the leaf's parameter
already uses (TP composes), then lay ``data`` on the largest remaining
dimension it divides; leaves with no divisible free dim stay replicated
(scalars, tiny biases — their memory is noise, and their gradient sync
stays an all-reduce).

The OPTIMIZER step on the shard these specs describe has a fused
lowering: under the explicit ZeRO-2 path, ``trainer/step.py`` routes
eligible SGD/momentum updates through
``ops/pallas/tpp/update.fused_shard_apply`` — one read-modify-write
kernel pass per leaf inside a ``shard_map`` region over ``data``,
p/velocity donated in place (gated by the ``fused_kernels`` flag;
bit-identical to ``optimizer.apply``, asserted in tests/test_tpp.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.compat import shard_map


def _leaf_spec(shape, n: int, axis: str, base: P | None) -> P:
    used = list(base) if base is not None else [None] * len(shape)
    used = used[:len(shape)] + [None] * (len(shape) - len(used))
    best, best_size = None, 0
    for d, size in enumerate(shape):
        if used[d] is None and size % n == 0 and size > best_size:
            best, best_size = d, size
    if best is None:
        return P(*used) if base is not None else P()
    used[best] = axis
    return P(*used)


def _normalize_base(spec, mesh) -> P | None:
    """A param-sharding spec with axes absent from ``mesh`` dropped."""
    if spec is None:
        return None
    present = set(mesh.axis_names)
    return P(*[a if a in present else None for a in spec])


def _base_list(params, mesh, param_specs):
    """Per-params-leaf base spec list (None = unannotated/replicated).
    ``param_specs`` must carry a P for EVERY params leaf (use ``P()`` for
    replicated — a None entry is an empty pytree to jax and would
    silently misalign the whole list)."""
    leaves = jax.tree.leaves(params)
    if param_specs is None:
        return leaves, [None] * len(leaves)
    base = [
        _normalize_base(sp, mesh)
        for sp in jax.tree.leaves(param_specs,
                                  is_leaf=lambda x: isinstance(x, P))
    ]
    if len(base) != len(leaves):
        raise ValueError(
            f"param_specs has {len(base)} PartitionSpec leaves for "
            f"{len(leaves)} parameter leaves — every leaf needs a spec "
            "(use P() for replicated; None entries vanish from pytrees)")
    return leaves, base


def grad_specs(params, mesh, axis: str = "data", param_specs=None):
    """PartitionSpec pytree matching ``params``: each leaf's ZeRO shard
    layout (base TP axes preserved, ``axis`` on the largest free
    divisible dim, replicated when nothing divides)."""
    n = mesh.shape[axis]
    leaves, base = _base_list(params, mesh, param_specs)
    treedef = jax.tree.structure(params)
    return jax.tree.unflatten(
        treedef,
        [_leaf_spec(p.shape, n, axis, b) for p, b in zip(leaves, base)])


def data_dim(spec: P, axis: str = "data") -> int | None:
    """Dim index ``axis`` occupies in ``spec`` (None = replicated)."""
    for d, entry in enumerate(spec):
        names = entry if isinstance(entry, tuple) else (entry,)
        if axis in names:
            return d
    return None


def _slot_spec(slot_shape, p, base: P | None, n: int, axis: str) -> P:
    """Spec for one optimizer-slot leaf: same layout as its parameter
    when shapes match (the common zeros_like slot); scalars and
    odd-shaped slots (SparseMomentum's alpha/beta/tau, SGD's mu) stay
    replicated unless their own shape divides."""
    if tuple(slot_shape) == tuple(p.shape):
        return _leaf_spec(p.shape, n, axis, base)
    if len(slot_shape) == 0:
        return P()
    return _leaf_spec(slot_shape, n, axis, None)


def state_specs(opt_state, params, mesh, axis: str = "data",
                param_specs=None):
    """PartitionSpec pytree matching ``opt_state`` for ZeRO state
    sharding.  Handles both optimizer-state layouts:

    - ``Optimizer.init_tree``/``apply_tree``: ``{"step", "slots": [per-
      params-leaf slot trees]}`` (the transformer family);
    - ``Optimizer.init``/``apply``: ``{"step", "slots": {name: slot
      tree}, ["avg": params-like, "avg_count"]}`` (the Topology trainer).

    The scalar ``step`` (and any other non-slot scalar) is never
    sharded; ``avg`` (model-average) leaves shard like their parameters.
    ``param_specs``: optional base PartitionSpec pytree matching
    ``params`` (TP axes preserved; for the trainer layout a
    ``{name: P}`` dict)."""
    n = mesh.shape[axis]
    slots = opt_state["slots"]
    if isinstance(slots, dict):
        # trainer layout: keyed by parameter name
        p_map = params
        base_map = param_specs or {}
        slot_specs = {
            name: jax.tree.map(
                lambda s, _p=p_map[name], _b=_normalize_base(
                    base_map.get(name), mesh):
                _slot_spec(getattr(s, "shape", ()), _p, _b, n, axis),
                slot)
            for name, slot in slots.items()
        }
    else:
        leaves, base = _base_list(params, mesh, param_specs)
        slot_specs = [
            jax.tree.map(
                lambda s, _p=p, _b=b: _slot_spec(
                    getattr(s, "shape", ()), _p, _b, n, axis),
                slot)
            for p, b, slot in zip(leaves, base, slots)
        ]
    specs = {}
    for k, v in opt_state.items():
        if k == "slots":
            specs[k] = slot_specs
        elif k == "avg":
            specs[k] = grad_specs(v, mesh, axis, param_specs=param_specs)
        else:
            specs[k] = jax.tree.map(lambda _: P(), v)
    return specs


def zero1_specs(opt_state, params, mesh, axis: str = "data",
                param_specs=None):
    """Back-compat alias of :func:`state_specs` (the original ZeRO-1
    entry point; transformer ``init_tree`` layout)."""
    return state_specs(opt_state, params, mesh, axis,
                       param_specs=param_specs)


def shard_opt_state(opt_state, params, mesh, axis: str = "data",
                    param_specs=None):
    """device_put the optimizer state per :func:`state_specs`."""
    specs = state_specs(opt_state, params, mesh, axis,
                        param_specs=param_specs)
    placed = _put_tree(opt_state, specs, mesh)
    # telemetry gauge: per-device slot residency (the ZeRO headline)
    from paddle_tpu.telemetry import get_default_registry, swallow

    with swallow("zero_state_gauge"):
        get_default_registry().gauge(
            "zero1_state_bytes_per_device",
            "addressable optimizer-slot bytes on one device").set(
            float(state_bytes_per_device(placed)), axis=axis)
    return placed


def _put_tree(state, specs, mesh):
    flat_s, treedef = jax.tree.flatten(state)
    flat_p = treedef.flatten_up_to(specs)
    placed = [jax.device_put(x, NamedSharding(mesh, sp))
              for x, sp in zip(flat_s, flat_p)]
    return jax.tree.unflatten(treedef, placed)


def constrain_tree(tree, specs, mesh, scope: str = "zero.constrain"):
    """with_sharding_constraint over a pytree (inside jit): pins each
    leaf to its shard so GSPMD keeps the sharded form instead of
    replicating."""
    flat_s, treedef = jax.tree.flatten(tree)
    flat_p = treedef.flatten_up_to(specs)
    with jax.named_scope(scope):
        out = [jax.lax.with_sharding_constraint(x, NamedSharding(mesh, sp))
               for x, sp in zip(flat_s, flat_p)]
    return jax.tree.unflatten(treedef, out)


def constrain_opt_state(opt_state, specs, mesh):
    """Pin the updated optimizer state to its ZeRO shards (inside jit)."""
    return constrain_tree(opt_state, specs, mesh,
                          scope="zero.constrain_opt_state")


def _record_directed(op: str, axis: str, nbytes: float) -> None:
    """Account a collective the GSPMD lowering DIRECTS the partitioner
    to emit (the explicit lowering records through the wrappers
    instead).  Never raises."""
    from paddle_tpu.telemetry import record_comm, swallow

    with swallow("zero_directed_census"):
        record_comm(op, axis, int(nbytes))


def constrain_grads(grads, specs, mesh, axis: str = "data"):
    """GSPMD lowering of the ZeRO-2 gradient reduce-scatter: constrain
    each gradient leaf to its shard layout, directing the partitioner to
    produce the cross-replica sum AS SHARDS (reduce-scatter on TPU; CPU
    XLA lowers the same program as all-reduce + dynamic-slice).  Records
    the directed per-device payload (shard bytes) per leaf."""
    n = mesh.shape[axis]
    flat_g, treedef = jax.tree.flatten(grads)
    flat_p = treedef.flatten_up_to(specs)
    for g, sp in zip(flat_g, flat_p):
        if data_dim(sp, axis) is not None:
            _record_directed("reduce_scatter", axis, g.size * g.dtype.itemsize // n)
        else:
            _record_directed("all_reduce", axis, g.size * g.dtype.itemsize)
    return constrain_tree(grads, specs, mesh, scope="zero.scatter_grads")


def constrain_params(params, mesh, axis: str = "data", param_specs=None,
                     zero_specs=None):
    """GSPMD lowering of the ZeRO param all-gather: constrain updated
    parameters back to their base layout (replicated, or the TP spec),
    directing an all-gather of each rank's updated shard."""
    leaves, base = _base_list(params, mesh, param_specs)
    n = mesh.shape[axis]
    if zero_specs is not None:
        flat_z = jax.tree.structure(params).flatten_up_to(zero_specs)
    else:
        flat_z = [None] * len(leaves)
    for p, z in zip(leaves, flat_z):
        if z is not None and data_dim(z, axis) is not None:
            _record_directed("all_gather", axis, p.size * p.dtype.itemsize // n)
    treedef = jax.tree.structure(params)
    base_specs = jax.tree.unflatten(
        treedef, [b if b is not None else P() for b in base])
    return constrain_tree(params, base_specs, mesh,
                          scope="zero.gather_params")


# -- the explicit lowering (shard_map over the data axis) ---------------------


def sync_grads(grads, specs, axis: str = "data"):
    """Gradient sync INSIDE a ``shard_map`` region over ``axis``: leaves
    whose spec carries ``axis`` are reduce-scattered onto that dim (each
    rank keeps its 1/n shard); leaves with no divisible dim are
    all-reduced (replicated — their state shards are replicated too).
    Goes through the ``parallel/collective.py`` wrappers, so every
    payload lands in the telemetry census."""
    from paddle_tpu.parallel import collective

    def sync(g, sp):
        d = data_dim(sp, axis)
        if d is None:
            return collective.all_reduce(g, axis)
        return collective.reduce_scatter(g, axis, axis=d)

    flat_g, treedef = jax.tree.flatten(grads)
    flat_p = treedef.flatten_up_to(specs)
    return jax.tree.unflatten(
        treedef, [sync(g, sp) for g, sp in zip(flat_g, flat_p)])


def gather_params(params, specs, mesh, axis: str = "data"):
    """Explicit ZeRO param all-gather: a ``shard_map`` region over
    ``axis`` whose in_specs hand each rank its updated shard and whose
    body all-gathers it back to the full parameter (through the
    collective wrappers — census-visible).  Leaves whose spec carries no
    ``axis`` pass through replicated.  Requires ``axis`` to be the only
    >1 mesh axis (the explicit lowering's precondition)."""
    from paddle_tpu.parallel import collective

    flat, treedef = jax.tree.flatten(params)
    flat_sp = treedef.flatten_up_to(specs)

    def body(*leaves):
        out = []
        for x, sp in zip(leaves, flat_sp):
            d = data_dim(sp, axis)
            if d is None:
                out.append(x)
            else:
                out.append(collective.all_gather(x, axis, axis=d,
                                                 tiled=True))
        return tuple(out)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=tuple(flat_sp),
        out_specs=tuple(P() for _ in flat),
        check_vma=False)
    return jax.tree.unflatten(treedef, list(fn(*flat)))


def explicit_lowering_ok(mesh, axis: str = "data") -> bool:
    """True when the explicit (shard_map) lowering applies: ``axis`` is
    on the mesh with size > 1 and every other axis is trivial.  Forwards
    with inner constraints/shard_maps naming other live axes (TP, MoE)
    need the GSPMD lowering instead."""
    if axis not in mesh.axis_names:
        return False
    if mesh.shape[axis] <= 1:
        return False
    return all(mesh.shape[a] == 1 for a in mesh.axis_names if a != axis)


def respec_report(opt_state, old_mesh, new_mesh, axis: str = "data",
                  ) -> dict:
    """How the ZeRO state layout changes when the ``axis`` degree
    changes — the elastic-resharding accounting (``resilience/
    elastic.py`` attaches it to every ``elastic_event`` record).

    Per optimizer-slot leaf the report counts: ``resharded`` (sharded
    at both degrees — its shard merely resizes), ``to_replicated``
    (divisible at the old degree only: the new degree can't split it,
    so it costs full residency again), ``to_sharded`` (the reverse) and
    ``replicated`` (never sharded), plus the resulting slot
    bytes/device at each degree.  Shapes only — no device data is
    touched, so it is safe to run on a mesh that is about to die.
    """
    old_n = int(dict(old_mesh.shape).get(axis, 1))
    new_n = int(dict(new_mesh.shape).get(axis, 1))
    report = {"axis": axis, "old_degree": old_n, "new_degree": new_n,
              "resharded": 0, "to_replicated": 0, "to_sharded": 0,
              "replicated": 0, "old_bytes_per_device": 0,
              "new_bytes_per_device": 0}
    slots = (opt_state.get("slots", opt_state)
             if isinstance(opt_state, dict) else opt_state)
    for leaf in jax.tree.leaves(slots):
        shape = tuple(getattr(leaf, "shape", ()))
        nbytes = 1
        for d in shape:
            nbytes *= int(d)
        nbytes *= int(getattr(getattr(leaf, "dtype", None), "itemsize",
                              4) or 4)

        def sharded_at(n):
            return (n > 1 and
                    data_dim(_leaf_spec(shape, n, axis, None),
                             axis) is not None)

        old_s, new_s = sharded_at(old_n), sharded_at(new_n)
        key = ("resharded" if old_s and new_s else
               "to_replicated" if old_s else
               "to_sharded" if new_s else "replicated")
        report[key] += 1
        report["old_bytes_per_device"] += nbytes // (old_n if old_s
                                                     else 1)
        report["new_bytes_per_device"] += nbytes // (new_n if new_s
                                                     else 1)
    return report


def state_bytes_per_device(opt_state) -> int:
    """Addressable bytes of one device's shard of the slot buffers."""
    total = 0
    for leaf in jax.tree.leaves(opt_state["slots"]):
        shard = leaf.addressable_shards[0]
        total += shard.data.size * shard.data.dtype.itemsize
    return total
