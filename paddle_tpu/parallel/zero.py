"""ZeRO-1: optimizer-state sharding over the ``data`` mesh axis.

The reference's parameter server IS sharded optimizer state: parameter
blocks hash across pservers and each server applies the update rule to
its shard only (``ParameterServer2.h:73-666``, ``addGradient:482`` →
server-side SGD; the Go path likewise splits parameters across pserver
indices, ``go/pserver/client/c/cclient.go``).  Rounds 2-4 replaced the
pserver wholesale with ICI all-reduce and *replicated* optimizer state;
this module restores the sharded-state property in-mesh — the ZeRO-1 /
FSDP spelling of the same idea:

- every Adam ``m``/``v`` buffer (any slot pytree) is sharded 1/n per
  data-parallel rank, cutting optimizer memory from 2x params to
  2x/n per device;
- the update is annotated with ``with_sharding_constraint`` so GSPMD
  keeps the state resident in shards and lowers the grad flow into
  reduce-scatter + sharded update + all-gather over ICI, instead of
  all-reduce + replicated update.

Sharding choice per leaf: keep whatever axes the leaf's parameter
already uses (TP composes), then lay ``data`` on the largest remaining
dimension it divides; leaves with no divisible free dim stay
replicated (scalars, tiny biases — their memory is noise).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def _leaf_spec(shape, n: int, axis: str, base: P | None) -> P:
    used = list(base) if base is not None else [None] * len(shape)
    used += [None] * (len(shape) - len(used))
    best, best_size = None, 0
    for d, size in enumerate(shape):
        if used[d] is None and size % n == 0 and size > best_size:
            best, best_size = d, size
    if best is None:
        return P(*used) if base is not None else P()
    used[best] = axis
    return P(*used)


def zero1_specs(opt_state, params, mesh, axis: str = "data",
                param_specs=None):
    """PartitionSpec pytree matching ``opt_state`` (the Optimizer
    init_tree/apply_tree layout: {"step", "slots": [per-leaf slot dicts]}).

    ``param_specs``: optional PartitionSpec pytree matching ``params``
    (e.g. transformer.param_shardings) whose axes are preserved; the
    ``axis`` shards one remaining dimension of every slot buffer.
    """
    n = mesh.shape[axis]
    leaves = jax.tree.leaves(params)
    if param_specs is None:
        base_list = [None] * len(leaves)
    else:
        present = set(mesh.axis_names)
        base_list = [
            P(*[a if a in present else None for a in sp])
            for sp in jax.tree.leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P))
        ]
    slot_specs = [
        jax.tree.map(
            lambda s, _p=p, _b=base: _leaf_spec(_p.shape, n, axis, _b),
            slots)
        for p, base, slots in zip(leaves, base_list, opt_state["slots"])
    ]
    specs = {k: jax.tree.map(lambda _: P(), v)
             for k, v in opt_state.items()}
    specs["slots"] = slot_specs
    return specs


def shard_opt_state(opt_state, params, mesh, axis: str = "data",
                    param_specs=None):
    """device_put the optimizer state per zero1_specs."""
    specs = zero1_specs(opt_state, params, mesh, axis,
                        param_specs=param_specs)
    placed = _put_tree(opt_state, specs, mesh)
    try:  # telemetry gauge: per-device slot residency (ZeRO-1 headline)
        from paddle_tpu.telemetry import get_default_registry

        get_default_registry().gauge(
            "zero1_state_bytes_per_device",
            "addressable optimizer-slot bytes on one device").set(
            float(state_bytes_per_device(placed)), axis=axis)
    except Exception:
        pass
    return placed


def _put_tree(state, specs, mesh):
    flat_s, treedef = jax.tree.flatten(state)
    flat_p = treedef.flatten_up_to(specs)
    placed = [jax.device_put(x, NamedSharding(mesh, sp))
              for x, sp in zip(flat_s, flat_p)]
    return jax.tree.unflatten(treedef, placed)


def constrain_opt_state(opt_state, specs, mesh):
    """with_sharding_constraint over the state pytree (inside jit): pins
    the updated slots to their shards so GSPMD keeps the sharded-update
    form instead of replicating."""
    flat_s, treedef = jax.tree.flatten(opt_state)
    flat_p = treedef.flatten_up_to(specs)
    with jax.named_scope("zero1.constrain_opt_state"):
        out = [jax.lax.with_sharding_constraint(x, NamedSharding(mesh, sp))
               for x, sp in zip(flat_s, flat_p)]
    return jax.tree.unflatten(treedef, out)


def state_bytes_per_device(opt_state) -> int:
    """Addressable bytes of one device's shard of the slot buffers."""
    total = 0
    for leaf in jax.tree.leaves(opt_state["slots"]):
        shard = leaf.addressable_shards[0]
        total += shard.data.size * shard.data.dtype.itemsize
    return total
