"""Mixture-of-Experts with expert parallelism over an ``expert`` mesh axis.

The reference has no MoE (2017); its closest capability is the sparse
pserver path — only-touched rows move over the wire
(``SparseRemoteParameterUpdater``, ``SparseRowMatrix.h:204``).  This
module is the TPU-native upgrade of that idea, designed from the GShard /
Switch-Transformer formulation (PAPERS.md): conditional computation where
each token activates ``top_k`` of ``num_experts`` FFNs, experts are
sharded across devices, and tokens move to their experts via
``lax.all_to_all`` riding ICI — the role NCCL alltoall plays in GPU MoE
stacks.

Everything is static-shaped for XLA: routing assigns each (token,
choice) a fixed slot in its expert's capacity buffer (capacity ``C``
tokens per expert per group; overflow tokens are dropped, the standard
capacity-factor semantics).  Token movement has two equivalent forms —
``dispatch="sort"`` (default): scatter/gather by flat slot id, O(T·D)
data movement; ``dispatch="einsum"``: the GShard dense one-hot
``[T, E, C]`` dispatch/combine tensors.  Either way the layer is a few
array ops + one pair of all_to_alls, all differentiable (gates
included) under ``jax.grad``/``shard_map``.

Two execution paths with identical math:

- ``moe_ffn(...)``         — single-group dense dispatch (no mesh): the
                             reference implementation and single-chip path.
- ``moe_ffn_sharded(...)`` — tokens AND experts sharded over the mesh's
                             ``expert`` axis; per-shard routing (each shard
                             is one GShard "group"), all_to_all exchanges
                             ``[E, C, D] -> [E_local, shards*C, D]``,
                             local expert FFNs, all_to_all back, combine.

``aux_load_balancing_loss`` is the Switch loss: E * mean(load_fraction *
mean_gate_prob) per expert, pushing the router toward uniform load.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.compat import shard_map
from paddle_tpu.parallel import collective


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int
    mlp_dim: int
    top_k: int = 2              # 1 = Switch routing, 2 = GShard routing
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    # token movement: "sort" (default) = scatter/gather by flat slot
    # index e*C+pos — O(T*D) data movement; "einsum" = the dense one-hot
    # GShard tensors [T,E,C], O(T*E*C*D) FLOPs.  Same routing decisions
    # exactly (tests pin value+grad equality); sort measured +56%/+36%
    # tok/s (top-2/top-1) at the 8-expert GPT-2-width bench shape.
    dispatch: str = "sort"

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(
                f"top_k must be 1 (Switch) or 2 (GShard); got {self.top_k}")
        if self.dispatch not in ("einsum", "sort"):
            raise ValueError(
                f"dispatch must be 'einsum' or 'sort'; got {self.dispatch}")


def init_moe_params(key: jax.Array, embed_dim: int, cfg: MoEConfig,
                    dtype=jnp.float32) -> dict:
    """Router + per-expert FFN weights (experts stacked on axis 0)."""
    kg, k1, k2 = jax.random.split(key, 3)
    E, D, H = cfg.num_experts, embed_dim, cfg.mlp_dim
    return {
        "wg": (jax.random.normal(kg, (D, E)) * (1.0 / D ** 0.5)).astype(dtype),
        "w1": (jax.random.normal(k1, (E, D, H)) * (2.0 / D) ** 0.5).astype(dtype),
        "b1": jnp.zeros((E, H), dtype),
        "w2": (jax.random.normal(k2, (E, H, D)) * (1.0 / H) ** 0.5).astype(dtype),
        "b2": jnp.zeros((E, D), dtype),
    }


def capacity(tokens_per_group: int, cfg: MoEConfig) -> int:
    """Static per-expert buffer size for one routing group."""
    c = int(cfg.capacity_factor * cfg.top_k * tokens_per_group
            / cfg.num_experts)
    return max(c, 1)


def _positions(mask: jax.Array, cap: int, offset=None):
    """mask [T, E] 0/1 -> (kept mask [T, E], positions [T, E] float).

    A token's position inside its expert's buffer is its running count
    (cumsum over the group's token order); positions >= cap drop out —
    the deterministic, order-based capacity rule (GShard §3.2).
    """
    pos = jnp.cumsum(mask, axis=0) - 1.0
    if offset is not None:
        pos = pos + offset[None, :]
    keep = mask * (pos < cap).astype(mask.dtype)
    return keep, pos


def route_choices(x: jax.Array, wg: jax.Array, cfg: MoEConfig, cap: int):
    """Routing core shared by both dispatch forms.

    Returns (choices, aux): ``choices`` is a list over the top_k
    assignment slots of dicts with per-token ``eid`` (expert id, int),
    ``pos`` (position in the expert's capacity buffer, int), ``keep``
    (0/1 f32 survived capacity), and ``w`` (the renormalized combine
    weight, already zeroed for dropped tokens).  Gradients flow into
    the router through ``w``.

    Top-2 gate normalization convention (intentional divergence from
    GShard): the two gates are renormalized over the SURVIVING choices
    only — ``w_i = g_i * keep_i / max(g1*keep1 + g2*keep2, eps)`` — so a
    token whose first choice is capacity-dropped routes with full
    weight 1.0 to its second expert.  GShard's reference formulation
    normalizes by ``g1 + g2`` computed BEFORE capacity drops, which
    down-weights such tokens by their lost first-choice share.
    Post-drop renormalization keeps every surviving token's combine
    weights summing to 1 (no silent output scaling under congestion);
    switch the ``denom`` below to the pre-drop ``gate1 + gate2`` to
    reproduce GShard exactly.
    """
    f32 = jnp.float32
    logits = x.astype(f32) @ wg.astype(f32)          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    E = cfg.num_experts

    idx1 = jnp.argmax(probs, axis=-1)                # [T]
    mask1 = jax.nn.one_hot(idx1, E, dtype=f32)
    gate1 = jnp.sum(probs * mask1, axis=-1)          # [T]

    # Switch aux loss over the FIRST choice: fraction of tokens routed
    # to each expert x mean router prob, scaled by E (minimum 1.0 at
    # uniform load)
    load = jnp.mean(mask1, axis=0)
    importance = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(load * importance)

    def per_token(grid, eid):
        return jnp.take_along_axis(grid, eid[:, None], axis=1)[:, 0]

    keep1g, pos1g = _positions(mask1, cap)
    k1 = per_token(keep1g, idx1)
    choices = [{"eid": idx1, "pos": per_token(pos1g, idx1).astype(jnp.int32),
                "keep": k1, "w": gate1 * k1}]

    if cfg.top_k >= 2:
        probs2 = probs * (1.0 - mask1)               # mask out the winner
        idx2 = jnp.argmax(probs2, axis=-1)
        mask2 = jax.nn.one_hot(idx2, E, dtype=f32)
        gate2 = jnp.sum(probs * mask2, axis=-1)
        # second choices queue BEHIND every first-choice token
        # (GShard: the expert's buffer fills greedily by priority)
        expert_load1 = jnp.sum(keep1g, axis=0)       # [E]
        keep2g, pos2g = _positions(mask2, cap, offset=expert_load1)
        k2 = per_token(keep2g, idx2)
        # renormalize the two gates over what survived
        g1, g2 = gate1 * k1, gate2 * k2
        denom = jnp.maximum(g1 + g2, 1e-9)
        choices[0]["w"] = g1 / denom * k1
        choices.append(
            {"eid": idx2, "pos": per_token(pos2g, idx2).astype(jnp.int32),
             "keep": k2, "w": g2 / denom * k2})
    return choices, aux


def route(x: jax.Array, wg: jax.Array, cfg: MoEConfig, cap: int):
    """Tokens [T, D] -> (dispatch [T,E,C], combine [T,E,C], aux_loss):
    the dense one-hot tensors built from ``route_choices``.  combine
    carries the (renormalized) gate probabilities; dispatch is its 0/1
    support."""
    choices, aux = route_choices(x, wg, cfg, cap)
    E = cfg.num_experts
    f32 = jnp.float32
    combine = 0.0
    for c in choices:
        oh = (jax.nn.one_hot(c["eid"], E, dtype=f32)[:, :, None]
              * jax.nn.one_hot(c["pos"], cap, dtype=f32)[:, None, :])
        combine = combine + c["w"][:, None, None] * oh  # w already keep-zeroed
    dispatch = (combine > 0.0).astype(f32)
    return dispatch, combine, aux


def _slot_ids(choices, E: int, cap: int):
    """Per choice: flat buffer slot e*C+pos for kept tokens, E*C (the
    junk row) for dropped ones."""
    return [jnp.where(c["keep"] > 0, c["eid"] * cap + c["pos"], E * cap)
            for c in choices]


def _scatter_tokens(x2, choices, E: int, cap: int):
    """Tokens -> expert buffers [E, C, D] by scatter (no [T,E,C] tensor).

    Slots are unique by construction (each (expert, pos<C) pair belongs
    to exactly one (token, choice)), so the scatter-add never collides
    except in the junk row."""
    d = x2.shape[1]
    slots = _slot_ids(choices, E, cap)
    s = jnp.concatenate(slots)
    upd = jnp.concatenate([x2] * len(choices), axis=0)
    xe_flat = jnp.zeros((E * cap + 1, d), x2.dtype).at[s].add(upd)
    return xe_flat[:-1].reshape(E, cap, d), slots


def _gather_tokens(ye, choices, slots):
    """Expert outputs [E, C, D] -> tokens [T, D] by weighted gather."""
    e, cap, d = ye.shape
    ye_pad = jnp.concatenate(
        [ye.reshape(e * cap, d), jnp.zeros((1, d), ye.dtype)], axis=0)
    y = 0.0
    for c, s in zip(choices, slots):
        y = y + c["w"].astype(ye.dtype)[:, None] * ye_pad[s]
    return y


def _expert_ffn(w1, b1, w2, b2, xe):
    """xe [E, C, D] through each expert's FFN (batched einsum)."""
    f32 = jnp.float32
    h = jnp.einsum("ecd,edh->ech", xe, w1.astype(xe.dtype)) + b1[:, None, :]
    h = jax.nn.gelu(h.astype(f32)).astype(xe.dtype)
    return jnp.einsum("ech,ehd->ecd", h, w2.astype(xe.dtype)) + b2[:, None, :]


def moe_ffn(params: dict, x: jax.Array, cfg: MoEConfig,
            cap: int | None = None):
    """Dense-dispatch MoE over one token group.

    x: [T, D] (or [B, T, D], flattened to one group).  Returns
    (y like x, aux_loss scalar).
    """
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    T = x2.shape[0]
    c = cap or capacity(T, cfg)
    E = cfg.num_experts
    if cfg.dispatch == "sort":
        choices, aux = route_choices(x2, params["wg"], cfg, c)
        xe, slots = _scatter_tokens(x2, choices, E, c)
        ye = _expert_ffn(params["w1"], params["b1"], params["w2"],
                         params["b2"], xe)
        y = _gather_tokens(ye, choices, slots)
    else:
        dispatch, combine, aux = route(x2, params["wg"], cfg, c)
        xe = jnp.einsum("tec,td->ecd", dispatch.astype(x2.dtype), x2)
        ye = _expert_ffn(params["w1"], params["b1"], params["w2"],
                         params["b2"], xe)
        y = jnp.einsum("tec,ecd->td", combine.astype(x2.dtype), ye)
    return y.reshape(shape), aux


def moe_ffn_sharded(params: dict, x: jax.Array, cfg: MoEConfig, mesh,
                    axis: str = "expert",
                    batch_axes: tuple[str, ...] | None = None,
                    cap: int | None = None):
    """Expert-parallel MoE: tokens and experts sharded over ``axis``.

    x: [T, D] (or [B, T, D]) with the leading dim divisible by the
    sharding axes; params["w1"/"b1"/"w2"/"b2"] sharded on their expert
    dim, ``wg`` replicated.  Each shard routes its local tokens (one
    GShard "group"), all_to_all sends each expert's ``[E, C, D]`` slice
    to the expert's owner (becoming ``[E_local, n*C, D]``), the local
    FFNs run, and the reverse all_to_all brings expert outputs home for
    the combine.

    ``batch_axes``: additional mesh axes the token batch is sharded
    over (e.g. ``("data",)`` inside a dp+ep step) — experts stay
    replicated across them; the all_to_all runs within each batch
    slice.  Defaults to ``("data",)`` when the mesh has one.  Returns
    (y, aux_loss averaged over every shard).
    """
    n = mesh.shape[axis]
    E = cfg.num_experts
    if E % n:
        raise ValueError(f"num_experts {E} not divisible by mesh axis "
                         f"'{axis}' size {n}")
    if batch_axes is None:
        batch_axes = ("data",) if "data" in mesh.axis_names else ()
    n_tok_shards = n
    for a in batch_axes:
        n_tok_shards *= mesh.shape[a]
    T = x.reshape(-1, x.shape[-1]).shape[0]
    c = cap or capacity(T // n_tok_shards, cfg)
    all_axes = tuple(batch_axes) + (axis,)

    def body(wg, w1, b1, w2, b2, xs):
        x2 = xs.reshape(-1, xs.shape[-1])
        if cfg.dispatch == "sort":
            choices, aux = route_choices(x2, wg, cfg, c)
            xe, slots = _scatter_tokens(x2, choices, E, c)
        else:
            dispatch, combine, aux = route(x2, wg, cfg, c)
            xe = jnp.einsum("tec,td->ecd", dispatch.astype(x2.dtype), x2)
        # [E, C, D] -> [E_local, n*C, D]: tokens travel to expert owners
        # (collective.all_to_all: trace-annotated + comm-bytes-counted)
        xe = collective.all_to_all(xe, axis, split_axis=0, concat_axis=1,
                                   tiled=True)
        ye = _expert_ffn(w1, b1, w2, b2, xe)
        # [E_local, n*C, D] -> [E, C, D]: results return to token owners
        ye = collective.all_to_all(ye, axis, split_axis=1, concat_axis=0,
                                   tiled=True)
        if cfg.dispatch == "sort":
            y = _gather_tokens(ye, choices, slots)
        else:
            y = jnp.einsum("tec,ecd->td", combine.astype(x2.dtype), ye)
        return y.reshape(xs.shape), lax.pmean(aux, all_axes)

    tok = P(all_axes) if x.ndim == 2 else P(all_axes, *([None] * (x.ndim - 1)))
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(axis, None, None), P(axis, None),
                  P(axis, None, None), P(axis, None), tok),
        out_specs=(tok, P()),
        check_vma=False,
    )
    return fn(params["wg"], params["w1"], params["b1"], params["w2"],
              params["b2"], x)


def place_moe_params(params: dict, mesh, axis: str = "expert") -> dict:
    """Device-put expert-stacked weights sharded over ``axis``."""
    from jax.sharding import NamedSharding

    def put(name, v):
        if name == "wg":
            return jax.device_put(v, NamedSharding(mesh, P()))
        spec = P(axis, *([None] * (v.ndim - 1)))
        return jax.device_put(v, NamedSharding(mesh, spec))

    return {k: put(k, v) for k, v in params.items()}
