"""Profiling + MFU accounting.

Reference parity: ``paddle/utils/Stat.h`` RAII timers (see core/stat.py),
``hl_profiler_start/end`` cuda-profiler hooks, and the ``--job=time``
benchmark mode (``paddle/trainer/TrainerBenchmark.cpp``).  TPU-native:
``jax.profiler`` traces for xprof, XLA cost analysis for FLOP counts, and a
step-timing harness that reports model FLOPs utilisation against the
chip's peak — the number SURVEY's north star is phrased in."""

from __future__ import annotations

import contextlib
import time

import jax

from paddle_tpu.core.stat import global_stat

# bf16 peak FLOPs/s per chip (MXU); used when the backend is unknown
_PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,  # v5e
    "tpu v5": 459e12,  # v5p
    "tpu v6 lite": 918e12,
    "cpu": 1e11,
}


def device_peak_flops() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for k, v in _PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return _PEAK_FLOPS["cpu"]


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a jax.profiler trace viewable in xprof/tensorboard
    (hl_profiler_start/end analog)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_annotation(name: str):
    """Named region inside a profile (REGISTER_TIMER analog on-device)."""
    return jax.profiler.TraceAnnotation(name)


def flops_of(fn, *args, **kwargs) -> float:
    """Total FLOPs of one call of jitted ``fn`` via XLA cost analysis."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0]
    return float(cost.get("flops", 0.0))


class BenchmarkResult:
    def __init__(self, seconds_per_step: float, flops_per_step: float,
                 peak_flops: float):
        self.seconds_per_step = seconds_per_step
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops

    @property
    def tflops_per_sec(self) -> float:
        return self.flops_per_step / self.seconds_per_step / 1e12

    @property
    def mfu(self) -> float:
        return (self.flops_per_step / self.seconds_per_step) / self.peak_flops

    def __repr__(self):
        return (f"BenchmarkResult({self.seconds_per_step * 1e3:.2f} ms/step, "
                f"{self.tflops_per_sec:.1f} TFLOP/s, mfu={self.mfu:.1%})")


def _readback(out) -> float:
    """Fetch one scalar from the output — the only reliable execution fence
    (remote/tunneled backends ack block_until_ready without completing)."""
    import jax.numpy as jnp

    for leaf in jax.tree.leaves(out):
        if hasattr(leaf, "dtype"):
            return float(jnp.ravel(leaf)[0])
    return 0.0


def benchmark(fn, args: tuple, iters: int = 50, warmup: int = 3,
              name: str = "benchmark") -> BenchmarkResult:
    """``--job=time`` analog: time jitted ``fn(*args)`` and report ms/step,
    TFLOP/s and MFU.  ``fn`` must be jax-jittable and return arrays.

    Timing is the two-point method: time n1 and n2 pipelined dispatches
    each fenced by a scalar readback, and divide the difference by
    (n2 - n1) — the constant dispatch/readback round-trip (~100 ms through
    a tunneled TPU) cancels out.
    """
    compiled = jax.jit(fn).lower(*args).compile()  # one compile: timing
    cost = compiled.cost_analysis()                # loop + FLOPs share it
    if isinstance(cost, list):
        cost = cost[0]
    flops = float(cost.get("flops", 0.0))
    out = None
    for _ in range(warmup):
        out = compiled(*args)
    _readback(out)

    def run(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = compiled(*args)
        _readback(out)
        return time.perf_counter() - t0

    n1 = max(1, iters // 10)
    n2 = max(iters, n1 + 1)
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    dt = max(t2 - t1, 1e-9) / (n2 - n1)
    global_stat.add(name, dt)
    return BenchmarkResult(dt, flops, device_peak_flops())


# ---- trace-based device timing (tunnel-noise-immune) ------------------------

def read_device_trace(logdir: str):
    """Parse a jax.profiler chrome trace: returns (op_events, module_ms)
    where op_events are the per-HLO-op events of the device's "XLA Ops"
    thread (dur_us, model_flops, raw_bytes_accessed, tf_op, source) and
    module_ms sums the "XLA Modules" thread — the device-side wall time.
    Single implementation shared by device_step_ms and tools/xprof.py."""
    import glob
    import gzip
    import json
    import os

    files = sorted(glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                             recursive=True))
    if not files:
        raise RuntimeError(f"no trace under {logdir}")
    tr = json.load(gzip.open(files[-1]))
    pids, tids = {}, {}
    for e in tr["traceEvents"]:
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                pids[e["pid"]] = e["args"].get("name")
            elif e.get("name") == "thread_name":
                tids[(e["pid"], e["tid"])] = e["args"].get("name")
    events = []
    module_us = 0.0
    for e in tr["traceEvents"]:
        if e.get("ph") != "X" or "TPU" not in (pids.get(e["pid"]) or ""):
            continue
        tname = tids.get((e["pid"], e["tid"]))
        if tname == "XLA Modules":
            module_us += e.get("dur", 0.0)
        elif tname == "XLA Ops":
            a = e.get("args", {})
            events.append({
                "name": e["name"],
                "dur_us": e.get("dur", 0.0),
                "flops": float(a.get("model_flops", 0) or 0),
                "bytes": float(a.get("raw_bytes_accessed", 0) or 0),
                "tf_op": a.get("tf_op", ""),
                "source": a.get("source", ""),
            })
    return events, module_us / 1000.0


def device_step_ms(step_fn, steps: int = 10, warmup: int = 3) -> float:
    """ms/step measured on the DEVICE via a jax.profiler trace — immune to
    the tunnel's host-dispatch noise, which makes two-point wall-clock
    timing unstable below ~10 ms/step.  ``step_fn`` must keep its own state
    and return a readback-able array (the readback fences the trace)."""
    import tempfile

    import numpy as np

    import shutil

    for _ in range(warmup):
        out = step_fn()
    float(np.asarray(out).reshape(-1)[0])
    logdir = tempfile.mkdtemp(prefix="bench_trace_")
    try:
        jax.profiler.start_trace(logdir)
        for _ in range(steps):
            out = step_fn()
        float(np.asarray(out).reshape(-1)[0])
        jax.profiler.stop_trace()
        return read_device_trace(logdir)[1] / steps
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


def step_ms_with_fallback(step_fn, wall_fn, steps: int = 10,
                          warmup: int = 3) -> tuple[float, str, str]:
    """(ms, "device-side"|"wall-clock", reason): try device_step_ms, fall
    back to ``wall_fn()`` (a callable returning ms) when the trace is
    unavailable OR empty (non-TPU backends write traces whose module
    filter matches nothing — a 0.0 must never masquerade as a
    measurement).  The reason string records why the fallback fired."""
    try:
        ms = device_step_ms(step_fn, steps=steps, warmup=warmup)
        if ms > 0:
            return ms, "device-side", ""
        reason = "empty device trace (non-TPU backend?)"
    except Exception as e:
        reason = f"{type(e).__name__}: {e}"[:120]
    return wall_fn(), "wall-clock", reason
