"""Profiling + MFU accounting.

Reference parity: ``paddle/utils/Stat.h`` RAII timers (see core/stat.py),
``hl_profiler_start/end`` cuda-profiler hooks, and the ``--job=time``
benchmark mode (``paddle/trainer/TrainerBenchmark.cpp``).  TPU-native:
``jax.profiler`` traces for xprof, XLA cost analysis for FLOP counts, and a
step-timing harness that reports model FLOPs utilisation against the
chip's peak — the number SURVEY's north star is phrased in."""

from __future__ import annotations

import contextlib
import time

import jax

from paddle_tpu.core.stat import global_stat

# bf16 peak FLOPs/s per chip (MXU); used when the backend is unknown
_PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,  # v5e
    "tpu v5": 459e12,  # v5p
    "tpu v6 lite": 918e12,
    "cpu": 1e11,
}


def device_peak_flops() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for k, v in _PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return _PEAK_FLOPS["cpu"]


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a jax.profiler trace viewable in xprof/tensorboard
    (hl_profiler_start/end analog)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_annotation(name: str):
    """Named region inside a profile (REGISTER_TIMER analog on-device)."""
    return jax.profiler.TraceAnnotation(name)


def flops_of(fn, *args, **kwargs) -> float:
    """Total FLOPs of one call of jitted ``fn`` via XLA cost analysis."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0]
    return float(cost.get("flops", 0.0))


class BenchmarkResult:
    def __init__(self, seconds_per_step: float, flops_per_step: float,
                 peak_flops: float):
        self.seconds_per_step = seconds_per_step
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops

    @property
    def tflops_per_sec(self) -> float:
        return self.flops_per_step / self.seconds_per_step / 1e12

    @property
    def mfu(self) -> float:
        return (self.flops_per_step / self.seconds_per_step) / self.peak_flops

    def __repr__(self):
        return (f"BenchmarkResult({self.seconds_per_step * 1e3:.2f} ms/step, "
                f"{self.tflops_per_sec:.1f} TFLOP/s, mfu={self.mfu:.1%})")


def _readback(out) -> float:
    """Fetch one scalar from the output — the only reliable execution fence
    (remote/tunneled backends ack block_until_ready without completing)."""
    import jax.numpy as jnp

    for leaf in jax.tree.leaves(out):
        if hasattr(leaf, "dtype"):
            return float(jnp.ravel(leaf)[0])
    return 0.0


def benchmark(fn, args: tuple, iters: int = 50, warmup: int = 3,
              name: str = "benchmark") -> BenchmarkResult:
    """``--job=time`` analog: time jitted ``fn(*args)`` and report ms/step,
    TFLOP/s and MFU.  ``fn`` must be jax-jittable and return arrays.

    Timing is the two-point method: time n1 and n2 pipelined dispatches
    each fenced by a scalar readback, and divide the difference by
    (n2 - n1) — the constant dispatch/readback round-trip (~100 ms through
    a tunneled TPU) cancels out.
    """
    compiled = jax.jit(fn).lower(*args).compile()  # one compile: timing
    cost = compiled.cost_analysis()                # loop + FLOPs share it
    if isinstance(cost, list):
        cost = cost[0]
    flops = float(cost.get("flops", 0.0))
    out = None
    for _ in range(warmup):
        out = compiled(*args)
    _readback(out)

    def run(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = compiled(*args)
        _readback(out)
        return time.perf_counter() - t0

    n1 = max(1, iters // 10)
    n2 = max(iters, n1 + 1)
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    dt = max(t2 - t1, 1e-9) / (n2 - n1)
    global_stat.add(name, dt)
    return BenchmarkResult(dt, flops, device_peak_flops())
