"""Layer-graph core: LayerOutput nodes + evaluation context.

The reference builds a ``ModelConfig`` proto from layer-helper calls
(``config_parser.py``), then C++ materializes ``Layer`` objects with
``forward``/``backward`` (``paddle/gserver/layers/Layer.h:62``).  Here each
helper call creates a :class:`LayerOutput` node carrying (a) a config record
(`attrs`, the ModelConfig analog, used for golden-serialization tests), (b)
parameter/state specs, and (c) a pure forward function.  ``backward`` does not
exist anywhere: ``jax.grad`` of the compiled forward is the whole autodiff
story (replacing per-layer backward + ``framework/backward.cc``)."""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Sequence

import jax

from paddle_tpu.core.enforce import enforce, error_scope
from paddle_tpu.core.lod import NestedSequenceBatch, SequenceBatch
from paddle_tpu.core.parameters import ParamSpec

Value = Any  # jax.Array | SequenceBatch | NestedSequenceBatch


@dataclasses.dataclass(frozen=True)
class StateSpec:
    """Non-trainable persistent state (BN moving stats, etc.)."""

    name: str
    shape: tuple[int, ...]
    init_value: float = 0.0
    dtype: Any = None


class Context:
    """Per-step evaluation context: train/test mode + per-layer RNG keys."""

    def __init__(self, is_train: bool, key: jax.Array | None = None):
        self.is_train = is_train
        self._key = key

    def key_for(self, name: str) -> jax.Array:
        enforce(self._key is not None, f"layer {name!r} needs an RNG key")
        # deterministic per-layer stream derived from the step key (crc32 so
        # runs are replayable across processes, unlike salted hash())
        import zlib

        h = zlib.crc32(name.encode()) & 0x7FFFFFFF
        return jax.random.fold_in(self._key, h)


_name_counters: dict[str, itertools.count] = {}

# every LayerOutput registers here at construction, in creation order — the
# analog of config_parser's g_layer_map/g_config.model_config.layers, which
# appends a LayerConfig per helper call.  Proto emission walks this (not the
# DFS order) so protostr layer ordering matches the reference byte-for-byte.
# Strong references on purpose: nodes are frequently created inline
# (``outputs(classification_cost(...))``) with no other owner, and emission
# must still see them.  Like the reference's ``g_config`` globals, the
# registry grows until ``reset_name_counters()`` — which every model builder
# and ``parse_config`` call first (≅ ``init_config_environment``).
_layer_registry: list["LayerOutput"] = []


def layer_registry() -> list["LayerOutput"]:
    return list(_layer_registry)


def gen_name(layer_type: str) -> str:
    c = _name_counters.setdefault(layer_type, itertools.count())
    return f"__{layer_type}_{next(c)}__"


def reset_name_counters() -> None:
    _name_counters.clear()
    _layer_registry.clear()
    # config-level g_default_* must not outlive the model build they were
    # declared in (every model builder/test resets counters first)
    from paddle_tpu.config import parse_state

    parse_state.reset_defaults()


@dataclasses.dataclass(eq=False)
class LayerOutput:
    """A node in the layer DAG (≅ v2 ``LayerOutput`` over a LayerConfig)."""

    name: str
    layer_type: str
    size: int  # output feature size (v2 `size` semantics); 0 if n/a
    parents: tuple["LayerOutput", ...] = ()
    param_specs: tuple[ParamSpec, ...] = ()
    state_specs: tuple[StateSpec, ...] = ()
    fn: Callable | None = None  # (ctx, params, states, *parent_values) -> value | (value, states)
    attrs: dict = dataclasses.field(default_factory=dict)
    # height/width for image layers (ModelConfig LayerConfig.height/width)
    height: int = 0
    width: int = 0
    depth: int = 1  # channels for image layers

    def __post_init__(self):
        _layer_registry.append(self)

    def config_record(self) -> dict:
        """Serializable config (the ModelConfig-protostr analog for golden tests)."""
        return {
            "name": self.name,
            "type": self.layer_type,
            "size": self.size,
            "inputs": [p.name for p in self.parents],
            "attrs": {k: v for k, v in sorted(self.attrs.items()) if _jsonable(v)},
            "params": [
                {"name": s.name, "shape": list(s.shape)} for s in self.param_specs
            ],
        }

    def __repr__(self):
        return f"LayerOutput({self.name}, type={self.layer_type}, size={self.size})"


def _jsonable(v) -> bool:
    if isinstance(v, (int, float, str, bool, type(None))):
        return True
    if isinstance(v, (list, tuple)):
        # containers must be jsonable all the way down (runtime attrs
        # like __emit_parent_nodes__ hold LayerOutput objects)
        return all(_jsonable(x) for x in v)
    return False


def topo_sort(outputs: Sequence[LayerOutput]) -> list[LayerOutput]:
    """Deterministic post-order DFS over parents (≅ config_parser's layer
    ordering; NeuralNetwork executes layers in config order)."""
    seen: dict[int, LayerOutput] = {}
    order: list[LayerOutput] = []

    def visit(node: LayerOutput, stack: set[int]):
        nid = id(node)
        if nid in seen:
            return
        enforce(nid not in stack, f"cycle in layer graph at {node.name!r}")
        stack.add(nid)
        for p in node.parents:
            visit(p, stack)
        stack.remove(nid)
        seen[nid] = node
        order.append(node)

    for out in outputs:
        visit(out, set())
    return order


def evaluate(
    nodes: Sequence[LayerOutput],
    ctx: Context,
    params: dict[str, jax.Array],
    states: dict[str, jax.Array],
    feed: dict[str, Value],
    taps: dict[str, jax.Array] | None = None,
) -> tuple[dict[str, Value], dict[str, jax.Array]]:
    """Evaluate the DAG once; returns ({layer_name: value}, new_states).

    ``taps`` adds a zero-valued array to the named layers' outputs; taking
    jax.grad of a cost w.r.t. the tap yields d(cost)/d(layer) — the
    mechanism behind gradient_printer_evaluator (GradientPrinter's backward
    hook in the reference)."""
    values: dict[str, Value] = {}
    new_states = dict(states)
    for node in topo_sort(nodes):
        if node.fn is None and node.name in feed:
            # leaves only — data layers and injected recurrent_group leaves
            # (placeholders, memories).  Computed layers (fn set) are never
            # shadowed by a same-named feed key.
            values[node.name] = feed[node.name]
            continue
        if node.layer_type == "data":
            enforce(node.name in feed, f"missing feed for data layer {node.name!r}")
            values[node.name] = feed[node.name]
            continue
        parent_vals = [values[p.name] for p in node.parents]
        pvals = {s.name: params[s.name] for s in node.param_specs}
        svals = {s.name: new_states[s.name] for s in node.state_specs}
        with error_scope(node.name):
            result = node.fn(ctx, pvals, svals, *parent_vals)
        if isinstance(result, tuple) and len(result) == 2 and isinstance(result[1], dict):
            value, supd = result
            new_states.update(supd)
        else:
            value = result
        if taps and node.name in taps:
            tap = taps[node.name]
            if isinstance(value, SequenceBatch):
                value = SequenceBatch(data=value.data + tap,
                                      length=value.length)
            else:
                value = value + tap
        values[node.name] = value
    return values, new_states


# ---- value helpers shared by layer impls -----------------------------------


IDS_SUFFIX = "#ids"  # dual-output companions (crf_decoding's path side)


def companion_name(name: str) -> str:
    """Hidden runtime-only companion carrying a layer's ids side (the
    reference Argument's value/ids duality)."""
    return name + IDS_SUFFIX


def is_sequence(v: Value) -> bool:
    return isinstance(v, SequenceBatch)


def is_nested_sequence(v: Value) -> bool:
    return isinstance(v, NestedSequenceBatch)


def raw(v: Value):
    """Underlying dense array."""
    if isinstance(v, (SequenceBatch, NestedSequenceBatch)):
        return v.data
    return v


def map_data(fn: Callable, v: Value) -> Value:
    """Apply fn to the dense data, preserving sequence metadata.  This is how
    per-timestep layers (fc, mixed, activation...) act on sequence input, like
    the reference running them over the flattened [sum_len, D] Argument."""
    if isinstance(v, SequenceBatch):
        return SequenceBatch(data=fn(v.data), length=v.length)
    if isinstance(v, NestedSequenceBatch):
        return NestedSequenceBatch(
            data=fn(v.data), seq_length=v.seq_length, sub_length=v.sub_length
        )
    return fn(v)


def like(v: Value, data) -> Value:
    if isinstance(v, SequenceBatch):
        return SequenceBatch(data=data, length=v.length)
    if isinstance(v, NestedSequenceBatch):
        return NestedSequenceBatch(
            data=data, seq_length=v.seq_length, sub_length=v.sub_length
        )
    return data
