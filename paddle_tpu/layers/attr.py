"""Attribute objects — successor of ``trainer_config_helpers/attrs.py``
(ParameterAttribute / ExtraLayerAttribute): per-parameter init, LR scale,
decay, sparsity, and per-layer dropout/device hints."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class ParamAttr:
    """≅ ParameterAttribute (attrs.py): controls one parameter's init/decay/LR."""

    name: str | None = None  # share parameters by giving two layers one name
    is_static: bool = False
    initial_std: float | None = None
    initial_mean: float | None = None
    initial_max: float | None = None  # uniform bounds
    initial_min: float | None = None
    learning_rate: float = 1.0
    l2_rate: float | None = None  # per-param decay override
    sparse_update: bool = False
    # update_hooks ≅ HookAttribute("pruning", sparsity_ratio)
    sparsity_ratio: float | None = None
    gradient_clipping_threshold: float | None = None
    initializer: Callable | None = None  # direct override
    # mesh axis name (or None) per weight dim — tensor-parallel sharding over
    # the pjit mesh; the capability upgrade over the reference's per-layer
    # device placement (ParallelNeuralNetwork.h:34 deviceId pinning)
    sharding: tuple | None = None

    def make_initializer(self, default: Callable) -> Callable:
        from paddle_tpu.core import initializer as I

        if self.initializer is not None:
            return self.initializer
        if self.initial_max is not None or self.initial_min is not None:
            lo = self.initial_min if self.initial_min is not None else -1.0
            hi = self.initial_max if self.initial_max is not None else 1.0
            return I.uniform(lo, hi)
        if self.initial_std is not None or self.initial_mean is not None:
            return I.paddle_default(self.initial_mean or 0.0, self.initial_std)
        return default


ParameterAttribute = ParamAttr  # reference alias


@dataclasses.dataclass
class ExtraAttr:
    """≅ ExtraLayerAttribute: layer-level knobs (dropout etc.)."""

    drop_rate: float = 0.0
    device: int | None = None  # kept for API compat; sharding supersedes it
    error_clipping_threshold: float | None = None


ExtraLayerAttribute = ExtraAttr

# v2 API aliases (python/paddle/v2/attr.py: Param / Extra / ParameterAttribute)
Param = ParamAttr
ParameterAttribute = ParamAttr
Extra = ExtraAttr


def param_attr_or_default(attr: ParamAttr | None) -> ParamAttr:
    return attr if attr is not None else ParamAttr()


def to_kwargs(obj: Any) -> dict:
    return dataclasses.asdict(obj) if obj is not None else {}
