"""Attribute objects — successor of ``trainer_config_helpers/attrs.py``
(ParameterAttribute / ExtraLayerAttribute): per-parameter init, LR scale,
decay, sparsity, and per-layer dropout/device hints."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class ParamAttr:
    """≅ ParameterAttribute (attrs.py): controls one parameter's init/decay/LR."""

    name: str | None = None  # share parameters by giving two layers one name
    is_static: bool = False
    initial_std: float | None = None
    initial_mean: float | None = None
    initial_max: float | None = None  # uniform bounds
    initial_min: float | None = None
    learning_rate: float | None = None  # None ⇒ global LR (scale 1)
    l1_rate: float | None = None  # per-param L1 decay (decay_rate_l1)
    l2_rate: float | None = None  # per-param decay override
    momentum: float | None = None  # per-param momentum (proto surface only)
    sparse_update: bool = False
    # update_hooks ≅ HookAttribute("pruning", sparsity_ratio)
    sparsity_ratio: float | None = None
    gradient_clipping_threshold: float | None = None
    initializer: Callable | None = None  # direct override
    # mesh axis name (or None) per weight dim — tensor-parallel sharding over
    # the pjit mesh; the capability upgrade over the reference's per-layer
    # device placement (ParallelNeuralNetwork.h:34 deviceId pinning)
    sharding: tuple | None = None

    def proto_fields(self) -> dict:
        """ParameterConfig-bound fields, with reference
        ``ParameterAttribute.__init__`` semantics (attrs.py:139-210): nothing
        set ⇒ smart init; std/mean ⇒ gauss (strategy 0); max/min ⇒ uniform
        (strategy 1) with derived mean/std."""
        d: dict = {}
        if self.is_static:
            d["is_static"] = True
        if (
            self.initial_std is None
            and self.initial_mean is None
            and self.initial_max is None
            and self.initial_min is None
        ):
            d["initial_smart"] = True
        elif self.initial_std is not None or self.initial_mean is not None:
            if self.initial_std is not None:
                d["initial_std"] = self.initial_std
            if self.initial_mean is not None:
                d["initial_mean"] = self.initial_mean
            d["initial_strategy"] = 0
        else:
            # tolerate one-sided bounds like make_initializer does
            lo = -1.0 if self.initial_min is None else self.initial_min
            hi = 1.0 if self.initial_max is None else self.initial_max
            mean = (hi + lo) / 2
            d["initial_mean"] = mean
            d["initial_std"] = mean - lo
            d["initial_strategy"] = 1
        if not self.is_static:
            if self.l1_rate is not None:
                d["decay_rate_l1"] = self.l1_rate
            if self.l2_rate is not None:
                d["decay_rate"] = self.l2_rate
            if self.learning_rate is not None:
                d["learning_rate"] = self.learning_rate
            if self.momentum is not None:
                d["momentum"] = self.momentum
        if self.sparse_update:
            d["sparse_update"] = True
            d["sparse_remote_update"] = True
        if self.gradient_clipping_threshold is not None:
            d["gradient_clipping_threshold"] = self.gradient_clipping_threshold
        if self.sparsity_ratio is not None:
            d["update_hooks"] = [("pruning", self.sparsity_ratio)]
        return d

    def make_initializer(self, default: Callable) -> Callable:
        from paddle_tpu.core import initializer as I

        if self.initializer is not None:
            return self.initializer
        if self.initial_max is not None or self.initial_min is not None:
            lo = self.initial_min if self.initial_min is not None else -1.0
            hi = self.initial_max if self.initial_max is not None else 1.0
            return I.uniform(lo, hi)
        # config-level defaults (default_initial_std()/default_initial_mean()/
        # default_initial_strategy(), ≅ config_parser g_default_*).  Read at
        # LAYER BUILD time (this method runs during config parsing); the
        # dict resets on every parse_config AND on reset_name_counters(),
        # so stale config defaults cannot leak into later model builds.
        from paddle_tpu.config import parse_state as _ps

        gd = _ps.G_DEFAULTS
        mean = (self.initial_mean if self.initial_mean is not None
                else gd["initial_mean"])
        std = (self.initial_std if self.initial_std is not None
               else gd["initial_std"])
        if gd["initial_strategy"] == 1:
            # uniform over (mean - std, mean + std)
            # (ParameterConfig.proto:51-53; config_parser.py:3920 applies
            # the global strategy to per-attr std/mean too)
            m = 0.0 if mean is None else mean
            s_ = 0.01 if std is None else std  # g_default_initial_std
            return I.uniform(m - s_, m + s_)
        if std is not None or mean is not None:
            return I.paddle_default(mean or 0.0, std)
        return default


ParameterAttribute = ParamAttr  # reference alias


@dataclasses.dataclass
class ExtraAttr:
    """≅ ExtraLayerAttribute: layer-level knobs (dropout etc.)."""

    drop_rate: float = 0.0
    device: int | None = None  # kept for API compat; sharding supersedes it
    error_clipping_threshold: float | None = None


ExtraLayerAttribute = ExtraAttr

# v2 API aliases (python/paddle/v2/attr.py: Param / Extra / ParameterAttribute)
Param = ParamAttr
ParameterAttribute = ParamAttr
Extra = ExtraAttr


def param_attr_or_default(attr: ParamAttr | None) -> ParamAttr:
    return attr if attr is not None else ParamAttr()


def to_kwargs(obj: Any) -> dict:
    return dataclasses.asdict(obj) if obj is not None else {}
