"""Pooling-type objects — successor of ``trainer_config_helpers/poolings.py``
(MaxPooling/AvgPooling/SumPooling/SqrtAvgPooling passed to ``pooling_layer``)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class BasePoolingType:
    name: str


class MaxPooling(BasePoolingType):
    def __init__(self, output_max_index: bool | None = None):
        super().__init__("max")
        object.__setattr__(self, "output_max_index", output_max_index)


class AvgPooling(BasePoolingType):
    def __init__(self):
        super().__init__("average")


class SumPooling(BasePoolingType):
    def __init__(self):
        super().__init__("sum")


class SqrtAvgPooling(BasePoolingType):
    """Sum scaled by 1/sqrt(len) — reference 'average-sqrt' mode."""

    def __init__(self):
        super().__init__("sqrt")


class CudnnMaxPooling(MaxPooling):  # API-compat aliases (no cudnn on TPU)
    pass


class CudnnAvgPooling(AvgPooling):
    pass


def get(p) -> str:
    if p is None:
        return "max"
    if isinstance(p, str):
        return p
    return p.name
