"""Remaining gserver layer types — completes the registry parity sweep
(reference REGISTER_LAYER list): prelu, multiplex, tensor (bilinear),
selective_fc, data_norm, resize, conv_shift, scale_shift,
scale_sub_region, sub_nested_seq, soft_binary_class_cross_entropy,
3-D conv/pool, print, gated_recurrent alias."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dt

from paddle_tpu.core import initializer as I
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import NestedSequenceBatch, SequenceBatch
from paddle_tpu.layers import activation as act_mod
from paddle_tpu.layers.api import _cost_node, _wspec
from paddle_tpu.layers.attr import ParamAttr
from paddle_tpu.layers.base import LayerOutput, gen_name, is_sequence, raw


def prelu(input: LayerOutput, partial_sum: int = 1, param_attr=None,
          name: str | None = None) -> LayerOutput:
    """≅ prelu (PReluLayer): y = x>0 ? x : a*x with learned slope ``a``;
    ``partial_sum`` groups channels sharing one slope (1 = per-element)."""
    name = name or gen_name("prelu_layer")
    n_slopes = input.size // partial_sum
    w = _wspec(param_attr, name, "w0", (n_slopes,), I.constant(0.25))

    def fwd(ctx, params, states, x):
        v = raw(x)
        a = jnp.repeat(params[w.name], partial_sum)
        if v.ndim == 4:  # NHWC feature map: apply in CHW order, like the ref
            b, h, w_, c = v.shape
            flat = v.transpose(0, 3, 1, 2).reshape(b, -1)
            out = jnp.where(flat > 0, flat, flat * a)
            return out.reshape(b, c, h, w_).transpose(0, 2, 3, 1)
        return jnp.where(v > 0, v, v * a)

    return LayerOutput(name=name, layer_type="prelu", size=input.size,
                       parents=(input,), param_specs=(w,), fn=fwd,
                       attrs={"partial_sum": partial_sum},
                       height=input.height, width=input.width,
                       depth=input.depth)


def multiplex(input: list[LayerOutput], name: str | None = None) -> LayerOutput:
    """≅ multiplex (MultiplexLayer): input[0] holds per-row indices k;
    output row i = input[k_i + 1] row i."""
    name = name or gen_name("multiplex_layer")
    enforce(len(input) >= 3, "multiplex needs an index layer + >=2 choices")
    size = input[1].size

    def fwd(ctx, params, states, idx, *choices):
        k = raw(idx).reshape(-1).astype(jnp.int32)
        stacked = jnp.stack([raw(c) for c in choices], axis=0)  # [N, B, D]
        return jnp.take_along_axis(
            stacked, k[None, :, None], axis=0
        )[0]

    return LayerOutput(name=name, layer_type="multiplex", size=size,
                       parents=tuple(input), fn=fwd)


def tensor_layer(a: LayerOutput, b: LayerOutput, size: int, act=None,
                 param_attr=None, bias_attr=None,
                 name: str | None = None) -> LayerOutput:
    """≅ tensor (TensorLayer): bilinear form y_i = a W_i b^T for i<size."""
    name = name or gen_name("tensor_layer")
    w = _wspec(param_attr, name, "w0", (size, a.size, b.size), I.xavier())
    specs = [w]
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(None, name, "wbias", (size,), I.constant(0.0))
        specs.append(bspec)
    activation = act_mod.get(act) if act is not None else act_mod.LinearActivation()

    def fwd(ctx, params, states, xa, xb):
        y = jnp.einsum("bm,imn,bn->bi", raw(xa), params[w.name], raw(xb),
                       precision=dt.dot_precision(raw(xa), params[w.name]))
        if use_bias:
            y = y + params[bspec.name]
        return activation(y)

    return LayerOutput(name=name, layer_type="tensor", size=size,
                       parents=(a, b), param_specs=tuple(specs), fn=fwd)


def selective_fc(input: LayerOutput, select: LayerOutput, size: int,
                 act=None, param_attr=None, bias_attr=None,
                 name: str | None = None) -> LayerOutput:
    """≅ selective_fc (SelectiveFullyConnectedLayer): fc whose output is
    masked to the columns flagged by ``select`` (a [B, size] 0/1 layer);
    unselected outputs are zero.  TPU-style: the full gemm runs on the MXU
    and the mask applies after — dense beats gather here."""
    name = name or gen_name("selective_fc_layer")
    inputs = input if isinstance(input, (list, tuple)) else [input]
    specs = [
        _wspec(param_attr, name, f"w{i}", (inp.size, size), I.xavier())
        for i, inp in enumerate(inputs)
    ]
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(
            bias_attr if isinstance(bias_attr, ParamAttr) else None,
            name, "wbias", (size,), I.constant(0.0))
        specs.append(bspec)
    activation = act_mod.get(act) if act is not None else act_mod.TanhActivation()

    def fwd(ctx, params, states, *vals):
        xs, sel = vals[:-1], vals[-1]
        y = sum(raw(x) @ params[s.name] for x, s in zip(xs, specs))
        if use_bias:
            y = y + params[bspec.name]
        return activation(y) * raw(sel)

    return LayerOutput(name=name, layer_type="selective_fc", size=size,
                       parents=tuple(inputs) + (select,),
                       param_specs=tuple(specs), fn=fwd,
                       attrs={"active_type": activation.name})


def data_norm(input: LayerOutput, strategy: str = "z-score",
              param_attr=None, name: str | None = None) -> LayerOutput:
    """≅ data_norm (DataNormLayer): normalize features with STATIC
    population statistics carried as non-trainable parameters
    (sum/squared-sum/count rows, as the reference stores them)."""
    name = name or gen_name("data_norm")
    # rows: [sum, squared_sum, count, min, max] like the reference's 5xD
    w = _wspec(param_attr, name, "w", (5, input.size), I.constant(0.0),
               is_static=True)

    def fwd(ctx, params, states, x):
        stats = params[w.name]
        s, sq, cnt, mn, mx = stats[0], stats[1], stats[2], stats[3], stats[4]
        n = jnp.maximum(cnt, 1.0)
        mean = s / n
        v = raw(x)
        if strategy == "z-score":
            var = jnp.maximum(sq / n - mean ** 2, 1e-8)
            return (v - mean) / jnp.sqrt(var)
        if strategy == "min-max":
            return (v - mn) / jnp.maximum(mx - mn, 1e-8)
        return v / jnp.maximum(jnp.abs(mx), 1.0)  # decimal-scaling

    return LayerOutput(name=name, layer_type="data_norm", size=input.size,
                       parents=(input,), param_specs=(w,), fn=fwd,
                       attrs={"strategy": strategy})


def resize(input: LayerOutput, size: int, name: str | None = None) -> LayerOutput:
    """≅ resize (ResizeLayer): reinterpret the batch as rows of ``size``."""
    name = name or gen_name("resize")

    def fwd(ctx, params, states, x):
        return raw(x).reshape(-1, size)

    return LayerOutput(name=name, layer_type="resize", size=size,
                       parents=(input,), fn=fwd)


def clip(input: LayerOutput, min: float, max: float,
         name: str | None = None) -> LayerOutput:
    """≅ clip_layer (ClipLayer, LayerConfig.clip_conf)."""
    name = name or gen_name("clip")
    lo, hi = min, max

    def fwd(ctx, params, states, x):
        from paddle_tpu.layers.base import map_data

        return map_data(lambda d: jnp.clip(d, float(lo), float(hi)), x)

    return LayerOutput(name=name, layer_type="clip", size=input.size,
                       parents=(input,), fn=fwd,
                       attrs={"clip_min": lo, "clip_max": hi})


clip_layer = clip


def conv_shift(a: LayerOutput, b: LayerOutput,
               name: str | None = None) -> LayerOutput:
    """≅ conv_shift (ConvShiftLayer): circular convolution of each row of
    ``a`` with the (odd-length) kernel row of ``b`` — the NTM shift op."""
    name = name or gen_name("conv_shift_layer")

    def fwd(ctx, params, states, xa, xb):
        va, vb = raw(xa), raw(xb)
        m = vb.shape[-1] // 2
        idx = (jnp.arange(va.shape[-1])[:, None]
               + jnp.arange(-m, m + 1)[None, :]) % va.shape[-1]
        return jnp.einsum("bnk,bk->bn", va[:, idx], vb,
                          precision=dt.dot_precision(va, vb))

    return LayerOutput(name=name, layer_type="conv_shift", size=a.size,
                       parents=(a, b), fn=fwd)


def scale_shift(input: LayerOutput, param_attr=None, bias_attr=None,
                name: str | None = None) -> LayerOutput:
    """≅ scale_shift (ScaleShiftLayer): y = w * x + b with SCALAR w, b."""
    name = name or gen_name("scale_shift")
    w = _wspec(param_attr, name, "w0", (1,), I.constant(1.0))
    specs = [w]
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(None, name, "wbias", (1,), I.constant(0.0))
        specs.append(bspec)

    def fwd(ctx, params, states, x):
        y = raw(x) * params[w.name]
        if use_bias:
            y = y + params[bspec.name]
        return y

    return LayerOutput(name=name, layer_type="scale_shift", size=input.size,
                       parents=(input,), param_specs=tuple(specs), fn=fwd)


def scale_sub_region(input: LayerOutput, indices: LayerOutput, value: float,
                     name: str | None = None) -> LayerOutput:
    """≅ scale_sub_region: scale a [c1:c2, h1:h2, w1:w2] box of each CHW
    image by ``value``; indices rows are [c1, c2, h1, h2, w1, w2]
    (1-based inclusive, like the reference)."""
    name = name or gen_name("scale_sub_region")
    c, h, w_ = input.depth, input.height, input.width

    def fwd(ctx, params, states, x, idx):
        v = raw(x)
        nhwc = v.ndim == 4  # conv/pool outputs; flat rows are CHW
        if nhwc:
            v = v.transpose(0, 3, 1, 2)
        else:
            v = v.reshape(-1, c, h, w_)
        ix = raw(idx).astype(jnp.int32)  # [B, 6]
        ci = jnp.arange(c)[None, :, None, None]
        hi = jnp.arange(h)[None, None, :, None]
        wi = jnp.arange(w_)[None, None, None, :]
        inside = (
            (ci >= ix[:, 0, None, None, None] - 1)
            & (ci <= ix[:, 1, None, None, None] - 1)
            & (hi >= ix[:, 2, None, None, None] - 1)
            & (hi <= ix[:, 3, None, None, None] - 1)
            & (wi >= ix[:, 4, None, None, None] - 1)
            & (wi <= ix[:, 5, None, None, None] - 1)
        )
        out = jnp.where(inside, v * value, v)
        if nhwc:
            return out.transpose(0, 2, 3, 1)
        return out.reshape(out.shape[0], -1)

    return LayerOutput(name=name, layer_type="scale_sub_region",
                       size=input.size, parents=(input, indices), fn=fwd,
                       attrs={"value": value, "channels": c},
                       height=h, width=w_, depth=c)


def sub_nested_seq(input: LayerOutput, selection: LayerOutput = None,
                   name: str | None = None,
                   selected_indices: LayerOutput = None) -> LayerOutput:
    """≅ sub_nested_seq (SubNestedSequenceLayer): from each nested sequence,
    keep the sub-sequence whose index the selection row gives, producing an
    ordinary sequence batch."""
    name = name or gen_name("sub_nested_seq_layer")
    if selection is None:
        selection = selected_indices

    def fwd(ctx, params, states, x, sel):
        enforce(isinstance(x, NestedSequenceBatch),
                "sub_nested_seq expects a nested sequence input")
        k = raw(sel).reshape(-1).astype(jnp.int32)  # [B]
        b = k.shape[0]
        rows = x.data[jnp.arange(b), k]  # [B, T, ...]
        lens = x.sub_length[jnp.arange(b), k]
        return SequenceBatch(data=rows, length=lens)

    return LayerOutput(name=name, layer_type="sub_nested_seq",
                       size=input.size, parents=(input, selection), fn=fwd,
                       attrs={"dfs_parents": (input,)})


def soft_binary_class_cross_entropy(input: LayerOutput, label: LayerOutput,
                                    coeff: float = 1.0,
                                    name: str | None = None) -> LayerOutput:
    """≅ soft_binary_class_cross_entropy: BCE against SOFT target
    probabilities in [0,1] per output unit."""
    name = name or gen_name("soft_binary_class_cross_entropy")

    def fwd(ctx, params, states, p, t):
        prob = jnp.clip(raw(p), 1e-7, 1 - 1e-7)
        tv = raw(t)
        ce = -(tv * jnp.log(prob) + (1 - tv) * jnp.log(1 - prob))
        return coeff * jnp.mean(jnp.sum(ce, axis=-1))

    return _cost_node(name, "soft_binary_class_cross_entropy",
                      (input, label), fwd)


class BeamInput:
    """One beam expansion for cross_entropy_over_beam (layers.py:6026):
    (candidate_scores, selected_candidates, gold)."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input, name: str | None = None) -> LayerOutput:
    """≅ cross_entropy_over_beam (CrossEntropyOverBeam.cpp): cross entropy
    over the candidates of a sequence of beam expansions — softmax over each
    expansion's candidate scores with the gold's slot as the target (a
    beam-search-aware training loss)."""
    name = name or gen_name("cross_entropy_over_beam")
    beams = list(input)
    enforce(all(isinstance(b, BeamInput) for b in beams),
            "cross_entropy_over_beam takes BeamInput objects")
    parents = []
    for b in beams:
        parents += [b.candidate_scores, b.selected_candidates, b.gold]

    def fwd(ctx, params, states, *vals):
        total = None
        for k in range(len(beams)):
            scores, sel, gold = vals[3 * k: 3 * k + 3]
            sv = raw(scores)
            if is_sequence(scores):
                sv = sv[..., 0] if sv.ndim == 3 else sv  # [B, T]
            sel_i = raw(sel).astype(jnp.int32)  # [B, K]
            cand = jnp.take_along_axis(sv, jnp.clip(sel_i, 0), axis=-1)
            logp = jax.nn.log_softmax(cand, axis=-1)  # [B, K]
            g = raw(gold).reshape(-1, 1).astype(jnp.int32)
            hit = (sel_i == g)  # gold's slot among the selected candidates
            found = jnp.any(hit, axis=-1)
            ce = -jnp.sum(jnp.where(hit, logp, 0.0), axis=-1)
            ce = jnp.where(found, ce, -jnp.log(1e-10))
            total = ce if total is None else total + ce
        return jnp.mean(total)

    return LayerOutput(name=name, layer_type="cross_entropy_over_beam",
                       size=0, parents=tuple(parents), fn=fwd)


def gated_unit(input, size, act=None, name=None, gate_attr=None,
               gate_param_attr=None, gate_bias_attr=True, inproj_attr=None,
               inproj_param_attr=None, inproj_bias_attr=True,
               layer_attr=None):
    """≅ gated_unit_layer (layers.py:6412): GLU = fc(act) ⊙ sigmoid-fc,
    composed exactly like the reference (input_proj + gate fc layers, then
    a mixed layer with a dotmul operator)."""
    from paddle_tpu.layers.api import fc_layer
    from paddle_tpu.layers.base import gen_name
    from paddle_tpu.layers.mixed import dotmul_operator, mixed_layer

    name = name or gen_name("gated_unit_layer")
    input_proj = fc_layer(
        input=input, name=f"{name}_input_proj", size=size, act=act,
        layer_attr=inproj_attr, param_attr=inproj_param_attr,
        bias_attr=inproj_bias_attr)
    gate = fc_layer(
        input=input, name=f"{name}_gate", size=size,
        act=act_mod.SigmoidActivation(), layer_attr=gate_attr,
        param_attr=gate_param_attr, bias_attr=gate_bias_attr)
    return mixed_layer(
        name=f"{name}_gated_act",
        input=dotmul_operator(input_proj, gate),
        layer_attr=layer_attr)


gated_unit_layer = gated_unit


def print_layer(input: LayerOutput, format: str | None = None,
                name: str | None = None) -> LayerOutput:
    """≅ print (PrintLayer): debug-print the value each step (jax.debug);
    passes its input through unchanged."""
    name = name or gen_name("print")

    def fwd(ctx, params, states, x):
        v = raw(x)
        jax.debug.print((format or (name + ": {}")).replace("%s", "{}"), v)
        return v

    return LayerOutput(name=name, layer_type="print", size=input.size,
                       parents=(input,), fn=fwd, height=input.height,
                       width=input.width, depth=input.depth,
                       attrs={"user_arg": format or ("layer=" +
                              input.name + " %s")})


# registry aliases: the reference registers these as distinct layer types,
# but they are parameterizations of existing layers here
def gated_recurrent(*args, **kwargs):
    """≅ gated_recurrent (GatedRecurrentLayer) — the grumemory layer."""
    from paddle_tpu.layers.api import grumemory

    return grumemory(*args, **kwargs)


def crf_error(input, label, size=None, param_attr=None, name=None):
    """≅ crf_error (CRFDecodingLayer with label): per-sequence 0/1 decode
    error — crf_decoding given a label."""
    from paddle_tpu.layers.extras import crf_decoding

    return crf_decoding(input=input, size=size, label=label,
                        param_attr=param_attr, name=name)


def _triple(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v, v)


def img_conv3d(input: LayerOutput, filter_size, num_filters: int,
               num_channels: int | None = None, img_size=None,
               stride=1, padding=0, act=None, param_attr=None,
               bias_attr=None, trans: bool = False, groups: int = 1,
               shared_biases: bool = True, layer_type: str | None = None,
               layer_attr=None, name: str | None = None) -> LayerOutput:
    """≅ img_conv3d_layer (conv3d/deconv3d, Conv3DLayer/DeConv3DLayer):
    NDHWC volumes.  v1 list args are (x, y, z) order; the volume comes from
    ``img_size=(d, h, w)``, the input's explicit depth/height/width, or a
    preceding 3-D layer."""
    from jax import lax as _lax

    name = name or gen_name("conv3d" if not trans else "deconv3d")
    kw, kh, kd = _triple(filter_size)  # v1 order: (x, y, z)
    sw, sh, sd = _triple(stride)
    pw, ph, pd = _triple(padding)
    enforce(groups == 1, "img_conv3d: grouped 3-D conv not supported")
    c_in = (num_channels or input.attrs.get("num_filters")
            or input.attrs.get("channels") or 1)
    img_size = img_size or input.attrs.get("out_vol")
    if img_size is None and input.attrs.get("explicit_depth"):
        img_size = (input.depth, input.height, input.width)
    enforce(img_size is not None, "img_conv3d needs img_size=(d, h, w)")
    d_in, h_in, w_in = img_size
    if trans:
        d_out = (d_in - 1) * sd + kd - 2 * pd
        h_out = (h_in - 1) * sh + kh - 2 * ph
        w_out = (w_in - 1) * sw + kw - 2 * pw
    else:
        d_out = (d_in + 2 * pd - kd) // sd + 1
        h_out = (h_in + 2 * ph - kh) // sh + 1
        w_out = (w_in + 2 * pw - kw) // sw + 1
    w = _wspec(param_attr, name, "w0", (kd, kh, kw, c_in, num_filters),
               I.msra())
    specs = [w]
    use_bias = bias_attr is not False
    if use_bias:
        b = _wspec(bias_attr if not isinstance(bias_attr, bool) else None,
                   name, "wbias", (num_filters,), I.constant(0.0))
        specs.append(b)
    activation = act_mod.get(act) if act is not None else act_mod.ReluActivation()

    def fwd(ctx, params, states, x):
        v = raw(x)
        if v.ndim == 2:
            v = v.reshape(-1, c_in, d_in, h_in, w_in).transpose(0, 2, 3, 4, 1)
        if trans:
            y = _lax.conv_transpose(
                v, params[w.name].transpose(0, 1, 2, 4, 3),
                strides=(sd, sh, sw),
                padding=[(kd - 1 - pd,) * 2, (kh - 1 - ph,) * 2,
                         (kw - 1 - pw,) * 2],
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
                transpose_kernel=True,
                precision=dt.dot_precision(v, params[w.name]))
        else:
            y = _lax.conv_general_dilated(
                v, params[w.name], window_strides=(sd, sh, sw),
                padding=[(pd, pd), (ph, ph), (pw, pw)],
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
                precision=dt.dot_precision(v, params[w.name]))
        if use_bias:
            y = y + params[b.name]
        return activation(y)

    node = LayerOutput(
        name=name, layer_type="deconv3d" if trans else "conv3d",
        size=num_filters * d_out * h_out * w_out, parents=(input,),
        param_specs=tuple(specs), fn=fwd,
        height=h_out, width=w_out, depth=d_out,
        attrs={"out_vol": [d_out, h_out, w_out],
               "active_type": activation.name,
               "channels": c_in, "num_filters": num_filters,
               "filter_size": (kw, kh, kd), "stride": (sw, sh, sd),
               "padding": (pw, ph, pd), "groups": groups, "trans": trans,
               "img_vol": (d_in, h_in, w_in),
               "shared_biases": shared_biases},
    )
    return node


img_conv3d_layer = img_conv3d


def img_pool3d(input: LayerOutput, pool_size, img_size=None,
               num_channels: int | None = None, stride=None, padding=0,
               pool_type="max", layer_attr=None,
               name: str | None = None) -> LayerOutput:
    """≅ img_pool3d_layer (pool3d, Pool3DLayer): max/avg pooling over NDHWC
    volumes.  v1 list args are (x, y, z) order."""
    import jax.numpy as _jnp
    from jax import lax as _lax
    from paddle_tpu.layers import pooling as pool_mod

    name = name or gen_name("pool3d")
    if not isinstance(pool_type, str):
        pool_type = pool_mod.get(pool_type)
    if pool_type not in ("max", "average"):
        pool_type = "average" if "av" in pool_type else "max"
    kw, kh, kd = _triple(pool_size)
    sw, sh, sd = _triple(stride if stride is not None else pool_size)
    pw, ph, pd = _triple(padding)
    c = (num_channels or input.attrs.get("num_filters")
         or input.attrs.get("channels") or 1)
    vol = img_size or input.attrs.get("out_vol")
    if vol is None and input.attrs.get("explicit_depth"):
        vol = (input.depth, input.height, input.width)
    enforce(vol is not None, "img_pool3d needs img_size or a conv3d input")
    d_in, h_in, w_in = vol
    # ceil output sizes, like the reference pool layers and 2D img_pool
    d_out = -(-(d_in + 2 * pd - kd) // sd) + 1
    h_out = -(-(h_in + 2 * ph - kh) // sh) + 1
    w_out = -(-(w_in + 2 * pw - kw) // sw) + 1
    # extra right-padding so reduce_window emits the ceil-mode windows
    xd = (d_out - 1) * sd + kd - (d_in + 2 * pd)
    xh = (h_out - 1) * sh + kh - (h_in + 2 * ph)
    xw = (w_out - 1) * sw + kw - (w_in + 2 * pw)
    pads = ((0, 0), (pd, pd + xd), (ph, ph + xh), (pw, pw + xw), (0, 0))

    def fwd(ctx, params, states, x):
        v = raw(x)
        if v.ndim == 2:
            v = v.reshape(-1, c, d_in, h_in, w_in).transpose(0, 2, 3, 4, 1)
        if pool_type == "max":
            return _lax.reduce_window(
                v, -_jnp.inf, _lax.max, (1, kd, kh, kw, 1),
                (1, sd, sh, sw, 1), pads)
        summed = _lax.reduce_window(
            v, 0.0, _lax.add, (1, kd, kh, kw, 1), (1, sd, sh, sw, 1), pads)
        # exclude-padding divisor (the reference's avgPool3DForward)
        counts = _lax.reduce_window(
            _jnp.ones_like(v), 0.0, _lax.add, (1, kd, kh, kw, 1),
            (1, sd, sh, sw, 1), pads)
        return summed / counts

    return LayerOutput(
        name=name, layer_type="pool3d",
        size=c * d_out * h_out * w_out, parents=(input,), fn=fwd,
        height=h_out, width=w_out, depth=d_out,
        attrs={"out_vol": [d_out, h_out, w_out],
               "pool_type": pool_type, "channels": c,
               "pool_size": (kw, kh, kd), "stride": (sw, sh, sd),
               "padding": (pw, ph, pd), "img_vol": (d_in, h_in, w_in)},
    )


def sub_seq(input: LayerOutput, offsets: LayerOutput, sizes: LayerOutput,
            act=None, bias_attr=None, name: str | None = None) -> LayerOutput:
    """≅ sub_seq_layer ('subseq', SubSequenceLayer.cpp:29): from each
    sequence take the [offset, offset+size) window, producing a shorter
    sequence per row."""
    from paddle_tpu.ops import sequence as seq_ops

    name = name or gen_name("sub_seq")
    activation = act_mod.get(act)

    def fwd(ctx, params, states, x, off, sz):
        enforce(is_sequence(x), "sub_seq expects a sequence input")
        s = raw(off).reshape(-1).astype(jnp.int32)
        e = s + raw(sz).reshape(-1).astype(jnp.int32)
        y = seq_ops.seq_slice(x, s, e)
        return SequenceBatch(data=activation(y.data), length=y.length)

    return LayerOutput(name=name, layer_type="subseq", size=input.size,
                       parents=(input, offsets, sizes), fn=fwd,
                       attrs={"active_type": activation.name,
                              "dfs_parents": (input,)})


sub_seq_layer = sub_seq


def switch_order(input: LayerOutput, reshape_axis: int | None = None,
                 act=None, name: str | None = None,
                 layer_attr=None) -> LayerOutput:
    """≅ switch_order_layer (SwitchOrderLayer): NCHW -> NHWC permute; the
    reshape_axis splits output dims into (height, width) groups
    (LayerConfig.reshape_conf)."""
    name = name or gen_name("switch_order")
    c, h, w = input.depth, input.height, input.width
    activation = act_mod.get(act)
    axis = reshape_axis if reshape_axis is not None else 3

    def fwd(ctx, params, states, x):
        from paddle_tpu.layers.api import _to_nhwc

        out = _to_nhwc(raw(x), c, h, w)
        return activation(out.reshape(out.shape[0], -1))

    return LayerOutput(
        name=name, layer_type="switch_order", size=input.size,
        parents=(input,), fn=fwd, height=h, width=w, depth=c,
        attrs={"active_type": activation.name,
               "reshape_axis": axis,
               "height_axis": list(range(1, axis)), "width_axis": [axis]},
    )


switch_order_layer = switch_order


def mdlstmemory(input: LayerOutput, size: int | None = None,
                directions=(True, True), act=None, gate_act=None,
                state_act=None, param_attr=None, bias_attr=None,
                name: str | None = None) -> LayerOutput:
    """≅ mdlstmemory (MDLstmLayer.cpp:180): multi-dimensional (2-D) LSTM
    over an image-shaped grid, one forget gate per dimension, scanned as an
    anti-diagonal wavefront (cells on a diagonal are independent — the
    TPU-parallel formulation of the reference's topological cell order).

    Input is pre-projected like lstmemory: channels = (3 + ndims) * size
    (i, o, candidate + one forget gate per dim).  Parameters follow the
    reference sizing: recurrent weight [size, size*(3+ndims)] shared by
    both neighbors, bias [(5 + 2*ndims) * size] = gate biases + peepholes.
    ``directions[d]`` False flips the scan direction along that axis."""
    from paddle_tpu.layers.api import _wspec

    enforce(len(directions) == 2, "mdlstmemory supports 2-D grids")
    ndims = 2
    gates_n = 3 + ndims  # i, o, g + f_per_dim
    d = size or (input.depth // gates_n if input.depth > 1 else None)
    enforce(d, "mdlstmemory needs size= or a pre-projected image input")
    name = name or gen_name("mdlstmemory")
    h_dim, w_dim = input.height, input.width
    enforce(h_dim and w_dim, "mdlstmemory input needs height/width")
    wspec = _wspec(param_attr, name, "w0", (d, d * gates_n),
                   I.paddle_default())
    specs = [wspec]
    use_bias = bias_attr is not False
    bspec = None
    if use_bias:
        bspec = _wspec(bias_attr if isinstance(bias_attr, ParamAttr) else None,
                       name, "wbias", ((5 + 2 * ndims) * d,), I.constant(0.0))
        specs.append(bspec)
    oa = act_mod.get(act) if act else act_mod.TanhActivation()
    ga = act_mod.get(gate_act) if gate_act else act_mod.SigmoidActivation()
    sa = act_mod.get(state_act) if state_act else act_mod.TanhActivation()

    def fwd(ctx, params, states, x):
        v = raw(x)
        b = v.shape[0]
        xg = v.reshape(b, gates_n * d, h_dim, w_dim).transpose(0, 2, 3, 1) \
            if v.ndim == 2 else v  # [B, H, W, G*D]
        if not directions[0]:
            xg = xg[:, ::-1]
        if not directions[1]:
            xg = xg[:, :, ::-1]
        w_r = params[wspec.name]  # [D, G*D]
        if use_bias:
            full = params[bspec.name]
            gate_b = full[: gates_n * d]
            peep = full[gates_n * d:]  # [(2 + ndims) * D]: i, o, f1, f2
            xg = xg + gate_b
        else:
            peep = jnp.zeros(((2 + ndims) * d,), v.dtype)

        ii = jnp.arange(h_dim)[:, None] + jnp.arange(w_dim)[None, :]  # i+j

        def diag_step(carry, dd):
            hg, cg = carry  # [B, H, W, D] each
            up_h = jnp.pad(hg, ((0, 0), (1, 0), (0, 0), (0, 0)))[:, :-1]
            lf_h = jnp.pad(hg, ((0, 0), (0, 0), (1, 0), (0, 0)))[:, :, :-1]
            up_c = jnp.pad(cg, ((0, 0), (1, 0), (0, 0), (0, 0)))[:, :-1]
            lf_c = jnp.pad(cg, ((0, 0), (0, 0), (1, 0), (0, 0)))[:, :, :-1]
            gates = xg + (up_h + lf_h) @ w_r  # [B, H, W, G*D]
            gi = gates[..., 0:d] + peep[0:d] * (up_c + lf_c)
            go_pre = gates[..., d:2 * d]
            gg = gates[..., 2 * d:3 * d]
            f1 = ga(gates[..., 3 * d:4 * d] + peep[2 * d:3 * d] * up_c)
            f2 = ga(gates[..., 4 * d:5 * d] + peep[3 * d:4 * d] * lf_c)
            c_new = ga(gi) * sa(gg) + f1 * up_c + f2 * lf_c
            o = ga(go_pre + peep[d:2 * d] * c_new)
            h_new = o * oa(c_new)
            on_diag = (ii == dd)[None, :, :, None]
            return (jnp.where(on_diag, h_new, hg),
                    jnp.where(on_diag, c_new, cg)), None

        init = (jnp.zeros((b, h_dim, w_dim, d), v.dtype),
                jnp.zeros((b, h_dim, w_dim, d), v.dtype))
        (hg, cg), _ = jax.lax.scan(
            diag_step, init, jnp.arange(h_dim + w_dim - 1))
        if not directions[0]:
            hg = hg[:, ::-1]
        if not directions[1]:
            hg = hg[:, :, ::-1]
        return hg

    return LayerOutput(
        name=name, layer_type="mdlstmemory", size=d * h_dim * w_dim,
        parents=(input,), param_specs=tuple(specs), fn=fwd,
        height=h_dim, width=w_dim, depth=d,
        attrs={"active_type": oa.name, "active_gate_type": ga.name,
               "active_state_type": sa.name,
               "directions": list(bool(x) for x in directions),
               "bias_spec": bspec.name if bspec else None},
    )
