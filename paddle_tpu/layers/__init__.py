"""Declarative layer API — successor of ``python/paddle/trainer_config_helpers/
layers.py`` (266 wrappers) + ``python/paddle/v2/layer.py``, compiled to pure
JAX functions instead of a ModelConfig proto interpreted by C++."""
