"""recurrent_group / memory / beam_search — the TPU-native successor of
``RecurrentGradientMachine`` (``paddle/gserver/gradientmachines/
RecurrentGradientMachine.h:32``, ``memoryFrameLines_:342``, generation
``generateSequence:307`` / ``beamSearch:309``) and the config surface
``trainer_config_helpers/layers.py`` (``memory:3393``,
``recurrent_group:3862``, ``beam_search:4145``).

The reference expands the step sub-network once per timestep at runtime
(dynamic subnet expansion over ragged batches).  XLA wants one traced program,
so here the step sub-DAG is built ONCE symbolically and compiled into a
``jax.lax.scan`` over the padded time axis, with per-row masks freezing
memories past each sequence's true length — same semantics, static shapes,
full MXU utilization.  Generation compiles beam search into a single scan of
``max_length`` steps with top-k beam pruning per step (replacing
``RecurrentGradientMachine::beamSearch``'s host-side loop).

Step functions receive placeholder nodes and may use any layer helpers;
values from outside the group must be passed as :class:`StaticInput`
(reference constraint, kept here)."""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core import initializer as I
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import NestedSequenceBatch, SequenceBatch
from paddle_tpu.core.parameters import ParamSpec
from paddle_tpu.layers.base import Context, LayerOutput, evaluate, gen_name

NEG_INF = -1e9


class StaticInput:
    """Read-only per-batch value imported unchanged into every timestep
    (≅ StaticInput, layers.py:3835).  May be a plain vector or a whole
    sequence (the attention use-case: encoder outputs)."""

    def __init__(self, input: LayerOutput, is_seq: bool = False, size=None):
        enforce(isinstance(input, LayerOutput), "StaticInput wraps a LayerOutput")
        self.input = input


class SubsequenceInput:
    """Marks a nested-sequence input scattered one SUBSEQUENCE per step
    (≅ SubsequenceInput, layers.py:3806)."""

    def __init__(self, input: LayerOutput):
        enforce(isinstance(input, LayerOutput),
                "SubsequenceInput wraps a LayerOutput")
        self.input = input


class BaseGeneratedInput:
    pass


class GeneratedInput(BaseGeneratedInput):
    """Generation-time input: the embedding of the previously generated token
    (≅ GeneratedInput, layers.py:3556).  ``embedding_name`` shares the
    parameter with the training graph's target-side embedding."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int):
        self.size = size  # dictionary size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size
        self.bos_id = 0
        self.eos_id = 1


def memory(name: str | None, size: int, boot_layer: LayerOutput | None = None,
           boot_bias=None, boot_bias_active_type=None,
           boot_with_const_id: int | None = None,
           is_seq: bool = False, memory_name: str | None = None) -> LayerOutput:
    """≅ memory (layers.py:3393): inside a step function, refers to the
    previous timestep's value of the layer called ``name``.  First step reads
    ``boot_layer``'s (outer) value, a constant id, or zeros."""
    enforce(not is_seq, "sequence-level memory not supported yet")
    enforce(boot_bias is None,
            "memory boot_bias is not implemented; pass boot_layer instead")
    node = LayerOutput(
        name=memory_name or gen_name("memory"),
        layer_type="__memory__",
        size=size,
        attrs={"link": name, "boot_const": boot_with_const_id},
    )
    node._boot_layer = boot_layer
    node._link_override = None
    node.set_input = lambda layer: _set_memory_input(node, layer)
    return node


def _set_memory_input(mem: LayerOutput, layer: LayerOutput) -> None:
    """Explicit linking alternative to name-matching (≅ memory.set_input)."""
    mem._link_override = layer


def _collect_step_graph(outs: Sequence[LayerOutput]):
    """Walk the step sub-DAG, stopping at placeholder/memory leaves."""
    seq_phs, static_phs, mems = [], [], []
    nodes = []
    seen = set()

    def visit(n: LayerOutput):
        if id(n) in seen:
            return
        seen.add(id(n))
        if n.layer_type == "__step_input__":
            seq_phs.append(n)
            return
        if n.layer_type == "__static_input__":
            static_phs.append(n)
            return
        if n.layer_type == "__memory__":
            mems.append(n)
            return
        enforce(
            n.layer_type != "data",
            f"layer {n.name!r}: outer values must enter a recurrent_group "
            "via StaticInput",
        )
        for p in n.parents:
            visit(p)
        nodes.append(n)

    for o in outs:
        visit(o)
    return nodes, seq_phs, static_phs, mems


def _resolve_links(mems, step_nodes, outs):
    """Map each memory to the step node whose output feeds it next step."""
    by_name = {n.name: n for n in step_nodes}
    linked = []
    for m in mems:
        if m._link_override is not None:
            linked.append(m._link_override)
            continue
        link = m.attrs["link"]
        enforce(link is not None, "memory() needs a name= linking it to a "
                                  "layer defined in the step function")
        tgt = by_name.get(link)
        enforce(tgt is not None,
                f"memory links to layer {link!r} but no layer with that name "
                "exists in the step function")
        linked.append(tgt)
    return linked


def _boot_value(mem, boot_val, batch, dtype=jnp.float32):
    if boot_val is not None:
        return boot_val
    const = mem.attrs.get("boot_const")
    if const is not None:
        return jnp.full((batch, mem.size), float(const), dtype)
    return jnp.zeros((batch, mem.size), dtype)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NestedGeneratedSequence:
    """Generation results for an outer sequence of subsequences (the nested
    configs of test_recurrent_machine_generation.cpp): one GeneratedSequence
    per (outer sample x subsequence), plus the outer LoD."""

    inner: "GeneratedSequence"  # [B*S, R, L]
    seq_length: jax.Array  # [B] valid subsequences per outer sample
    n_sub: int = dataclasses.field(metadata=dict(static=True))


#: sink-the-scan-tail optimization toggle (tests flip it to prove
#: numerical equivalence of the sunk and per-step paths)
SINK_SCAN_TAIL = True


def recurrent_group(step: Callable, input, reverse: bool = False,
                    name: str | None = None, targetInlink=None):
    """≅ recurrent_group (layers.py:3862).  Scatters sequence inputs into
    timesteps, runs ``step`` under ``lax.scan``, gathers outputs back into a
    sequence."""
    from paddle_tpu.layers import base as layer_base

    name = name or gen_name("recurrent_group")
    reg_start = len(layer_base.layer_registry())
    if isinstance(input, (LayerOutput, StaticInput, SubsequenceInput)):
        input = [input]
    input = list(input)
    enforce(len(input) > 0, "recurrent_group needs at least one input")

    # build placeholders and call the user's step function symbolically
    in_args = []
    seq_inputs: list[LayerOutput] = []  # outer sequence nodes, in order
    static_inputs: list[LayerOutput] = []  # outer static nodes, in order
    input = [
        each.input if isinstance(each, SubsequenceInput) else each
        for each in input
    ]
    for each in input:
        if isinstance(each, StaticInput):
            ph = LayerOutput(name=gen_name("static_in"),
                             layer_type="__static_input__",
                             size=each.input.size)
            ph._outer = each.input
            static_inputs.append(each.input)
            in_args.append(ph)
        else:
            enforce(isinstance(each, LayerOutput),
                    "recurrent_group inputs must be LayerOutput or StaticInput")
            ph = LayerOutput(name=gen_name("step_in"),
                             layer_type="__step_input__", size=each.size)
            ph._outer = each
            seq_inputs.append(each)
            in_args.append(ph)
    enforce(len(seq_inputs) > 0,
            "recurrent_group needs at least one sequence input")

    outs = step(*in_args)
    single = isinstance(outs, LayerOutput)
    outs = [outs] if single else list(outs)

    if single and outs[0].layer_type == "beam_search":
        return _nested_beam_group(name, outs[0], seq_inputs)

    # every node built during step() (registry slice), in creation order —
    # this also catches layers only reachable through memory links (e.g. the
    # lstm state get_output), ≅ the reference's submodel layer list
    created = layer_base.layer_registry()[reg_start:]

    step_nodes, seq_phs, static_phs, mems = _collect_step_graph(outs)
    link_targets = _resolve_links(mems, step_nodes + [
        n for n in created
        if n.layer_type not in ("__memory__", "__step_input__",
                                "__static_input__")
    ], outs)
    # evaluation roots: outputs + every memory's link target
    roots = list(outs)
    for t in link_targets:
        if not any(t is r for r in roots):
            roots.append(t)
    # re-collect so link-only-reachable layers join the step graph
    step_nodes, seq_phs, static_phs, mems2 = _collect_step_graph(roots)
    for m in mems2:
        if not any(m is x for x in mems):
            mems.append(m)
            link_targets.append(_resolve_links([m], step_nodes, outs)[0])

    # placeholders found by the walk, matched back to outer nodes
    seq_ph_order = [ph for ph in in_args if ph.layer_type == "__step_input__"]
    static_ph_order = [ph for ph in in_args if ph.layer_type == "__static_input__"]
    boot_layers = [m._boot_layer for m in mems]

    parents = (tuple(seq_inputs) + tuple(static_inputs)
               + tuple(b for b in boot_layers if b is not None))
    param_specs = []
    seen_p = set()
    for n in step_nodes:
        for s in n.param_specs:
            if s.name not in seen_p:
                seen_p.add(s.name)
                param_specs.append(s)
    state_specs = []
    seen_s = set()
    for n in step_nodes:
        for s in n.state_specs:
            if s.name not in seen_s:
                seen_s.add(s.name)
                state_specs.append(s)

    n_seq = len(seq_inputs)
    n_static = len(static_inputs)

    # governing sequence: lengths/mask come from targetInlink when given
    # (reference semantics), else the first sequence input
    govern_idx = 0
    if targetInlink is not None:
        tgt_node = (targetInlink.input if isinstance(targetInlink, StaticInput)
                    else targetInlink)
        for i, s in enumerate(seq_inputs):
            if s is tgt_node:
                govern_idx = i
                break
        else:
            enforce(False,
                    "targetInlink must be one of the group's sequence inputs")

    # ---- fused fast path: a step that is EXACTLY one standard gru_step
    # (gru_group / networks.simple_gru) lowers to the Pallas GRU sequence
    # kernel instead of the generic lax.scan — same freeze-mask semantics,
    # same parameters, same emission metadata; only the runtime closure
    # changes.  (The lstmemory analog lives in layers/api.py.)
    fused_fwd = None
    if (len(outs) == 1 and outs[0].layer_type == "gru_step"
            and len(step_nodes) == 1 and len(mems) == 1
            and link_targets[0] is outs[0]
            and len(seq_inputs) == 1 and not static_inputs
            and len(outs[0].parents) == 2
            and outs[0].parents[0] in seq_ph_order
            and outs[0].parents[1] is mems[0]
            and outs[0].attrs.get("active_type") == "tanh"
            and outs[0].attrs.get("active_gate_type") == "sigmoid"):
        from paddle_tpu.ops import rnn as rnn_ops

        g_node = outs[0]
        g_size = g_node.size
        g_wspec = g_node.param_specs[0]
        g_mem = mems[0]
        g_has_boot = boot_layers[0] is not None

        def fused_fwd(ctx, params, states, *parent_values):
            seq_val = parent_values[0]
            enforce(isinstance(seq_val, SequenceBatch),
                    "recurrent_group sequence inputs must be sequences")
            boot = parent_values[1] if g_has_boot else None
            init = _boot_value(
                g_mem, _raw_boot(boot) if boot is not None else None,
                seq_val.batch_size)
            xw = seq_val.data
            bias_name = g_node.attrs.get("bias_spec")
            if bias_name:
                xw = xw + params[bias_name]
            w = params[g_wspec.name]
            out, _ = rnn_ops.gru_fused(
                SequenceBatch(xw, seq_val.length),
                w[:, : 2 * g_size], w[:, 2 * g_size:], init,
                reverse=reverse)
            return out

    # ---- sink the feed-forward tail out of the scan (round 5): output-
    # side step nodes that feed NO memory update are a pure per-step
    # function of the recurrence's frontier values, so they run ONCE on
    # the time-stacked sequence instead of T times inside the serial
    # loop.  For the canonical NMT decoder step (simple_attention +
    # gru_step -> softmax fc, the reference's networks.py:1304 pattern)
    # this moves the [B,V] vocab projection, its softmax, AND their
    # backward out of the sequential chain — T small [B,H]x[H,V] matmuls
    # become one MXU-shaped [B*T,H]x[H,V] matmul, and the per-step [B,V]
    # output stacking disappears.  Emission metadata and parameters are
    # untouched: only the runtime closure changes.
    _SINKABLE = {"fc", "mixed", "addto", "slope_intercept", "scaling"}
    needed_ids: set = set()
    if not SINK_SCAN_TAIL:
        _SINKABLE = set()
    _stk = list(link_targets)
    while _stk:
        _nd = _stk.pop()
        if id(_nd) in needed_ids:
            continue
        needed_ids.add(id(_nd))
        _stk.extend(_nd.parents)
    sunk: list = []           # tail nodes applied outside the scan
    sink_frontier: list = []  # step nodes whose stacked values feed them
    if fused_fwd is None and len(outs) == 1 \
            and id(outs[0]) not in needed_ids and not reverse:
        chain_ok = True
        _pending = [outs[0]]
        _seen: set = set()
        while _pending and chain_ok:
            nd = _pending.pop()
            if id(nd) in _seen:
                continue
            _seen.add(id(nd))
            if (nd.layer_type not in _SINKABLE or nd.state_specs
                    or nd.attrs.get("drop_rate")):
                chain_ok = False
                break
            sunk.append(nd)
            for p in nd.parents:
                # placeholder checks FIRST: a static input that also
                # feeds the recurrence is in needed_ids, and stacking its
                # whole-sequence per-step value would be wrong — the
                # rejection must win over the frontier classification
                if any(p is ph for ph in static_ph_order):
                    # static inputs carry the WHOLE sequence per step;
                    # their layout differs outside — don't sink
                    chain_ok = False
                    break
                if any(p is ph for ph in seq_ph_order):
                    pass  # outer sequence value feeds the tail directly
                elif id(p) in needed_ids:
                    if not any(p is f for f in sink_frontier):
                        sink_frontier.append(p)
                else:
                    _pending.append(p)
        if not chain_ok or not sink_frontier:
            sunk, sink_frontier = [], []
    inner_outs = sink_frontier if sunk else outs
    if sunk:
        # the scan must trace ONLY the recurrence (+frontier): leaving the
        # tail in `roots` would trace its per-step ops into the scan jaxpr
        # and rest the speedup on XLA DCE
        scan_roots: list = []
        for n in list(link_targets) + list(sink_frontier):
            if not any(n is r for r in scan_roots):
                scan_roots.append(n)
    else:
        scan_roots = roots

    def fwd(ctx, params, states, *parent_values, __final_logits__=False):
        seq_vals = parent_values[:n_seq]
        static_vals = parent_values[n_seq:n_seq + n_static]
        boot_vals_in = parent_values[n_seq + n_static:]
        for v in seq_vals:
            enforce(isinstance(v, SequenceBatch),
                    "recurrent_group sequence inputs must be sequences")
        govern = seq_vals[govern_idx]
        b = govern.batch_size
        t_len = govern.max_len
        length = govern.length
        mask = govern.mask()  # [B, T]

        # scanned inputs: time-major per-step slices
        xs = tuple(jnp.swapaxes(v.data, 0, 1) for v in seq_vals)  # [T, B, ...]
        ms = jnp.swapaxes(mask, 0, 1)  # [T, B]

        bi = iter(boot_vals_in)
        boot_vals = [next(bi) if bl is not None else None for bl in boot_layers]
        carry0 = {
            m.name: _boot_value(m, _raw_boot(bv), b)
            for m, bv in zip(mems, boot_vals)
        }
        static_feed = {ph.name: sv
                       for ph, sv in zip(static_ph_order, static_vals)}

        def body(carry, scanned):
            mem_c, states_c = carry
            t_idx, mt, *xts = scanned
            feed = dict(static_feed)
            feed.update({ph.name: x for ph, x in zip(seq_ph_order, xts)})
            feed.update(mem_c)
            key = (jax.random.fold_in(ctx._key, t_idx)
                   if ctx._key is not None else None)
            sub_ctx = Context(is_train=ctx.is_train, key=key)
            vals, states_n = evaluate(scan_roots, sub_ctx, params, states_c,
                                      feed)
            mcol = mt[:, None]
            new_carry = {}
            for m, tgt in zip(mems, link_targets):
                nv = vals[tgt.name]
                nv = nv.data if isinstance(nv, SequenceBatch) else nv
                # carry dtype follows the boot (e.g. a bf16 boot from a
                # fused upstream group under the mixed-precision policy)
                new_carry[m.name] = (
                    mcol * nv + (1.0 - mcol) * mem_c[m.name]
                ).astype(mem_c[m.name].dtype)
            step_out = tuple(_raw_boot(vals[o.name]) for o in inner_outs)
            return (new_carry, states_n), step_out

        t_ids = jnp.arange(t_len, dtype=jnp.int32)
        (_, states_final), ys = jax.lax.scan(
            body, (carry0, dict(states)), (t_ids, ms) + xs, reverse=reverse)
        stacked = {
            o.name: SequenceBatch(data=jnp.swapaxes(y, 0, 1), length=length)
            for o, y in zip(inner_outs, ys)
        }
        if sunk:
            # apply the sunk tail once over the stacked sequences (layer
            # fns are sequence-aware: fc/mixed on [B,T,...] broadcast
            # over time exactly as the per-step application did)
            outer_vals: dict = dict(stacked)
            for ph, sv in zip(seq_ph_order, seq_vals):
                outer_vals[ph.name] = sv
            remaining = list(sunk)
            while remaining:
                progressed = False
                for nd in list(remaining):
                    if all(p.name in outer_vals for p in nd.parents):
                        pv = [outer_vals[p.name] for p in nd.parents]
                        pvals = {s.name: params[s.name]
                                 for s in nd.param_specs}
                        fn = nd.fn
                        if __final_logits__ and nd is outs[0]:
                            # the fused-CE path wants the tail's final
                            # softmax fc as PRE-activation logits
                            fn = nd.attrs["__fc_logits__"]
                        res = fn(ctx, pvals, {}, *pv)
                        outer_vals[nd.name] = res
                        remaining.remove(nd)
                        progressed = True
                enforce(progressed, "recurrent_group sink: unresolvable "
                        "tail dependency")

            def _with_govern_length(v):
                # group outputs always carry the GOVERNING sequence's
                # lengths (per-step path semantics); a tail that consumed
                # a non-governing input must not leak that input's
                # lengths onto the output
                if isinstance(v, SequenceBatch):
                    return SequenceBatch(data=v.data, length=length)
                return v

            results = tuple(_with_govern_length(outer_vals[o.name])
                            for o in outs)
        else:
            results = tuple(stacked[o.name] for o in outs)
        result = results[0] if single else results
        if state_specs:
            # stateful layers (e.g. BN) inside the group: updated running
            # stats from the scan are surfaced to the outer evaluate
            return result, states_final
        return result

    # ---- submodel naming + emission metadata (≅ RecurrentLayerGroupBegin/
    # End, config_parser.py): in-group layers get the "@<group>" suffix, the
    # memory agents the "+delay1@<group>" names, auto-named parameters follow
    # their layer, and the gather agent at root takes the step output's name.
    out_base_names = [o.name for o in outs]
    members = []  # creation-order in-group nodes (memories + step layers)
    in_group = {id(n) for n in step_nodes} | {id(m) for m in mems}
    for n in created:
        if id(n) in in_group:
            members.append(n)
    for ph, outer in zip(seq_ph_order, seq_inputs):
        ph.name = f"{outer.name}@{name}"
        ph.attrs["__in_group__"] = name
    for ph, outer in zip(static_ph_order, static_inputs):
        ph.name = f"{outer.name}@{name}"
        ph.attrs["__in_group__"] = name
    for m in mems:
        link = m.attrs.get("link")
        base = f"{link}+delay1" if link else m.name
        m.name = f"{base}@{name}"
        m.attrs["__in_group__"] = name
    for n in step_nodes:
        old = n.name
        n.name = f"{old}@{name}"
        n.attrs["__in_group__"] = name
        for s in n.param_specs:
            a = getattr(s, "attr", None)
            if (a is None or a.name is None) and s.name.startswith(f"_{old}."):
                # frozen dataclass: rename in place so runtime closures
                # (which read .name at call time) stay consistent
                object.__setattr__(
                    s, "name", f"_{n.name}." + s.name[len(old) + 2:])
        if (n.attrs.get("bias_spec") or "").startswith(f"_{old}."):
            n.attrs["bias_spec"] = (
                f"_{n.name}." + n.attrs["bias_spec"][len(old) + 2:])

    group = LayerOutput(
        name=out_base_names[0] if single else f"{name}__outputs",
        layer_type="recurrent_layer_group",
        size=outs[0].size, parents=parents,
        param_specs=tuple(param_specs), state_specs=tuple(state_specs),
        fn=fused_fwd if fused_fwd is not None else fwd, attrs={
            "reverse": reverse, "n_outputs": len(outs),
            "group": {
                "marker": name,
                "scatter": list(zip(seq_ph_order, seq_inputs))
                + list(zip(static_ph_order, static_inputs)),
                "members": members,
                "memories": list(zip(mems, link_targets)),
                "outs": list(outs),
                "out_bases": out_base_names,
            },
        },
    )
    if single:
        if (sunk and fused_fwd is None
                and outs[0].attrs.get("__fc_logits__") is not None):
            # propagate the logits hook through the group: same contract
            # (drop-in for fn, same parents, returns pre-softmax logits);
            # classification_cost's fused path then skips the [B,T,V]
            # softmax round-trip entirely — the scan portion is shared
            # with (or replaces) the probs path
            group.attrs["__fc_logits__"] = (
                lambda ctx, params, states, *pv: fwd(
                    ctx, params, states, *pv, __final_logits__=True))
        return group
    # selector children expose each output as its own node
    sels = []
    for k, o in enumerate(outs):
        def make_sel(k):
            def sel(ctx, params, states, v):
                return v[k]
            return sel
        sels.append(LayerOutput(
            name=out_base_names[k], layer_type="gather_selector", size=o.size,
            parents=(group,), fn=make_sel(k)))
    group.attrs["group"]["selectors"] = sels
    return sels


def _raw_boot(v):
    if isinstance(v, SequenceBatch):
        return v.data
    return v


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneratedSequence:
    """Beam-search result (≅ the SWIG SequenceGenerator output,
    ``api/PaddleAPI.h:1025``): per input row, ``num_results`` candidate
    sequences with scores.  ``ids`` excludes <s>, includes <e> when emitted."""

    ids: jax.Array  # [B, R, L] int32
    length: jax.Array  # [B, R] int32
    score: jax.Array  # [B, R] float, sum of log-probs

    def to_list(self):
        """Ragged python lists: [batch][result] -> (score, [ids])."""
        out = []
        ids = jax.device_get(self.ids)
        lens = jax.device_get(self.length)
        scores = jax.device_get(self.score)
        for b in range(ids.shape[0]):
            row = []
            for r in range(ids.shape[1]):
                row.append((float(scores[b, r]),
                            [int(i) for i in ids[b, r, :int(lens[b, r])]]))
            out.append(row)
        return out


def _nested_beam_group(name, beam_node, seq_inputs):
    """recurrent_group over subsequences whose step IS a beam_search (the
    sample_trainer_nest_rnn_gen.conf shape).

    Two execution modes:

    - independent subsequences (the reference test's shape — its outer
      memory is read-only and unused): flatten [B, S, ...] into a
      [B*S]-row generation batch, one fused run;
    - LIVE outer memory (≅ RecurrentGradientMachine.cpp:1291, outer-frame
      memory plumbed into inner frames via ScatterAgentLayer): an inner
      memory whose ``boot_layer`` is an outer ``memory()`` placeholder
      boots each subsequence's generation from the state the PREVIOUS
      subsequence ended in (best beam); the outer loop runs the (static)
      subsequence count sequentially, freezing the carry past each row's
      ``seq_length``.
    """
    enforce(len(seq_inputs) == 1,
            "nested beam generation supports exactly one subsequence input")
    beam_info = beam_node.attrs.get("beam_run") or {}
    live_idx = [i for i, bl in enumerate(beam_info.get("boot_layers", ()))
                if bl is not None and bl.layer_type == "__memory__"]
    if live_idx:
        return _nested_beam_group_live(name, beam_node, seq_inputs,
                                       beam_info, live_idx)
    enforce(
        len(beam_node.parents) == 1,
        "nested beam generation requires the inner beam_search to take "
        "exactly one (read-only) outer input; extra StaticInputs without "
        "a live outer memory are not supported — restructure so the "
        "inner step depends only on the subsequence input",
    )
    outer = seq_inputs[0]
    # the wrapper supersedes the inner node as "__beam_search_predict__"
    inner_aliases = beam_node.attrs.get("aliases", ())
    beam_node.attrs["aliases"] = ()
    beam_node.attrs["__in_group__"] = True

    def fwd(ctx, params, states, outer_val):
        enforce(isinstance(outer_val, NestedSequenceBatch),
                "nested beam generation needs a NestedSequenceBatch feed "
                "(sequence of subsequences)")
        flat = outer_val.flatten_outer()
        res = beam_node.fn(ctx, params, states, flat)
        return NestedGeneratedSequence(
            inner=res, seq_length=outer_val.seq_length,
            n_sub=outer_val.data.shape[1])

    return LayerOutput(
        name=name, layer_type="beam_search", size=beam_node.size,
        parents=(outer,), param_specs=beam_node.param_specs,
        state_specs=beam_node.state_specs, fn=fwd,
        attrs={**{k: v for k, v in beam_node.attrs.items()
                  if k != "__in_group__"},
               "aliases": inner_aliases or ("__beam_search_predict__",)},
    )


def _nested_beam_group_live(name, beam_node, seq_inputs, beam_info,
                            live_idx):
    """Live-outer-memory nested generation (see _nested_beam_group)."""
    outer = seq_inputs[0]
    run = beam_info["run"]
    mems = beam_info["mems"]
    boot_layers = list(beam_info["boot_layers"])
    static_inputs = list(beam_info["static_inputs"])
    enforce(
        len(static_inputs) == 1
        and static_inputs[0].layer_type == "__step_input__",
        "live-outer-memory nested generation takes exactly one "
        "subsequence input (plus outer memories)")
    live_mem_names = [mems[i].name for i in live_idx]
    outer_mem_phs = [boot_layers[i] for i in live_idx]
    # the outer memories' own boots (real outer-graph layers, or zeros)
    outer_boots = [ph._boot_layer for ph in outer_mem_phs]
    # inner memories booted from a FIXED outer layer (not a live memory):
    # same value every outer step
    fixed_idx = [j for j, bl in enumerate(boot_layers)
                 if bl is not None and j not in live_idx]
    inner_aliases = beam_node.attrs.get("aliases", ())
    beam_node.attrs["aliases"] = ()
    beam_node.attrs["__in_group__"] = True

    def fwd(ctx, params, states, outer_val, *pv):
        enforce(isinstance(outer_val, NestedSequenceBatch),
                "nested beam generation needs a NestedSequenceBatch feed "
                "(sequence of subsequences)")
        b, n_sub = outer_val.data.shape[:2]
        # parent values: outer-memory boots first, then fixed boots —
        # the order `parents` is declared in below
        pv = list(pv)
        carries = []
        for ph, ob in zip(outer_mem_phs, outer_boots):
            if ob is not None:
                carries.append(_raw_boot(pv.pop(0)))
            else:
                carries.append(_boot_value(ph, None, b))
        fixed_vals = {j: pv.pop(0) for j in fixed_idx}

        per_step = []
        for t in range(n_sub):
            sub_t = SequenceBatch(data=outer_val.data[:, t],
                                  length=outer_val.sub_length[:, t])
            # boot list in `run`'s expected order: every not-None boot of
            # boot_layers, live entries replaced by the running carry
            boots_in = []
            li = 0
            for j, bl in enumerate(boot_layers):
                if j in live_idx:
                    boots_in.append(carries[li])
                    li += 1
                elif bl is not None:
                    boots_in.append(fixed_vals[j])
            gen, final = run(ctx, params, states, [sub_t], boots_in,
                             return_final_mems=True)
            # rows whose outer sequence already ended freeze their carry
            active = (t < outer_val.seq_length)[:, None]
            carries = [
                jnp.where(active, final[nm], c)
                for nm, c in zip(live_mem_names, carries)
            ]
            per_step.append(gen)
        inner = GeneratedSequence(
            ids=jnp.stack([g.ids for g in per_step], axis=1).reshape(
                b * n_sub, *per_step[0].ids.shape[1:]),
            length=jnp.stack([g.length for g in per_step], axis=1).reshape(
                b * n_sub, -1),
            score=jnp.stack([g.score for g in per_step], axis=1).reshape(
                b * n_sub, -1),
        )
        return NestedGeneratedSequence(
            inner=inner, seq_length=outer_val.seq_length, n_sub=n_sub)

    parents = ((outer,) + tuple(ob for ob in outer_boots if ob is not None)
               + tuple(boot_layers[j] for j in fixed_idx))
    return LayerOutput(
        name=name, layer_type="beam_search", size=beam_node.size,
        parents=parents, param_specs=beam_node.param_specs,
        state_specs=beam_node.state_specs, fn=fwd,
        attrs={"aliases": ("__beam_search_predict__",) + tuple(inner_aliases),
               "nested": True, "live_outer_memory": True},
    )


def beam_search(step: Callable, input, bos_id: int, eos_id: int,
                beam_size: int, max_length: int = 500,
                name: str | None = None,
                num_results_per_sample: int | None = None) -> LayerOutput:
    """≅ beam_search (layers.py:4145): generation-time recurrent group whose
    sequence input is the model's own previous output.  Compiles to one
    ``lax.scan`` of ``max_length`` steps over a [B*beam] batch with top-k
    pruning, instead of the reference's host-side beam loop."""
    name = name or gen_name("beam_search")
    if num_results_per_sample is None:
        num_results_per_sample = beam_size
    enforce(num_results_per_sample <= beam_size,
            "num_results_per_sample must be <= beam_size")
    if isinstance(input, (StaticInput, BaseGeneratedInput)):
        input = [input]
    input = list(input)

    gen_idx = -1
    for i, each in enumerate(input):
        enforce(not isinstance(each, LayerOutput),
                "in beam_search none of the inputs may be a plain LayerOutput")
        if isinstance(each, BaseGeneratedInput):
            enforce(gen_idx == -1, "beam_search accepts only one GeneratedInput")
            gen_idx = i
    enforce(gen_idx != -1, "beam_search needs a GeneratedInput")
    gipt: GeneratedInput = input[gen_idx]
    gipt.bos_id, gipt.eos_id = bos_id, eos_id
    vocab = gipt.size

    emb_spec = ParamSpec(
        name=gipt.embedding_name,
        shape=(gipt.size, gipt.embedding_size),
        initializer=I.paddle_default(),
    )

    # placeholders + symbolic step call
    in_args = []
    static_inputs: list[LayerOutput] = []
    static_ph_order: list[LayerOutput] = []
    for each in input:
        if isinstance(each, BaseGeneratedInput):
            ph = LayerOutput(name=gen_name("gen_in"),
                             layer_type="__step_input__",
                             size=gipt.embedding_size)
            gen_ph = ph
        else:
            ph = LayerOutput(name=gen_name("static_in"),
                             layer_type="__static_input__",
                             size=each.input.size)
            ph._outer = each.input
            static_inputs.append(each.input)
            static_ph_order.append(ph)
        in_args.append(ph)

    outs = step(*in_args)
    enforce(isinstance(outs, LayerOutput),
            "beam_search step must return a single (softmax) output layer")
    out_node = outs
    step_nodes, seq_phs, st_phs, mems = _collect_step_graph([out_node])
    link_targets = _resolve_links(mems, step_nodes, [out_node])
    roots = [out_node]
    for t in link_targets:
        if not any(t is r for r in roots):
            roots.append(t)
    boot_layers = [m._boot_layer for m in mems]

    parents = (tuple(static_inputs)
               + tuple(b for b in boot_layers if b is not None))
    param_specs = [emb_spec]
    seen_p = {emb_spec.name}
    state_specs = []
    seen_s = set()
    for n in step_nodes:
        for s in n.param_specs:
            if s.name not in seen_p:
                seen_p.add(s.name)
                param_specs.append(s)
        for s in n.state_specs:
            if s.name not in seen_s:
                seen_s.add(s.name)
                state_specs.append(s)

    n_static = len(static_inputs)
    beam = beam_size
    n_res = num_results_per_sample

    def _expand(v):
        """[B, ...] -> [B*beam, ...] repeating rows (beam-major per row)."""
        if isinstance(v, SequenceBatch):
            return SequenceBatch(data=jnp.repeat(v.data, beam, axis=0),
                                 length=jnp.repeat(v.length, beam, axis=0))
        return jnp.repeat(v, beam, axis=0)

    def run(ctx, params, states, static_vals, boot_vals_in,
            return_final_mems=False):
        if static_vals:
            sv0 = static_vals[0]
            b = sv0.batch_size if isinstance(sv0, SequenceBatch) else sv0.shape[0]
        elif boot_vals_in:
            b = _raw_boot(boot_vals_in[0]).shape[0]
        else:
            b = 1
        bb = b * beam

        static_feed = {ph.name: _expand(sv)
                       for ph, sv in zip(static_ph_order, static_vals)}
        bi = iter(boot_vals_in)
        boot_vals = [next(bi) if bl is not None else None for bl in boot_layers]
        carry_mems = {
            m.name: _boot_value(m, None, bb) if bv is None
            else _expand(_raw_boot(bv))
            for m, bv in zip(mems, boot_vals)
        }

        table = params[emb_spec.name]
        tokens0 = jnp.zeros((b, beam, max_length), jnp.int32)
        scores0 = jnp.concatenate(
            [jnp.zeros((b, 1)), jnp.full((b, beam - 1), NEG_INF)], axis=1)
        finished0 = jnp.zeros((b, beam), bool)
        lengths0 = jnp.zeros((b, beam), jnp.int32)
        last0 = jnp.full((b, beam), bos_id, jnp.int32)

        def body(carry, t_idx):
            mems_c, tokens, scores, finished, lengths, last = carry
            emb = jnp.take(table, last.reshape(bb), axis=0)  # [Bb, E]
            feed = dict(static_feed)
            feed[gen_ph.name] = emb
            feed.update(mems_c)
            key = (jax.random.fold_in(ctx._key, t_idx)
                   if ctx._key is not None else None)
            sub_ctx = Context(is_train=False, key=key)
            vals, _ = evaluate(roots, sub_ctx, params, states, feed)
            probs = _raw_boot(vals[out_node.name]).reshape(b, beam, vocab)
            logp = jnp.log(jnp.clip(probs, 1e-20))
            # finished beams may only emit <e> at no cost (score frozen)
            fin_row = jnp.full((vocab,), NEG_INF).at[eos_id].set(0.0)
            logp = jnp.where(finished[:, :, None], fin_row[None, None, :], logp)
            cand = (scores[:, :, None] + logp).reshape(b, beam * vocab)
            new_scores, idx = jax.lax.top_k(cand, beam)  # [B, beam]
            prev_beam = idx // vocab  # [B, beam]
            token = (idx % vocab).astype(jnp.int32)

            def reorder_rows(x2d):
                flat = (jnp.arange(b)[:, None] * beam + prev_beam).reshape(-1)
                return x2d[flat]

            mems_n = {k: reorder_rows(v) for k, v in mems_c.items()}
            # re-run? no: memories advance from the step we just evaluated.
            new_mem_vals = {
                m.name: reorder_rows(_raw_boot(vals[tgt.name]))
                for m, tgt in zip(mems, link_targets)
            }
            fin_r = jnp.take_along_axis(finished, prev_beam, axis=1)
            len_r = jnp.take_along_axis(lengths, prev_beam, axis=1)
            tokens = jnp.take_along_axis(
                tokens, prev_beam[:, :, None], axis=1)
            tokens = tokens.at[:, :, t_idx].set(
                jnp.where(fin_r, tokens[:, :, t_idx], token))
            new_finished = fin_r | (token == eos_id)
            new_lengths = jnp.where(fin_r, len_r, len_r + 1)
            # frozen beams keep their old memory values
            mems_out = {
                k: jnp.where(fin_r.reshape(bb)[:, None],
                             mems_n[k], new_mem_vals[k])
                for k in mems_n
            }
            new_last = jnp.where(fin_r, last, token)
            return ((mems_out, tokens, new_scores, new_finished,
                     new_lengths, new_last), None)

        carry0 = (carry_mems, tokens0, scores0, finished0, lengths0, last0)
        (mems_c, tokens, scores, finished, lengths, last), _ = jax.lax.scan(
            body, carry0, jnp.arange(max_length, dtype=jnp.int32))
        gen = GeneratedSequence(
            ids=tokens[:, :n_res, :],
            length=lengths[:, :n_res],
            score=scores[:, :n_res],
        )
        if return_final_mems:
            # per inner memory: the BEST beam's final value [B, D] (beams
            # come out of top_k score-sorted, best first) — the value a
            # live outer memory carries to the next subsequence's frame
            # (≅ RecurrentGradientMachine.cpp:1291 outer-frame plumbing)
            final = {
                m.name: v.reshape(b, beam, *v.shape[1:])[:, 0]
                for m, v in ((m, mems_c[m.name]) for m in mems)
            }
            return gen, final
        return gen

    def fwd(ctx, params, states, *parent_values):
        return run(ctx, params, states, parent_values[:n_static],
                   parent_values[n_static:])

    return LayerOutput(
        name=name, layer_type="beam_search", size=gipt.size,
        parents=parents, param_specs=tuple(param_specs),
        state_specs=tuple(state_specs), fn=fwd,
        attrs={"bos_id": bos_id, "eos_id": eos_id, "beam_size": beam_size,
               "max_length": max_length,
               "beam_run": {"run": run, "mems": mems,
                            "boot_layers": boot_layers,
                            "static_inputs": static_inputs},
               # reference beam_search names its prediction output layer
               # "__beam_search_predict__" (networks.py); configs reference it
               "aliases": ("__beam_search_predict__",)},
    )


def gru_step_layer(input: LayerOutput, output_mem: LayerOutput,
                   size: int | None = None, act=None, gate_act=None,
                   name: str | None = None, bias_attr=None,
                   param_attr=None) -> LayerOutput:
    """One GRU step given a pre-projected input of size 3*D and the previous
    hidden state (≅ gru_step_layer, layers.py:3157 / GruStepLayer).  Used
    inside recurrent_group step functions, with ``output_mem`` the memory that
    this layer's output feeds."""
    from paddle_tpu.layers import activation as act_mod
    from paddle_tpu.layers.api import _wspec
    from paddle_tpu.ops import rnn as rnn_ops

    size = size or input.size // 3
    name = name or gen_name("gru_step")
    # single fused recurrent weight [size, 3*size] like the reference
    # GruStepLayer parameter (dims [size, 3*size])
    w_spec = _wspec(param_attr, name, "w0", (size, 3 * size), I.paddle_default())
    specs = [w_spec]
    use_bias = bias_attr is not False
    bspec = None
    if use_bias:
        from paddle_tpu.layers.attr import ParamAttr
        battr = bias_attr if isinstance(bias_attr, ParamAttr) else None
        bspec = _wspec(battr, name, "wbias", (3 * size,), I.constant(0.0))
        specs.append(bspec)
    ga = act_mod.get(gate_act) if gate_act else act_mod.SigmoidActivation()
    sa = act_mod.get(act) if act else act_mod.TanhActivation()

    def fwd(ctx, params, states, x, h):
        xw = _raw_boot(x)
        if bspec is not None:
            xw = xw + params[bspec.name]
        w = params[w_spec.name]
        return rnn_ops.gru_cell(xw, _raw_boot(h), w[:, : 2 * size],
                                w[:, 2 * size:], ga, sa)

    return LayerOutput(name=name, layer_type="gru_step", size=size,
                       parents=(input, output_mem),
                       param_specs=tuple(specs), fn=fwd,
                       attrs={"active_type": sa.name,
                              "active_gate_type": ga.name,
                              "bias_spec": bspec.name if bspec else None})


def lstm_step_layer(input: LayerOutput, state: LayerOutput,
                    size: int | None = None, act=None, gate_act=None,
                    state_act=None, name: str | None = None,
                    bias_attr=None, param_attr=None):
    """One LSTM step (≅ lstm_step_layer, layers.py:3077 / LstmStepLayer):
    ``input`` is the pre-projected 4*D gate input, ``state`` the previous cell
    memory.  Returns (h_node, c_node); link the h-memory to h_node's name and
    the cell memory to c_node's name."""
    from paddle_tpu.layers import activation as act_mod
    from paddle_tpu.layers.api import _wspec
    from paddle_tpu.ops import rnn as rnn_ops

    size = size or input.size // 4
    name = name or gen_name("lstm_step")
    specs = []
    use_bias = bias_attr is not False
    bspec = None
    if use_bias:
        from paddle_tpu.layers.attr import ParamAttr
        battr = bias_attr if isinstance(bias_attr, ParamAttr) else None
        # reference LstmStepLayer bias is the 3*size PEEPHOLE weights
        # (W_ci/W_cf/W_co); gate biases live in the input projection
        bspec = _wspec(battr, name, "wbias", (3 * size,), I.constant(0.0))
        specs.append(bspec)
    ga = act_mod.get(gate_act) if gate_act else act_mod.SigmoidActivation()
    oa = act_mod.get(act) if act else act_mod.TanhActivation()
    sa = act_mod.get(state_act) if state_act else act_mod.TanhActivation()

    def cell(params, x, c_prev):
        gates = _raw_boot(x)
        cp = _raw_boot(c_prev)
        d = size
        gi = gates[:, 0 * d:1 * d]
        gf = gates[:, 1 * d:2 * d]
        gg = gates[:, 2 * d:3 * d]
        go = gates[:, 3 * d:4 * d]
        if bspec is not None:
            peep = params[bspec.name]
            gi = gi + peep[0 * d:1 * d] * cp
            gf = gf + peep[1 * d:2 * d] * cp
        i, f = ga(gi), ga(gf)
        c = f * cp + i * sa(gg)
        if bspec is not None:
            go = go + params[bspec.name][2 * d:3 * d] * c
        o = ga(go)
        h = o * oa(c)
        return h, c

    def fwd_h(ctx, params, states, x, c_prev):
        return cell(params, x, c_prev)[0]

    def fwd_c(ctx, params, states, x, c_prev):
        return cell(params, x, c_prev)[1]

    h_node = LayerOutput(name=name, layer_type="lstm_step", size=size,
                         parents=(input, state),
                         param_specs=tuple(specs), fn=fwd_h,
                         attrs={"active_type": oa.name,
                                "active_gate_type": ga.name,
                                "active_state_type": sa.name,
                                "bias_spec": bspec.name if bspec else None})
    c_node = LayerOutput(name=name + "@state", layer_type="get_output",
                         size=size, parents=(input, state), fn=fwd_c,
                         attrs={"arg_name": "state", "arg_of": name})
    h_node._state_node = c_node
    c_node.attrs["arg_of_node"] = h_node
    return h_node, c_node


def get_output_layer(input: LayerOutput, arg_name: str = "state",
                     name: str | None = None) -> LayerOutput:
    """≅ get_output_layer (layers.py:3728): expose a layer's secondary
    output (the lstm_step 'state' cell value)."""
    enforce(arg_name == "state" and hasattr(input, "_state_node"),
            "get_output_layer supports the lstm_step 'state' output")
    node = input._state_node
    if name:
        node.name = name
    return node


def lstmemory_group(input: LayerOutput, size: int | None = None,
                    name: str | None = None, reverse: bool = False,
                    out_memory=None, act=None, gate_act=None, state_act=None,
                    input_proj_bias_attr=None, input_proj_layer_attr=None,
                    mixed_bias_attr=None, lstm_bias_attr=None,
                    param_attr=None, mixed_layer_attr=None,
                    lstm_layer_attr=None) -> LayerOutput:
    """≅ networks.lstmemory_group: lstm built from in-group primitives so each
    step is addressable (memory/attention use-cases) — input_recurrent mixed
    (identity + fc-of-output-memory), lstm_step, state get_output."""
    from paddle_tpu.layers.mixed import (
        full_matrix_projection,
        identity_projection,
        mixed_layer,
    )

    name = name or gen_name("lstm_group")
    size = size or input.size // 4

    def step(ipt):
        out_mem = memory(name=name, size=size)
        state_mem = memory(name=f"{name}_state", size=size)
        bias = (input_proj_bias_attr if input_proj_bias_attr is not None
                else mixed_bias_attr)
        with mixed_layer(name=f"{name}_input_recurrent", size=size * 4,
                         bias_attr=bias,
                         layer_attr=input_proj_layer_attr) as m:
            m += identity_projection(input=ipt)
            m += full_matrix_projection(input=out_mem, param_attr=param_attr)
        h, c = lstm_step_layer(
            input=m, state=state_mem, name=name, size=size, act=act,
            gate_act=gate_act, state_act=state_act, bias_attr=lstm_bias_attr)
        get_output_layer(input=h, arg_name="state", name=f"{name}_state")
        return h

    return recurrent_group(
        name=f"{name}_recurrent_group", step=step, input=input,
        reverse=reverse)


def gru_group(input: LayerOutput, size: int | None = None,
              name: str | None = None, reverse: bool = False,
              act=None, gate_act=None, gru_bias_attr=None,
              gru_param_attr=None, gru_layer_attr=None) -> LayerOutput:
    """≅ networks.gru_group: gru from in-group primitives."""
    name = name or gen_name("gru_group")
    size = size or input.size // 3

    def step(ipt):
        out_mem = memory(name=name, size=size)
        return gru_step_layer(
            input=ipt, output_mem=out_mem, name=name, size=size, act=act,
            gate_act=gate_act, bias_attr=gru_bias_attr,
            param_attr=gru_param_attr)

    return recurrent_group(
        name=f"{name}_recurrent_group", step=step, input=input,
        reverse=reverse)
