"""Mixed layer + projections/operators.

Reference: ``MixedLayer`` composes cheap sub-units — Projections (one input,
may own a parameter: ``paddle/gserver/layers/Projection.h``,
``FullMatrixProjection``, ``TableProjection``, ``ContextProjection``,
``IdentityProjection``, ``ScalingProjection``, ``DotMulProjection``,
``TransposedFullMatrixProjection``) and Operators (multi-input, parameter-free:
``DotMulOperator``, ``ConvOperator``) — summing their outputs
(``trainer_config_helpers/layers.py:563-998`` helper surface,
``mixed_layer:739``).  Attention in 2017-Paddle NMT demos is hand-built from
exactly these pieces, so they are load-bearing for seq2seq parity.

TPU-native: a projection is a pure function on the input value; the mixed
node's fn sums projection outputs (XLA fuses the adds into the surrounding
matmuls).  Both the functional form ``mixed(input=[...])`` and the
``with mixed(size=..) as m: m += proj`` incremental form are supported."""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax

from paddle_tpu.core import initializer as I
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.core.parameters import ParamSpec
from paddle_tpu.layers import activation as act_mod
from paddle_tpu.layers.attr import ParamAttr
from paddle_tpu.layers.base import LayerOutput, gen_name, like, raw
from paddle_tpu.ops import sequence as seq_ops
from paddle_tpu.ops.embedding import lookup as emb_lookup
from paddle_tpu.ops.math import matmul


@dataclasses.dataclass
class Projection:
    """One summand inside a mixed layer (≅ Projection/Operator config)."""

    inputs: tuple[LayerOutput, ...]
    size: int
    proj_type: str
    param_specs: tuple[ParamSpec, ...] = ()
    # fn(params, *input_values) -> value with same sequence structure
    fn: Callable = None


def _wspec(param_attr, name, shape, default_init) -> ParamSpec:
    """Single source of truth for ParamAttr -> ParamSpec lives in api._wspec;
    this shim only adapts mixed's full-name convention (`<base>.<suffix>`)."""
    from paddle_tpu.layers.api import _wspec as api_wspec

    base, _, suffix = name.rpartition(".")
    return api_wspec(param_attr, base.lstrip("_"), suffix, shape, default_init)


def full_matrix_projection(input: LayerOutput, size: int,
                           param_attr: ParamAttr | None = None) -> Projection:
    """out = in @ W  (≅ FullMatrixProjection, layers.py:563)."""
    w = _wspec(param_attr, gen_name("fm_proj") + ".w", (input.size, size),
               I.paddle_default())

    def fn(params, v):
        return like(v, matmul(raw(v).reshape(-1, input.size),
                              params[w.name]).reshape(raw(v).shape[:-1] + (size,)))

    return Projection(inputs=(input,), size=size, proj_type="fc",
                      param_specs=(w,), fn=fn)


def trans_full_matrix_projection(input: LayerOutput, size: int,
                                 param_attr: ParamAttr | None = None) -> Projection:
    """out = in @ W^T — the parameter is stored transposed [size, in]
    (≅ TransposedFullMatrixProjection, layers.py:619)."""
    w = _wspec(param_attr, gen_name("trans_fm_proj") + ".w", (size, input.size),
               I.paddle_default())

    def fn(params, v):
        return like(v, matmul(raw(v).reshape(-1, input.size),
                              params[w.name].T).reshape(raw(v).shape[:-1] + (size,)))

    return Projection(inputs=(input,), size=size, proj_type="trans_fc",
                      param_specs=(w,), fn=fn)


def identity_projection(input: LayerOutput, offset: int | None = None,
                        size: int | None = None) -> Projection:
    """Pass-through, optionally a feature slice [offset, offset+size)
    (≅ IdentityProjection / IdentityOffsetProjection, layers.py:744)."""
    if offset is None:
        out_size = input.size

        def fn(params, v):
            return v
    else:
        out_size = size or (input.size - offset)

        def fn(params, v):
            return like(v, raw(v)[..., offset:offset + out_size])

    return Projection(inputs=(input,), size=out_size, proj_type="identity", fn=fn)


def scaling_projection(input: LayerOutput,
                       param_attr: ParamAttr | None = None) -> Projection:
    """out = w * in with a single learned scalar (≅ ScalingProjection,
    layers.py:802)."""
    w = _wspec(param_attr, gen_name("scaling_proj") + ".w", (1,), I.constant(1.0))

    def fn(params, v):
        return like(v, raw(v) * params[w.name][0])

    return Projection(inputs=(input,), size=input.size, proj_type="scaling",
                      param_specs=(w,), fn=fn)


def dotmul_projection(input: LayerOutput,
                      param_attr: ParamAttr | None = None) -> Projection:
    """out = in ⊙ w, elementwise with a learned vector (≅ DotMulProjection,
    layers.py:845)."""
    w = _wspec(param_attr, gen_name("dotmul_proj") + ".w", (input.size,),
               I.uniform(1.0))

    def fn(params, v):
        return like(v, raw(v) * params[w.name])

    return Projection(inputs=(input,), size=input.size, proj_type="dot_mul",
                      param_specs=(w,), fn=fn)


def table_projection(input: LayerOutput, size: int,
                     param_attr: ParamAttr | None = None) -> Projection:
    """Embedding rows summed into the mix: ids -> table[ids]
    (≅ TableProjection, layers.py:667)."""
    w = _wspec(param_attr, gen_name("table_proj") + ".w", (input.size, size),
               I.paddle_default())

    def fn(params, v):
        return like(v, emb_lookup(params[w.name], raw(v)))

    return Projection(inputs=(input,), size=size, proj_type="table",
                      param_specs=(w,), fn=fn)


def context_projection(input: LayerOutput, context_len: int,
                       context_start: int | None = None,
                       padding_attr: ParamAttr | bool | None = False) -> Projection:
    """Sliding-window concat of neighbor steps over a sequence
    (≅ ContextProjection, layers.py:889).  Trainable padding not supported;
    zero padding at sequence boundaries."""
    enforce(padding_attr is False or padding_attr is None,
            "trainable context padding is only supported via "
            "layer.context_projection_layer, not the mixed projection")
    ctx_start = -(context_len // 2) if context_start is None else context_start
    out_size = input.size * context_len

    def fn(params, v):
        enforce(isinstance(v, SequenceBatch),
                "context_projection needs sequence input")
        return seq_ops.context_projection(v, context_len, ctx_start)

    return Projection(inputs=(input,), size=out_size, proj_type="context",
                      fn=fn)


def dotmul_operator(a: LayerOutput, b: LayerOutput, scale: float = 1.0) -> Projection:
    """out = scale * (a ⊙ b) (≅ DotMulOperator, layers.py:921)."""
    enforce(a.size == b.size, "dotmul_operator inputs must share size")

    def fn(params, va, vb):
        return like(va, scale * raw(va) * raw(vb))

    return Projection(inputs=(a, b), size=a.size, proj_type="dot_mul_op", fn=fn)


def conv_operator(img: LayerOutput, filter: LayerOutput, filter_size: int,
                  num_filters: int, num_channels: int | None = None,
                  stride: int = 1, padding: int = 0,
                  filter_size_y: int | None = None, stride_y: int | None = None,
                  padding_y: int | None = None) -> Projection:
    """Convolution whose filter comes from another layer's output
    (≅ ConvOperator, layers.py:680).  filter value is reshaped to
    [num_filters, C, fh, fw]."""
    c = num_channels or img.depth
    fh = filter_size_y or filter_size
    fw = filter_size
    sy = stride_y or stride
    py = padding_y if padding_y is not None else padding
    h, w = img.height, img.width
    oh = (h + 2 * py - fh) // sy + 1
    ow = (w + 2 * padding - fw) // stride + 1

    def fn(params, vimg, vfilt):
        x = raw(vimg).reshape(-1, c, h, w)
        k = raw(vfilt).reshape(num_filters, c, fh, fw)
        out = jax.lax.conv_general_dilated(
            x, k, window_strides=(sy, stride),
            padding=((py, py), (padding, padding)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return like(vimg, out.reshape(out.shape[0], -1))

    return Projection(inputs=(img, filter), size=num_filters * oh * ow,
                      proj_type="conv_op", fn=fn)


class MixedLayerOutput(LayerOutput):
    """LayerOutput that also supports the incremental ``with``/``+=`` form."""

    def __iadd__(self, other: Projection):
        enforce(isinstance(other, Projection), "mixed += expects a Projection")
        enforce(not self._finalized, "mixed layer already finalized")
        self._projections.append(other)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            _finalize_mixed(self)
        return False


def mixed(size: int | None = None, input=None, name: str | None = None,
          act=None, bias_attr=None, layer_attr=None) -> MixedLayerOutput:
    """≅ mixed_layer (layers.py:739).  Sums its projection/operator inputs,
    adds bias, applies activation (default linear)."""
    name = name or gen_name("mixed")
    node = MixedLayerOutput(name=name, layer_type="mixed", size=size or 0)
    node._projections = []
    node._finalized = False
    node._act = act_mod.get(act) if act else act_mod.LinearActivation()
    node._bias_attr = bias_attr
    if input is not None:
        projs = input if isinstance(input, (list, tuple)) else [input]
        for p in projs:
            enforce(isinstance(p, Projection),
                    "mixed input must be projections/operators "
                    "(use fc/identity_projection/... helpers)")
            node._projections.append(p)
        _finalize_mixed(node)
    return node


mixed_layer = mixed


def _finalize_mixed(node: MixedLayerOutput) -> None:
    projs = node._projections
    enforce(len(projs) > 0, f"mixed layer {node.name!r} has no inputs")
    size = node.size or projs[0].size
    for p in projs:
        enforce(p.size == size,
                f"mixed layer {node.name!r}: projection size {p.size} != {size}")
    parents: list[LayerOutput] = []
    for p in projs:
        for inp in p.inputs:
            if inp not in parents:
                parents.append(inp)
    specs = tuple(s for p in projs for s in p.param_specs)
    # reference default: mixed_layer has NO bias (wrap_bias_attr_default(
    # has_bias=False), layers.py:853) — bias only when explicitly requested
    use_bias = node._bias_attr is True or isinstance(node._bias_attr, ParamAttr)
    bspec = None
    if use_bias:
        battr = node._bias_attr if isinstance(node._bias_attr, ParamAttr) else None
        bspec = _wspec(battr, f"_{node.name}.wbias", (size,), I.constant(0.0))
        specs = specs + (bspec,)
    act = node._act
    idx_of = {id(n): i for i, n in enumerate(parents)}

    def fwd(ctx, params, states, *parent_values):
        total = None
        template = None
        for p in projs:
            vals = [parent_values[idx_of[id(inp)]] for inp in p.inputs]
            out = p.fn(params, *vals)
            if template is None and isinstance(out, SequenceBatch):
                template = out
            total = raw(out) if total is None else total + raw(out)
        if bspec is not None:
            total = total + params[bspec.name]
        total = act(total)
        if template is not None:
            return SequenceBatch(data=total, length=template.length)
        return total

    node.size = size
    node.parents = tuple(parents)
    node.param_specs = specs
    node.fn = fwd
    node.attrs = {"projections": [p.proj_type for p in projs]}
    node._finalized = True
