"""Mixed layer + projections/operators.

Reference: ``MixedLayer`` composes cheap sub-units — Projections (one input,
may own a parameter: ``paddle/gserver/layers/Projection.h``,
``FullMatrixProjection``, ``TableProjection``, ``ContextProjection``,
``IdentityProjection``, ``ScalingProjection``, ``DotMulProjection``,
``TransposedFullMatrixProjection``, ``ConvProjection``) and Operators
(multi-input, parameter-free: ``DotMulOperator``, ``ConvOperator``) — summing
their outputs (``trainer_config_helpers/layers.py:563-998`` helper surface,
``mixed_layer:851``; config side ``config_parser.py:3387`` MixedLayer).
Attention in 2017-Paddle NMT demos is hand-built from exactly these pieces,
so they are load-bearing for seq2seq parity.

TPU-native: a projection is a pure function on the input value; the mixed
node's fn sums projection outputs (XLA fuses the adds into the surrounding
matmuls).  Parameters are named by the OWNING layer at finalize time
(``_<layer>.w<slot>``, ≅ gen_parameter_name), so protostr/checkpoint names
match the reference; each slot of the layer's input list is one projection
or an operator leg (operators' extra inputs appended at the end,
config_parser.py:3392-3405).  Both the functional form ``mixed(input=[..])``
and the ``with mixed(size=..) as m: m += proj`` incremental form work."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dt

from paddle_tpu.core import initializer as I
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.core.parameters import ParamSpec
from paddle_tpu.layers import activation as act_mod
from paddle_tpu.layers.attr import ParamAttr
from paddle_tpu.layers.base import LayerOutput, gen_name, like, raw
from paddle_tpu.ops import sequence as seq_ops
from paddle_tpu.ops.embedding import lookup as emb_lookup
from paddle_tpu.ops.math import matmul


@dataclasses.dataclass
class Projection:
    """One summand inside a mixed layer (≅ Projection/Operator config).

    The parameter (if any) is unnamed until the owning layer binds it:
    ``param_shape``/``param_attr``/``default_init`` describe it;
    ``make_fn(pname)`` builds the runtime closure; ``proto`` carries the
    reference ProjectionConfig/OperatorConfig extras for emission."""

    inputs: tuple[LayerOutput, ...]
    size: int  # output size (0 = adopt the mixed layer's)
    proj_type: str
    is_operator: bool = False
    param_shape: tuple | None = None
    param_attr: ParamAttr | None = None
    default_init: Callable | None = None
    # emission: ParameterConfig dims + default attr when user gave none
    param_dims: list | None = None
    default_emit_attr: ParamAttr | None = None
    make_fn: Callable = None  # (pname | None) -> fn(params, *vals)
    proto: dict = dataclasses.field(default_factory=dict)

    # set at bind time
    spec: Any = None

    def bind(self, pname: str) -> tuple[ParamSpec | None, Callable]:
        from paddle_tpu.layers.api import _wspec

        spec = None
        if self.param_shape is not None:
            base, _, suffix = pname.rpartition(".")
            spec = _wspec(self.param_attr, base[1:], suffix, self.param_shape,
                          self.default_init or I.paddle_default())
        self.spec = spec
        return spec, self.make_fn(spec.name if spec is not None else None)


def full_matrix_projection(input: LayerOutput, size: int = 0,
                           param_attr: ParamAttr | None = None) -> Projection:
    """out = in @ W  (≅ FullMatrixProjection, layers.py:563)."""

    def make_fn(pname):
        def fn(params, v):
            return like(v, matmul(raw(v).reshape(-1, input.size),
                                  params[pname]).reshape(raw(v).shape[:-1] + (-1,)))

        return fn

    return Projection(
        inputs=(input,), size=size, proj_type="fc",
        param_shape=None if size == 0 else (input.size, size),
        param_attr=param_attr, make_fn=make_fn,
        param_dims=[input.size, size],
    )


def trans_full_matrix_projection(input: LayerOutput, size: int = 0,
                                 param_attr: ParamAttr | None = None) -> Projection:
    """out = in @ W^T — parameter stored transposed [size, in]
    (≅ TransposedFullMatrixProjection, layers.py:619)."""

    def make_fn(pname):
        def fn(params, v):
            return like(v, matmul(raw(v).reshape(-1, input.size),
                                  params[pname].T).reshape(raw(v).shape[:-1] + (-1,)))

        return fn

    return Projection(
        inputs=(input,), size=size, proj_type="trans_fc",
        param_shape=None if size == 0 else (size, input.size),
        param_attr=param_attr, make_fn=make_fn,
        param_dims=[size, input.size],
    )


def identity_projection(input: LayerOutput, offset: int | None = None,
                        size: int | None = None) -> Projection:
    """Pass-through, optionally a feature slice [offset, offset+size)
    (≅ IdentityProjection / IdentityOffsetProjection, layers.py:744)."""
    if offset is None:
        def make_fn(pname):
            return lambda params, v: v

        return Projection(inputs=(input,), size=input.size,
                          proj_type="identity", make_fn=make_fn)
    out_size = size or (input.size - offset)

    def make_fn(pname):
        return lambda params, v: like(v, raw(v)[..., offset:offset + out_size])

    return Projection(inputs=(input,), size=out_size,
                      proj_type="identity_offset", make_fn=make_fn,
                      proto={"offset": offset})


def slice_projection(input: LayerOutput, slices) -> Projection:
    """Concat of feature slices [start, end) (≅ SliceProjection)."""
    slices = [tuple(s) for s in slices]
    out_size = sum(e - s for s, e in slices)

    def make_fn(pname):
        def fn(params, v):
            parts = [raw(v)[..., s:e] for s, e in slices]
            return like(v, jnp.concatenate(parts, axis=-1))

        return fn

    return Projection(inputs=(input,), size=out_size, proj_type="slice",
                      make_fn=make_fn, proto={"slices": slices})


def scaling_projection(input: LayerOutput,
                       param_attr: ParamAttr | None = None) -> Projection:
    """out = w * in with a single learned scalar (≅ ScalingProjection,
    layers.py:802)."""

    def make_fn(pname):
        return lambda params, v: like(v, raw(v) * params[pname][0])

    return Projection(inputs=(input,), size=input.size, proj_type="scaling",
                      param_shape=(1,), param_attr=param_attr,
                      default_init=I.constant(1.0), make_fn=make_fn,
                      param_dims=[1, 1])


def dotmul_projection(input: LayerOutput,
                      param_attr: ParamAttr | None = None) -> Projection:
    """out = in ⊙ w, elementwise with a learned vector (≅ DotMulProjection,
    layers.py:845)."""

    def make_fn(pname):
        return lambda params, v: like(v, raw(v) * params[pname])

    return Projection(inputs=(input,), size=input.size, proj_type="dot_mul",
                      param_shape=(input.size,), param_attr=param_attr,
                      default_init=I.uniform(1.0), make_fn=make_fn,
                      param_dims=[1, input.size])


def table_projection(input: LayerOutput, size: int = 0,
                     param_attr: ParamAttr | None = None) -> Projection:
    """Embedding rows summed into the mix: ids -> table[ids]
    (≅ TableProjection, layers.py:667)."""

    def make_fn(pname):
        return lambda params, v: like(v, emb_lookup(params[pname], raw(v)))

    return Projection(
        inputs=(input,), size=size, proj_type="table",
        param_shape=None if size == 0 else (input.size, size),
        param_attr=param_attr, make_fn=make_fn,
        param_dims=[input.size, size],
    )


def context_projection(input: LayerOutput, context_len: int,
                       context_start: int | None = None,
                       padding_attr=None) -> Projection:
    """Sliding-window concat of neighbor steps over a sequence
    (≅ ContextProjection, layers.py:889).  With a ParamAttr (or the default),
    boundary padding rows are trainable (config_parser.py:665
    ContextProjection: param dims [total_pad, input_size])."""
    ctx_start = -(context_len - 1) // 2 if context_start is None else context_start
    out_size = input.size * context_len
    begin_pad = max(0, -ctx_start)
    end_pad = max(0, ctx_start + context_len - 1)
    total_pad = begin_pad + end_pad
    trainable = padding_attr is not False and total_pad > 0
    attr = padding_attr if isinstance(padding_attr, ParamAttr) else None

    def make_fn(pname):
        def fn(params, v):
            enforce(isinstance(v, SequenceBatch),
                    "context_projection needs sequence input")
            out = seq_ops.context_projection(v, context_len, ctx_start)
            if pname is not None:
                # overwrite the zero-padded boundary windows with the
                # trainable padding rows (reference ContextProjection)
                pad = params[pname]  # [total_pad, D]
                data = out.data.reshape(
                    out.data.shape[0], out.data.shape[1], context_len, -1)
                t = data.shape[1]
                steps = jnp.arange(t)
                for j in range(context_len):
                    off = ctx_start + j
                    src = steps + off
                    if off < 0:
                        row = pad[jnp.clip(src, -begin_pad, -1) + begin_pad]
                        data = data.at[:, :, j].set(
                            jnp.where((src < 0)[None, :, None], row[None],
                                      data[:, :, j]))
                    elif off > 0:
                        over = src - (out.length[:, None] - 1)
                        row = pad[jnp.clip(
                            begin_pad + over - 1, begin_pad,
                            total_pad - 1 if total_pad else 0)]
                        data = data.at[:, :, j].set(
                            jnp.where((over > 0)[..., None], row, data[:, :, j]))
                return SequenceBatch(
                    data=data.reshape(out.data.shape), length=out.length)
            return out

        return fn

    return Projection(
        inputs=(input,), size=out_size, proj_type="context",
        param_shape=(total_pad, input.size) if trainable else None,
        param_attr=attr, default_init=I.constant(0.0), make_fn=make_fn,
        param_dims=[total_pad, input.size],
        default_emit_attr=ParamAttr(initial_mean=0.0, initial_std=0.0),
        proto={"context_start": ctx_start, "context_length": context_len,
               "trainable_padding": trainable},
    )


def _conv_geometry(img: LayerOutput, filter_size, filter_size_y, stride,
                   stride_y, padding, padding_y, channels, num_filters,
                   groups, trans):
    """ConvConfig numbers the reference computes in parse_conv
    (config_parser.py:1369)."""
    from paddle_tpu.config.proto_emit import cnn_image_size, cnn_output_size

    fh = filter_size_y or filter_size
    fw = filter_size
    sy = stride_y or stride
    sx = stride
    py = padding_y if padding_y is not None else padding
    px = padding
    from paddle_tpu.config.proto_emit import get_img_size

    iw, ih = get_img_size(img, channels)
    g = dict(filter_size=fw, filter_size_y=fh, channels=channels,
             stride=sx, stride_y=sy, padding=px, padding_y=py,
             groups=groups, caffe_mode=True)
    if not trans:
        g["filter_channels"] = channels // groups
        g["img_size"], g["img_size_y"] = iw, ih
        g["output_x"] = cnn_output_size(iw, fw, px, sx, True)
        g["output_y"] = cnn_output_size(ih, fh, py, sy, True)
        out_x, out_y = g["output_x"], g["output_y"]
    else:
        g["filter_channels"] = num_filters // groups
        g["output_x"], g["output_y"] = iw, ih
        g["img_size"] = cnn_image_size(iw, fw, px, sx, True)
        g["img_size_y"] = cnn_image_size(ih, fh, py, sy, True)
        out_x, out_y = g["img_size"], g["img_size_y"]
    return g, num_filters * out_x * out_y, (out_y, out_x)


def conv_projection(input: LayerOutput, filter_size: int, num_filters: int,
                    num_channels: int | None = None, stride: int = 1,
                    padding: int = 0, filter_size_y: int | None = None,
                    stride_y: int | None = None, padding_y: int | None = None,
                    groups: int = 1, param_attr: ParamAttr | None = None,
                    trans: bool = False) -> Projection:
    """Convolution with its own learned filter (≅ ConvProjection /
    ConvTransProjection, layers.py:684)."""
    c = num_channels or input.depth
    g, out_size, (oh, ow) = _conv_geometry(
        input, filter_size, filter_size_y, stride, stride_y, padding,
        padding_y, c, num_filters, groups, trans)
    fh, fw = g["filter_size_y"], g["filter_size"]

    def make_fn(pname):
        def fn(params, v):
            from paddle_tpu.ops import nn as nn_ops

            hh = input.height or int((input.size // c) ** 0.5)
            wwid = input.width or (input.size // c) // hh
            x = raw(v).reshape(-1, c, hh, wwid).transpose(0, 2, 3, 1)
            k = params[pname].reshape(num_filters, c // groups, fh, fw)
            k = k.transpose(2, 3, 1, 0)  # HWIO
            if trans:
                y = nn_ops.conv2d_transpose(
                    x, k.transpose(0, 1, 3, 2), (g["stride_y"], g["stride"]),
                    (g["padding_y"], g["padding"]))
            else:
                y = nn_ops.conv2d(x, k, (g["stride_y"], g["stride"]),
                                  (g["padding_y"], g["padding"]), groups=groups)
            return like(v, y.transpose(0, 3, 1, 2).reshape(y.shape[0], -1))

        return fn

    # ConvBaseProjection.calc_parameter_size: co*ci*fh*fw/groups (same for
    # trans — ci is conv_conf.channels, not filter_channels)
    psize = num_filters * c * fh * fw // groups
    init_std = (2.0 / (filter_size ** 2 * c)) ** 0.5
    return Projection(
        inputs=(input,), size=out_size,
        proj_type="convt" if trans else "conv",
        param_shape=(psize,), param_attr=param_attr,
        default_init=I.paddle_default(0.0, init_std), make_fn=make_fn,
        param_dims=[],
        default_emit_attr=ParamAttr(initial_mean=0.0, initial_std=init_std),
        proto={"conv": g, "num_filters": num_filters},
    )


def dotmul_operator(a: LayerOutput, b: LayerOutput, scale=1) -> Projection:
    """out = scale * (a ⊙ b) (≅ DotMulOperator, layers.py:921)."""
    enforce(a.size == b.size, "dotmul_operator inputs must share size")

    def make_fn(pname):
        return lambda params, va, vb: like(va, scale * raw(va) * raw(vb))

    return Projection(inputs=(a, b), size=a.size, proj_type="dot_mul",
                      is_operator=True, make_fn=make_fn,
                      proto={"dotmul_scale": scale})


def conv_operator(img: LayerOutput, filter: LayerOutput, filter_size: int,
                  num_filters: int, num_channels: int | None = None,
                  stride: int = 1, padding: int = 0,
                  filter_size_y: int | None = None, stride_y: int | None = None,
                  padding_y: int | None = None,
                  trans: bool = False) -> Projection:
    """Convolution whose filter comes from another layer's output
    (≅ ConvOperator / ConvTransOperator, layers.py:680)."""
    c = num_channels or img.depth
    g, out_size, (oh, ow) = _conv_geometry(
        img, filter_size, filter_size_y, stride, stride_y, padding,
        padding_y, c, num_filters, 1, trans)
    fh, fw = g["filter_size_y"], g["filter_size"]

    def make_fn(pname):
        def fn(params, vimg, vfilt):
            hh = img.height or int((img.size // c) ** 0.5)
            ww = img.width or (img.size // c) // hh
            x = raw(vimg).reshape(-1, c, hh, ww)
            k = raw(vfilt).reshape(num_filters, c, fh, fw)
            if trans:
                out = jax.lax.conv_transpose(
                    x.transpose(0, 2, 3, 1),
                    k.transpose(2, 3, 0, 1),  # HWOI -> use IO swap below
                    strides=(g["stride_y"], g["stride"]),
                    padding=((g["padding_y"], g["padding_y"]),
                             (g["padding"], g["padding"])),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    transpose_kernel=True,
                    precision=dt.dot_precision(x, k),
                )
                return like(vimg, out.transpose(0, 3, 1, 2).reshape(out.shape[0], -1))
            out = jax.lax.conv_general_dilated(
                x, k, window_strides=(g["stride_y"], g["stride"]),
                padding=((g["padding_y"], g["padding_y"]),
                         (g["padding"], g["padding"])),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                precision=dt.dot_precision(x, k))
            return like(vimg, out.reshape(out.shape[0], -1))

        return fn

    return Projection(inputs=(img, filter), size=out_size,
                      proj_type="convt" if trans else "conv",
                      is_operator=True, make_fn=make_fn,
                      proto={"conv": g, "num_filters": num_filters})


conv_projection_layer = conv_projection


class MixedLayerOutput(LayerOutput):
    """LayerOutput that also supports the incremental ``with``/``+=`` form."""

    def __iadd__(self, other: Projection):
        enforce(isinstance(other, Projection), "mixed += expects a Projection")
        enforce(not self._finalized, "mixed layer already finalized")
        self._projections.append(other)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            _finalize_mixed(self)
        return False


def mixed(size: int | None = None, input=None, name: str | None = None,
          act=None, bias_attr=None, layer_attr=None) -> MixedLayerOutput:
    """≅ mixed_layer (layers.py:851).  Sums its projection/operator inputs,
    adds bias, applies activation (default linear)."""
    name = name or gen_name("mixed")
    node = MixedLayerOutput(name=name, layer_type="mixed", size=size or 0)
    node._projections = []
    node._finalized = False
    node._act = act_mod.get(act) if act else act_mod.LinearActivation()
    node._bias_attr = bias_attr
    node._layer_attr = layer_attr
    if input is not None:
        projs = input if isinstance(input, (list, tuple)) else [input]
        for p in projs:
            enforce(isinstance(p, Projection),
                    "mixed input must be projections/operators "
                    "(use full_matrix_projection/identity_projection/...)")
            node._projections.append(p)
        _finalize_mixed(node)
    return node


mixed_layer = mixed


def _finalize_mixed(node: MixedLayerOutput) -> None:
    projs = node._projections
    enforce(len(projs) > 0, f"mixed layer {node.name!r} has no inputs")
    size = node.size or 0
    if not size:
        for p in projs:
            if p.size:
                size = p.size
                break
    enforce(size, f"mixed layer {node.name!r}: size is not set")
    for p in projs:
        if p.size == 0:  # fc/table with size elided adopt the layer size
            p.size = size
            if p.proj_type in ("fc", "table"):
                p.param_shape = (p.inputs[0].size, size)
                p.param_dims = [p.inputs[0].size, size]
            elif p.proj_type == "trans_fc":
                p.param_shape = (size, p.inputs[0].size)
                p.param_dims = [size, p.inputs[0].size]
        enforce(p.size == size,
                f"mixed layer {node.name!r}: projection size {p.size} != {size}")

    # slot layout (≅ MixedLayer config class): one slot per projection /
    # operator first leg, then operators' extra legs appended at the end
    slots: list[LayerOutput] = []
    fns = []  # (fn, [slot indices])
    specs: list[ParamSpec] = []
    items = []  # emission records
    op_extras = []
    for p in projs:
        idx = len(slots)
        pname = f"_{node.name}.w{idx}"
        if p.is_operator:
            slots.append(p.inputs[0])
            _, fn = p.bind(pname)
            rec = {"kind": "op", "type": p.proj_type,
                   "indices": [idx], "output_size": p.size,
                   "proto": dict(p.proto)}
            items.append(rec)
            op_extras.append((p, rec, fn))
        else:
            spec, fn = p.bind(pname)
            slots.append(p.inputs[0])
            if spec is not None:
                specs.append(spec)
            fns.append((fn, [idx]))
            items.append({
                "kind": "proj", "type": p.proj_type, "slot": idx,
                "pname": pname, "spec": spec,
                "input_size": p.inputs[0].size, "output_size": p.size,
                "param_dims": p.param_dims,
                "default_emit_attr": p.default_emit_attr,
                "proto": dict(p.proto),
            })
    for p, rec, fn in op_extras:
        for extra in p.inputs[1:]:
            rec["indices"].append(len(slots))
            slots.append(extra)
        rec["input_sizes"] = [slots[i].size for i in rec["indices"]]
        fns.append((fn, list(rec["indices"])))

    use_bias = node._bias_attr is True or isinstance(node._bias_attr, ParamAttr)
    bspec = None
    if use_bias:
        from paddle_tpu.layers.api import _wspec

        battr = node._bias_attr if isinstance(node._bias_attr, ParamAttr) else None
        bspec = _wspec(battr, node.name, "wbias", (size,), I.constant(0.0))
        specs.append(bspec)
    act = node._act

    def fwd(ctx, params, states, *slot_values):
        total = None
        template = None
        for fn, idxs in fns:
            out = fn(params, *[slot_values[i] for i in idxs])
            if template is None and isinstance(out, SequenceBatch):
                template = out
            total = raw(out) if total is None else total + raw(out)
        if bspec is not None:
            total = total + params[bspec.name]
        total = act(total)
        if template is not None:
            return SequenceBatch(data=total, length=template.length)
        return total

    node.size = size
    node.parents = tuple(slots)
    node.param_specs = tuple(specs)
    node.fn = fwd
    node.attrs = {"mixed_items": items, "active_type": act.name}
    node._finalized = True
    if node._layer_attr is not None:
        from paddle_tpu.layers.api import _maybe_dropout

        if getattr(node._layer_attr, "error_clipping_threshold", None):
            node.attrs["error_clipping_threshold"] = (
                node._layer_attr.error_clipping_threshold
            )
        _maybe_dropout(node, node._layer_attr)
