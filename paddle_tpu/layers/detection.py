"""SSD detection layers — priorbox, multibox_loss, detection_output.

Reference parity: ``paddle/gserver/layers/PriorBox.cpp`` (per-cell anchor
generation), ``MultiBoxLossLayer.cpp`` (bipartite+threshold matching,
smooth-L1 loc loss, softmax conf loss with 3:1 hard-negative mining) and
``DetectionOutputLayer.cpp`` (decode + per-class NMS + top-k), over the
box math in ``paddle_tpu/ops/detection.py``.

Ground truth feeds as a dense ``[B, G_max, 5]`` array of
``[label, xmin, ymin, xmax, ymax]`` rows padded with label -1 (the
fixed-shape TPU stand-in for the reference's variable-length label
sequences)."""

from __future__ import annotations

import math as _pymath

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.ops import detection as D
from paddle_tpu.ops.loss import smooth_l1
from paddle_tpu.layers.base import LayerOutput, gen_name, raw


def priorbox(input: LayerOutput, image_size, min_size, max_size=(),
             aspect_ratio=(2.0,), variance=(0.1, 0.1, 0.2, 0.2),
             name: str | None = None) -> LayerOutput:
    """≅ priorbox (PriorBoxLayer): one anchor set per feature-map cell.

    Per cell: a min_size square, a sqrt(min*max) square per max_size, and
    a pair of w/h-swapped boxes per aspect ratio.  Output value
    [P, 8]: corner box + its 4 variances (priors are data-independent;
    the array is a compile-time constant folded into the XLA program)."""
    name = name or gen_name("priorbox")
    fh, fw = input.height, input.width
    enforce(fh and fw, "priorbox needs a feature-map input with h/w")
    img_w, img_h = (image_size if isinstance(image_size, (tuple, list))
                    else (image_size, image_size))
    mins = [min_size] if np.isscalar(min_size) else list(min_size)
    maxs = [max_size] if np.isscalar(max_size) else list(max_size)

    boxes = []
    step_x, step_y = 1.0 / fw, 1.0 / fh
    for y in range(fh):
        for x in range(fw):
            cx, cy = (x + 0.5) * step_x, (y + 0.5) * step_y
            for i, ms in enumerate(mins):
                bw, bh = ms / img_w, ms / img_h
                boxes.append([cx - bw / 2, cy - bh / 2,
                              cx + bw / 2, cy + bh / 2])
                if i < len(maxs):
                    s = _pymath.sqrt(ms * maxs[i])
                    bw, bh = s / img_w, s / img_h
                    boxes.append([cx - bw / 2, cy - bh / 2,
                                  cx + bw / 2, cy + bh / 2])
                for ar in aspect_ratio:
                    for a in (ar, 1.0 / ar):
                        bw = ms * _pymath.sqrt(a) / img_w
                        bh = ms / _pymath.sqrt(a) / img_h
                        boxes.append([cx - bw / 2, cy - bh / 2,
                                      cx + bw / 2, cy + bh / 2])
    prior_arr = np.clip(np.asarray(boxes, np.float32), 0.0, 1.0)
    n_priors = prior_arr.shape[0]
    var_arr = np.tile(np.asarray(variance, np.float32), (n_priors, 1))
    value = np.concatenate([prior_arr, var_arr], axis=1)

    def fwd(ctx, params, states, x):
        return jnp.asarray(value)

    return LayerOutput(name=name, layer_type="priorbox", size=n_priors * 8,
                       parents=(input,), fn=fwd,
                       attrs={"num_priors": n_priors})


def _gather_preds(loc_layers, conf_layers, num_classes):
    """Concatenate per-scale conv outputs into [B, P, 4] / [B, P, C]."""

    def cat(vals, last):
        flat = [raw(v).reshape(raw(v).shape[0], -1, last) for v in vals]
        return jnp.concatenate(flat, axis=1)

    return cat(loc_layers, 4), cat(conf_layers, num_classes)


def multibox_loss(priors: LayerOutput, label: LayerOutput,
                  loc_layers, conf_layers, num_classes: int,
                  overlap_threshold: float = 0.5,
                  neg_pos_ratio: float = 3.0,
                  neg_overlap: float = 0.5, background_id: int = 0,
                  name: str | None = None) -> LayerOutput:
    """≅ multibox_loss (MultiBoxLossLayer).  Class 0 is background;
    gt labels are 1-based object classes."""
    name = name or gen_name("multibox_loss")
    loc_layers = list(loc_layers)
    conf_layers = list(conf_layers)

    def fwd(ctx, params, states, pri, lbl, *preds):
        loc_vals = preds[:len(loc_layers)]
        conf_vals = preds[len(loc_layers):]
        loc, conf = _gather_preds(loc_vals, conf_vals, num_classes)
        prior_boxes = pri[:, :4]
        variance = pri[0, 4:8]
        gt = raw(lbl)  # [B, G, 5]
        b, n_p = loc.shape[0], prior_boxes.shape[0]

        def per_image(loc_i, conf_i, gt_i):
            gt_valid = (gt_i[:, 0] >= 0).astype(jnp.float32)
            gt_boxes = gt_i[:, 1:5]
            match_idx, pos = D.match_priors(
                prior_boxes, gt_boxes, gt_valid, overlap_threshold)
            n_pos = jnp.sum(pos)
            # localisation: smooth-L1 on positives
            matched = gt_boxes[match_idx]
            target = D.encode_boxes(matched, prior_boxes, variance)
            loc_l = jnp.sum(smooth_l1(loc_i, target) * pos)  # [P] masked
            # confidence: softmax CE; target class = gt label+? (labels are
            # 1-based already, background 0)
            cls = jnp.where(pos, gt_i[match_idx, 0].astype(jnp.int32), 0)
            logp = jax.nn.log_softmax(conf_i, axis=-1)
            ce = -jnp.take_along_axis(logp, cls[:, None], axis=1)[:, 0]
            # hard negative mining: top (ratio * n_pos) negatives by loss
            neg_loss = jnp.where(pos, -jnp.inf, ce)
            order = jnp.argsort(-neg_loss)
            rank = jnp.zeros((n_p,), jnp.int32).at[order].set(
                jnp.arange(n_p, dtype=jnp.int32))
            n_neg = jnp.minimum(
                (neg_pos_ratio * n_pos).astype(jnp.int32),
                n_p - n_pos.astype(jnp.int32))
            neg = (rank < n_neg) & ~pos
            conf_l = jnp.sum(ce * (pos | neg))
            return loc_l, conf_l, n_pos

        loc_l, conf_l, n_pos = jax.vmap(per_image)(loc, conf, gt)
        denom = jnp.maximum(jnp.sum(n_pos), 1.0)
        return (jnp.sum(loc_l) + jnp.sum(conf_l)) / denom

    return LayerOutput(
        name=name, layer_type="multibox_loss", size=1,
        parents=tuple([priors, label] + loc_layers + conf_layers), fn=fwd,
        attrs={"num_classes": num_classes,
               "overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio,
               "neg_overlap": neg_overlap, "background_id": background_id,
               "input_num": len(loc_layers)},
    )


def multibox_loss_layer(input_loc, input_conf, priorbox, label, num_classes,
                        overlap_threshold=0.5, neg_pos_ratio=3.0,
                        neg_overlap=0.5, background_id=0, name=None):
    """v1 surface (layers.py:1156)."""
    locs = input_loc if isinstance(input_loc, (list, tuple)) else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) else [input_conf]
    return multibox_loss(
        priors=priorbox, label=label, loc_layers=list(locs),
        conf_layers=list(confs), num_classes=num_classes,
        overlap_threshold=overlap_threshold, neg_pos_ratio=neg_pos_ratio,
        neg_overlap=neg_overlap, background_id=background_id, name=name)


def detection_output(priors: LayerOutput, loc_layers, conf_layers,
                     num_classes: int, nms_threshold: float = 0.45,
                     nms_top_k: int = 400, keep_top_k: int = 200,
                     confidence_threshold: float = 0.01,
                     background_id: int = 0,
                     name: str | None = None) -> LayerOutput:
    """≅ detection_output (DetectionOutputLayer): decode + per-class NMS.

    Output [B, keep_top_k, 6] rows [label, score, xmin, ymin, xmax, ymax];
    empty slots have label -1 (fixed shape instead of the reference's
    variable-row output)."""
    name = name or gen_name("detection_output")
    loc_layers = list(loc_layers)
    conf_layers = list(conf_layers)

    def fwd(ctx, params, states, pri, *preds):
        loc_vals = preds[:len(loc_layers)]
        conf_vals = preds[len(loc_layers):]
        loc, conf = _gather_preds(loc_vals, conf_vals, num_classes)
        prior_boxes = pri[:, :4]
        variance = pri[0, 4:8]

        def per_image(loc_i, conf_i):
            boxes = D.decode_boxes(loc_i, prior_boxes, variance)
            probs = jax.nn.softmax(conf_i, axis=-1)  # [P, C]
            outs = []
            for c in range(num_classes):
                if c == background_id:
                    continue
                idxs, valid = D.nms(
                    boxes, probs[:, c], nms_threshold,
                    max_out=min(nms_top_k, boxes.shape[0]),
                    score_threshold=confidence_threshold)
                sel = jnp.clip(idxs, 0)
                rows = jnp.concatenate([
                    jnp.where(valid, float(c), -1.0)[:, None],
                    jnp.where(valid, probs[sel, c], 0.0)[:, None],
                    boxes[sel] * valid[:, None],
                ], axis=1)
                outs.append(rows)
            allrows = jnp.concatenate(outs, axis=0)
            top = jnp.argsort(-allrows[:, 1])[:keep_top_k]
            return allrows[top]

        rows = jax.vmap(per_image)(loc, conf)  # [B, K, 6]
        # reference rows are 7-wide: [image_id, label, score, box*4]
        b = rows.shape[0]
        img_ids = jnp.broadcast_to(
            jnp.arange(b, dtype=rows.dtype)[:, None, None],
            (b, rows.shape[1], 1))
        return jnp.concatenate([img_ids, rows], axis=-1)

    return LayerOutput(
        name=name, layer_type="detection_output", size=keep_top_k * 7,
        parents=tuple([priors] + loc_layers + conf_layers), fn=fwd,
        attrs={"num_classes": num_classes, "nms_threshold": nms_threshold,
               "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
               "confidence_threshold": confidence_threshold,
               "background_id": background_id,
               "input_num": len(loc_layers)},
    )


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                           confidence_threshold=0.01, background_id=0,
                           name=None):
    """v1 surface (layers.py:1228): loc/conf given as layers or lists."""
    locs = input_loc if isinstance(input_loc, (list, tuple)) else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) else [input_conf]
    return detection_output(
        priors=priorbox, loc_layers=list(locs), conf_layers=list(confs),
        num_classes=num_classes, nms_threshold=nms_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        confidence_threshold=confidence_threshold,
        background_id=background_id, name=name)
