"""Structured-prediction + assorted layers: CRF, CTC, and friends.

Reference parity: ``crf_layer`` (layers.py:5065, CRFLayer),
``crf_decoding_layer`` (layers.py:5134, CRFDecodingLayer), ``ctc_layer``
(layers.py:5189 — blank is the LAST category index), ``warp_ctc_layer``
(layers.py:5251 — blank configurable, default 0), ``linear_comb_layer``
(layers.py:5875), ``out_prod_layer`` (layers.py:4068), ``repeat_layer``
(layers.py:1807), ``kmax_seq_score`` (layers.py:6371)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dt

from paddle_tpu.core import initializer as I
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.layers.api import _wspec
from paddle_tpu.layers.base import companion_name, LayerOutput, gen_name, is_sequence, raw
from paddle_tpu.ops import crf as crf_ops
from paddle_tpu.ops import ctc as ctc_ops


def crf(input: LayerOutput, label: LayerOutput, size: int | None = None,
        weight: LayerOutput | None = None, param_attr=None,
        name: str | None = None, layer_attr=None,
        coeff: float | None = None) -> LayerOutput:
    """CRF negative log-likelihood cost (≅ crf_layer / LinearChainCRF).
    ``input`` are per-step emission scores [*, size]; parameter is the
    reference's [size+2, size] start/end/transition matrix.  To share the
    transitions with ``crf_decoding``, give both the same param_attr name."""
    name = name or gen_name("crf_layer")
    size = size or input.size
    w = _wspec(param_attr, name, "w0", (size + 2, size), I.paddle_default())
    parents = [input, label] + ([weight] if weight is not None else [])

    def fwd(ctx, params, states, emis, lbl, *wgt):
        enforce(is_sequence(emis), "crf expects sequence emissions")
        lbl_seq = lbl if is_sequence(lbl) else SequenceBatch(
            raw(lbl), emis.length)
        nll = crf_ops.crf_nll(emis, lbl_seq, params[w.name])  # [B]
        if wgt:
            nll = nll * raw(wgt[0]).reshape(-1)
        # reference crf_layer coeff: scales the cost (and thus gradients)
        return jnp.mean(nll) * (1.0 if coeff is None else coeff)

    return LayerOutput(name=name, layer_type="crf", size=1,
                       parents=tuple(parents), param_specs=(w,), fn=fwd,
                       attrs={"num_classes": size})


crf_layer = crf


def crf_decoding(input: LayerOutput, size: int | None = None,
                 label: LayerOutput | None = None, param_attr=None,
                 name: str | None = None, layer_attr=None) -> LayerOutput:
    """Viterbi decode (≅ crf_decoding_layer).  Without ``label``: outputs the
    best path ids as an int sequence.  With ``label``: outputs a 0/1 error
    indicator per sequence (1 = path differs), like the reference."""
    name = name or gen_name("crf_decoding_layer")
    size = size or input.size
    w = _wspec(param_attr, name, "w0", (size + 2, size), I.paddle_default())
    parents = [input] + ([label] if label is not None else [])

    def fwd(ctx, params, states, emis, *lbl):
        enforce(is_sequence(emis), "crf_decoding expects sequence emissions")
        path = crf_ops.crf_decode(emis, params[w.name])
        if not lbl:
            return path
        y = raw(lbl[0]).astype(jnp.int32)
        mask = emis.mask()
        diff = (path.data != y) & (mask > 0)
        return jnp.any(diff, axis=1).astype(jnp.float32)[:, None]

    node = LayerOutput(name=name, layer_type="crf_decoding",
                       size=(1 if label is not None else size),
                       parents=tuple(parents), param_specs=(w,), fn=fwd,
                       attrs={"num_classes": size})
    if label is not None:
        # the reference Argument carries BOTH the error indicator (value)
        # and the decoded path (ids); evaluators like chunk F1 consume the
        # ids (ChunkEvaluator::evalImp reads arguments[0].ids).  Expose the
        # path as a hidden companion layer "<name>#ids" — XLA CSEs the
        # duplicate Viterbi pass, and the evaluator runtime prefers it.
        def ids_fwd(ctx, params, states, emis):
            return crf_ops.crf_decode(emis, params[w.name])

        LayerOutput(name=companion_name(name), layer_type="crf_decoding",
                    size=size, parents=(input,), param_specs=(w,),
                    fn=ids_fwd, attrs={"num_classes": size,
                                       "__hidden__": True})
    return node


crf_decoding_layer = crf_decoding


def _fused_ctc_on() -> bool:
    """Route the CTC cost through ops/pallas/ctc when the fused_kernels
    flag resolves on.  impl="auto" inside the fused entry still picks
    the scan references off-TPU, so a flag-on CPU run (the bench
    ablation) computes EXACTLY the unfused program."""
    from paddle_tpu.ops.pallas.tpp import fused_enabled

    return fused_enabled()


def ctc(input: LayerOutput, label: LayerOutput, size: int | None = None,
        name: str | None = None, norm_by_times: bool = False) -> LayerOutput:
    """CTC cost (≅ ctc_layer / CTCLayer): ``input`` is post-softmax
    probabilities with ``size = num_classes + 1`` and blank = size-1 (the
    reference's convention for ctc_layer)."""
    name = name or gen_name("ctc_layer")
    size = size or (label.size + 1)  # reference: label classes + blank
    if input.size != size:
        from paddle_tpu.core import logger

        logger.warning(
            "ctc layer %s: input size %d != num_classes+1 (%d); the blank "
            "index follows `size`, matching the reference's CTCLayer",
            name, input.size, size)
    blank = size - 1

    def fwd(ctx, params, states, probs, lbl):
        enforce(is_sequence(probs) and is_sequence(lbl),
                "ctc expects sequence probs and labels")
        if _fused_ctc_on():
            # fused forward-backward kernel on TPU (hand-derived grad,
            # no jax.grad re-trace of the alpha scan); the reference
            # resolution on CPU is bit-identical to the unfused path
            from paddle_tpu.ops.pallas.ctc import ctc_loss_fused

            loss = ctc_loss_fused(
                jnp.log(jnp.clip(probs.data, 1e-12)), probs.length,
                raw(lbl).astype(jnp.int32), lbl.length, blank=blank)
        else:
            loss = ctc_ops.ctc_loss_from_probs(
                probs.data, probs.length, raw(lbl).astype(jnp.int32),
                lbl.length, blank=blank)
        if norm_by_times:
            loss = loss / jnp.maximum(probs.length.astype(loss.dtype), 1.0)
        return jnp.mean(loss)

    return LayerOutput(name=name, layer_type="ctc", size=size,
                       parents=(input, label), fn=fwd,
                       attrs={"blank": blank, "norm_by_times": norm_by_times})


ctc_layer = ctc


def warp_ctc(input: LayerOutput, label: LayerOutput, size: int | None = None,
             blank: int = 0, norm_by_times: bool = False,
             name: str | None = None) -> LayerOutput:
    """warp-ctc parity (≅ warp_ctc_layer / WarpCTCLayer): ``input`` is
    pre-softmax activations; softmax happens inside, blank defaults to 0."""
    name = name or gen_name("warp_ctc_layer")
    size = size or (label.size + 1)
    if input.size != size:
        from paddle_tpu.core import logger

        logger.warning(
            "warp_ctc layer %s: input size %d != num_classes+1 (%d)",
            name, input.size, size)

    def fwd(ctx, params, states, logits, lbl):
        enforce(is_sequence(logits) and is_sequence(lbl),
                "warp_ctc expects sequence logits and labels")
        if _fused_ctc_on():
            # normalize=True folds the log-softmax into the fused kernel
            # (the [B, T, V] log-prob slab never lands in HBM on TPU)
            from paddle_tpu.ops.pallas.ctc import ctc_loss_fused

            loss = ctc_loss_fused(
                logits.data, logits.length, raw(lbl).astype(jnp.int32),
                lbl.length, blank=blank, normalize=True)
        else:
            log_probs = jax.nn.log_softmax(logits.data, axis=-1)
            loss = ctc_ops.ctc_loss(
                log_probs, logits.length, raw(lbl).astype(jnp.int32),
                lbl.length, blank=blank)
        if norm_by_times:
            loss = loss / jnp.maximum(logits.length.astype(loss.dtype), 1.0)
        return jnp.mean(loss)

    return LayerOutput(name=name, layer_type="warp_ctc", size=size,
                       parents=(input, label), fn=fwd,
                       attrs={"blank": blank, "norm_by_times": norm_by_times,
                              "explicit_blank": True})


warp_ctc_layer = warp_ctc


def out_prod(input1: LayerOutput, input2: LayerOutput,
             name: str | None = None) -> LayerOutput:
    """Outer product of two vectors per batch row (≅ out_prod_layer)."""
    name = name or gen_name("out_prod_layer")

    def fwd(ctx, params, states, a, b):
        av, bv = raw(a), raw(b)
        return jnp.einsum("bi,bj->bij", av, bv,
                          precision=dt.dot_precision(av, bv)).reshape(av.shape[0], -1)

    return LayerOutput(name=name, layer_type="out_prod",
                       size=input1.size * input2.size,
                       parents=(input1, input2), fn=fwd)


out_prod_layer = out_prod


def linear_comb(weights: LayerOutput, vectors: LayerOutput,
                size: int | None = None,
                name: str | None = None) -> LayerOutput:
    """out = w (1xM) * V (MxN), per row (≅ linear_comb_layer)."""
    name = name or gen_name("linear_comb_layer")
    if size is None:
        size = vectors.size // weights.size
    m = weights.size

    def fwd(ctx, params, states, w, v):
        wv, vv = raw(w), raw(v)
        return jnp.einsum("bm,bmn->bn", wv, vv.reshape(-1, m, size),
                          precision=dt.dot_precision(wv, vv))

    return LayerOutput(name=name, layer_type="convex_comb", size=size,
                       parents=(weights, vectors), fn=fwd)


linear_comb_layer = linear_comb


def repeat(input: LayerOutput, num_repeats: int,
           name: str | None = None, as_row_vector: bool = True,
           act=None) -> LayerOutput:
    """Feature-repeat (≅ repeat_layer): [..., N] -> [..., N*num_repeats]."""
    from paddle_tpu.layers import activation as act_mod
    from paddle_tpu.layers.base import map_data

    name = name or gen_name("repeat_layer")
    a = act_mod.get(act) if act else act_mod.IdentityActivation()

    def fwd(ctx, params, states, x):
        if as_row_vector:
            return map_data(lambda d: a(jnp.tile(d, (1,) * (d.ndim - 1)
                                                 + (num_repeats,))), x)
        return map_data(
            lambda d: a(jnp.repeat(d, num_repeats, axis=-1)), x)

    attrs = {"num_filters": num_repeats, "active_type": a.name}
    if not as_row_vector:
        attrs["user_arg"] = "as_col_vec"
    return LayerOutput(name=name, layer_type="featmap_expand",
                       size=input.size * num_repeats, parents=(input,),
                       fn=fwd, attrs=attrs)


repeat_layer = repeat


def kmax_seq_score(input: LayerOutput, beam_size: int = 1,
                   name: str | None = None) -> LayerOutput:
    """Indices of the k highest-scoring steps of a score sequence
    (≅ kmax_seq_score_layer)."""
    name = name or gen_name("kmax_seq_score_layer")

    def fwd(ctx, params, states, x):
        enforce(is_sequence(x), "kmax_seq_score expects a sequence")
        scores = x.data[..., 0] if x.data.ndim == 3 else x.data  # [B, T]
        masked = jnp.where(x.mask() > 0, scores, -1e30)
        _, idx = jax.lax.top_k(masked, beam_size)
        return idx.astype(jnp.int32)

    return LayerOutput(name=name, layer_type="kmax_seq_score", size=beam_size,
                       parents=(input,), fn=fwd,
                       attrs={"beam_size": beam_size})


kmax_seq_score_layer = kmax_seq_score
