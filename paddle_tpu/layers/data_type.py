"""Input type declarations — successor of ``python/paddle/v2/data_type.py`` /
``trainer/PyDataProvider2.py`` InputType (dense_vector, integer_value,
sparse_binary_vector, and their _sequence variants)."""

from __future__ import annotations

import dataclasses


class SeqType:
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class DataKind:
    DENSE = "dense"
    INTEGER = "integer"
    SPARSE_BINARY = "sparse_binary"
    SPARSE_FLOAT = "sparse_float"


@dataclasses.dataclass(frozen=True)
class InputType:
    dim: int
    seq_type: int = SeqType.NO_SEQUENCE
    kind: str = DataKind.DENSE
    height: int = 0
    width: int = 0
    channels: int = 0


def dense_vector(dim: int, height: int = 0, width: int = 0, channels: int = 0) -> InputType:
    return InputType(dim, SeqType.NO_SEQUENCE, DataKind.DENSE, height, width, channels)


def dense_array(dim, **kw) -> InputType:  # alias used by some demos
    return dense_vector(dim, **kw)


def integer_value(value_range: int) -> InputType:
    return InputType(value_range, SeqType.NO_SEQUENCE, DataKind.INTEGER)


def sparse_binary_vector(dim: int) -> InputType:
    return InputType(dim, SeqType.NO_SEQUENCE, DataKind.SPARSE_BINARY)


def sparse_float_vector(dim: int) -> InputType:
    return InputType(dim, SeqType.NO_SEQUENCE, DataKind.SPARSE_FLOAT)


def dense_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SeqType.SEQUENCE, DataKind.DENSE)


def integer_value_sequence(value_range: int) -> InputType:
    return InputType(value_range, SeqType.SEQUENCE, DataKind.INTEGER)


def sparse_binary_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SeqType.SEQUENCE, DataKind.SPARSE_BINARY)


def sparse_float_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SeqType.SEQUENCE, DataKind.SPARSE_FLOAT)


def integer_value_sub_sequence(value_range: int) -> InputType:
    return InputType(value_range, SeqType.SUB_SEQUENCE, DataKind.INTEGER)


def dense_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, SeqType.SUB_SEQUENCE, DataKind.DENSE)
