"""The ``paddle.layer`` surface — v2-compatible layer constructors.

Reference parity map (``trainer_config_helpers/layers.py`` line cites):
``data``/data_layer:72, ``fc``/fc_layer:999, ``embedding``:1045,
``img_conv``:2379, ``img_pool``:2576, ``batch_norm``:2841, ``addto``:2975,
``concat``:3041, ``dropout``:3650(dropout_layer), ``lstmemory``:1431,
``grumemory``:1593, ``recurrent``:3732(recurrent_layer), ``pooling``:1268,
``first_seq``/``last_seq``:1348/1303, ``expand``:1767, ``cos_sim``:2196,
``classification_cost``:4390, ``cross_entropy_cost``, ``square_error_cost``,
``max_id``:4335, ``crf``:4583, ``ctc``:4480, plus the math family
(mixed/projections live in ``mixed.py``).

Each constructor returns a :class:`LayerOutput` node; no proto, no C++ — the
node carries a pure JAX forward closure compiled later by ``Topology``."""

from __future__ import annotations

import math as _pymath
from typing import Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dt

from paddle_tpu.core import initializer as I
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import NestedSequenceBatch, SequenceBatch
from paddle_tpu.core.parameters import ParamSpec
from paddle_tpu.layers import activation as act_mod
from paddle_tpu.layers import pooling as pool_mod
from paddle_tpu.layers.attr import ExtraAttr, ParamAttr, param_attr_or_default
from paddle_tpu.layers.base import (
    Context,
    LayerOutput,
    StateSpec,
    gen_name,
    is_sequence,
    like,
    map_data,
    raw,
)
from paddle_tpu.layers.data_type import InputType, SeqType
from paddle_tpu.ops import loss as loss_ops
from paddle_tpu.ops import math as math_ops
from paddle_tpu.ops import nn as nn_ops
from paddle_tpu.ops import rnn as rnn_ops
from paddle_tpu.ops import sequence as seq_ops
from paddle_tpu.ops.embedding import lookup as emb_lookup


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _pname(attr: ParamAttr | None, layer_name: str, suffix: str) -> str:
    if attr is not None and attr.name:
        return attr.name
    return f"_{layer_name}.{suffix}"


def _default_decay():
    """config-level default_decay_rate() (≅ config_parser.py:3896:
    ``decay_rate = default(decay_rate, g_default_decay_rate)``)."""
    from paddle_tpu.config import parse_state

    return parse_state.G_DEFAULTS["decay_rate"]


def parse_state_momentum():
    """config-level default_momentum() (≅ config_parser's
    ``momentum = default(momentum, g_default_momentum)``)."""
    from paddle_tpu.config import parse_state

    return parse_state.G_DEFAULTS["momentum"]


def _wspec(attr, layer_name, suffix, shape, default_init, **kw) -> ParamSpec:
    a = param_attr_or_default(attr)
    fields = dict(
        name=_pname(a, layer_name, suffix),
        shape=tuple(shape),
        initializer=a.make_initializer(default_init),
        is_static=a.is_static,
        learning_rate=1.0 if a.learning_rate is None else a.learning_rate,
        decay_rate=a.l2_rate if a.l2_rate is not None else _default_decay(),
        momentum=(a.momentum if a.momentum is not None
                  else parse_state_momentum()),
        attr=a,
        gradient_clipping_threshold=a.gradient_clipping_threshold,
        sparse=a.sparse_update,
        sharding=a.sharding,
        sparsity_ratio=a.sparsity_ratio,
    )
    fields.update(kw)  # layer-specific overrides (e.g. embedding sparse=True)
    return ParamSpec(**fields)


def _maybe_dropout(node: LayerOutput, layer_attr: ExtraAttr | None) -> LayerOutput:
    """Fold ExtraAttr.drop_rate into the node itself — the reference stores
    it as ``LayerConfig.drop_rate`` on the same layer (no extra layer is
    created), so both runtime graph and protostr keep reference naming."""
    if layer_attr is not None and getattr(
            layer_attr, "error_clipping_threshold", None):
        node.attrs["error_clipping_threshold"] = (
            layer_attr.error_clipping_threshold)
    if layer_attr is None or not layer_attr.drop_rate:
        return node
    rate = layer_attr.drop_rate
    inner = node.fn

    def fwd(ctx, params, states, *xs):
        result = inner(ctx, params, states, *xs)
        if not ctx.is_train:
            return result
        key = ctx.key_for(node.name)

        def drop(v):
            return map_data(lambda d: nn_ops.dropout(d, rate, key, True), v)

        if isinstance(result, tuple) and len(result) == 2 and isinstance(result[1], dict):
            return drop(result[0]), result[1]
        return drop(result)

    node.fn = fwd
    node.attrs["drop_rate"] = rate
    return node


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------


def data(name: str, type: InputType, height: int = 0, width: int = 0) -> LayerOutput:
    """≅ v2 paddle.layer.data / data_layer (layers.py:72)."""
    h, w, c = height or type.height, width or type.width, type.channels
    if not (h and w) and c:
        side = int(_pymath.sqrt(type.dim // c))
        if side * side * c == type.dim:
            h = w = side
    return LayerOutput(
        name=name,
        layer_type="data",
        size=type.dim,
        height=h,
        width=w,
        depth=c or 1,
        attrs={"data_type": type.kind, "seq_type": type.seq_type, "dim": type.dim},
    )


data_layer = data


# ---------------------------------------------------------------------------
# fully connected / embedding
# ---------------------------------------------------------------------------


def fc(
    input,
    size: int,
    act=None,
    param_attr: ParamAttr | Sequence[ParamAttr] | None = None,
    bias_attr=None,
    layer_attr: ExtraAttr | None = None,
    name: str | None = None,
) -> LayerOutput:
    """≅ fc_layer (layers.py:999): multi-input weighted sum + bias + act.
    Sequence inputs are handled per-timestep (flattened [B*T, D] matmul —
    one big MXU call, like the reference's flattened Argument gemm)."""
    inputs = _as_list(input)
    name = name or gen_name("fc_layer")
    pattrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    specs = []
    for i, (inp, pa) in enumerate(zip(inputs, pattrs)):
        in_size = inp.size
        specs.append(
            _wspec(pa, name, f"w{i}", (in_size, size), I.xavier())
        )
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(
            bias_attr if isinstance(bias_attr, ParamAttr) else None,
            name,
            "wbias",
            (size,),
            I.constant(0.0),
        )
        specs.append(bspec)
    # reference fc_layer default act is Tanh (@wrap_act_default(), layers.py:997)
    activation = act_mod.get(act) if act is not None else act_mod.TanhActivation()

    def _apply(params, parents, apply_act):
        def compute(flats):
            y = None
            for i, x in enumerate(flats):
                x2 = x.reshape(x.shape[0], -1) if x.ndim > 2 else x
                t = math_ops.matmul(x2, params[specs[i].name])
                y = t if y is None else y + t
            if use_bias:
                y = y + params[bspec.name]
            return activation(y) if apply_act else y

        if any(is_sequence(p) for p in parents):
            ref = next(p for p in parents if is_sequence(p))
            b, t = ref.data.shape[:2]
            flats = [raw(p).reshape(b * t, -1) for p in parents]
            y = compute(flats)
            return SequenceBatch(data=y.reshape(b, t, size),
                                 length=ref.length)
        return compute([raw(p) for p in parents])

    def fwd(ctx: Context, params, states, *parents):
        if (activation.name == "sequence_softmax"
                and any(is_sequence(p) for p in parents)):
            # softmax over the TIMESTEPS of each sequence (reference
            # SequenceSoftmaxActivation, activations.py:86) — the
            # attention-weights use case
            ref = next(p for p in parents if is_sequence(p))
            b, t = ref.data.shape[:2]
            flats = [raw(p).reshape(b * t, -1) for p in parents]
            pre = None
            for i, x in enumerate(flats):
                tmp = math_ops.matmul(x, params[specs[i].name])
                pre = tmp if pre is None else pre + tmp
            if use_bias:
                pre = pre + params[bspec.name]
            pre = pre.reshape(b, t, size)
            mask = ref.mask()[:, :, None]
            pre = jnp.where(mask > 0, pre, -1e30)
            y = jax.nn.softmax(pre, axis=1) * mask
            return SequenceBatch(data=y, length=ref.length)
        return _apply(params, parents, apply_act=True)

    node = _maybe_dropout(
        LayerOutput(
            name=name,
            layer_type="fc",
            size=size,
            parents=tuple(inputs),
            param_specs=tuple(specs),
            fn=fwd,
            attrs={"size": size, "active_type": activation.name,
                   "bias_spec": bspec.name if use_bias else None},
        ),
        layer_attr,
    )
    if activation.name == "softmax" and not node.attrs.get("drop_rate"):
        # drop-in replacement for fn returning PRE-softmax logits (same
        # parents/params): lets classification_cost compute the fused
        # lse-based CE without the [.., V] softmax round-trip; also
        # propagated through recurrent_group's sunk tail
        node.attrs["__fc_logits__"] = (
            lambda ctx, params, states, *parents: _apply(
                params, parents, apply_act=False))
    return node


fc_layer = fc


def embedding(
    input: LayerOutput,
    size: int,
    param_attr: ParamAttr | None = None,
    name: str | None = None,
    padding_idx: int | None = None,
    pad_rows_to: int | None = None,
) -> LayerOutput:
    """≅ embedding_layer (layers.py:1045) / TableProjection.  Sparse-update
    semantics come from XLA's scatter-add gather gradient (SelectedRows analog).

    ``pad_rows_to=k`` rounds the table's row count up to a multiple of
    ``k`` so it can row-shard over a k-way mesh axis
    (``parallel.embedding.pad_vocab``); the forward then clamps-and-zeros
    ids outside the *logical* vocab so pad rows are never read and never
    receive gradient."""
    name = name or gen_name("embedding")
    vocab = input.size
    rows = vocab if not pad_rows_to else -(-vocab // pad_rows_to) * pad_rows_to
    spec = _wspec(
        param_attr, name, "w0", (rows, size), I.paddle_default(0.0, None), sparse=True
    )

    def fwd(ctx, params, states, ids):
        table = params[spec.name]
        if rows == vocab:
            return map_data(lambda d: emb_lookup(table, d, padding_idx), ids)

        def one(d):
            di = d.astype(jnp.int32)
            got = emb_lookup(table, jnp.clip(di, 0, vocab - 1), padding_idx)
            ok = (di >= 0) & (di < vocab)
            return jnp.where(ok[..., None], got, jnp.zeros((), got.dtype))

        return map_data(one, ids)

    # the reference implements embedding_layer as a mixed layer holding one
    # TableProjection (layers.py:963), so that's the proto shape too
    return LayerOutput(
        name=name,
        layer_type="mixed",
        size=size,
        parents=(input,),
        param_specs=(spec,),
        fn=fwd,
        attrs={
            "size": size, "vocab": vocab, "active_type": "",
            "mixed_items": [{
                "kind": "proj", "type": "table", "slot": 0,
                "pname": spec.name, "spec": spec,
                "input_size": vocab, "output_size": size,
                "param_dims": [rows, size], "default_emit_attr": None,
                "proto": {},
            }],
        },
    )


embedding_layer = embedding


# ---------------------------------------------------------------------------
# image layers (NHWC internally; accepts flat [B, C*H*W] v2 input)
# ---------------------------------------------------------------------------


def _to_nhwc(x: jax.Array, channels: int, height: int, width: int) -> jax.Array:
    """v2 data layers feed flat CHW rows; image layers reshape on entry."""
    if x.ndim == 4:
        return x
    b = x.shape[0]
    return x.reshape(b, channels, height, width).transpose(0, 2, 3, 1)


def _conv_out(sz, k, s, p):
    return (sz + 2 * p - k) // s + 1


def img_conv(
    input: LayerOutput,
    filter_size,
    num_filters: int,
    num_channels: int | None = None,
    stride=1,
    padding=0,
    groups: int = 1,
    act=None,
    param_attr: ParamAttr | None = None,
    bias_attr=None,
    shared_biases: bool = True,
    layer_attr: ExtraAttr | None = None,
    name: str | None = None,
    trans: bool = False,
    dilation=1,
    filter_size_y=None,
    stride_y=None,
    padding_y=None,
) -> LayerOutput:
    """≅ img_conv_layer (layers.py:2379) over ExpandConvLayer/CudnnConvLayer;
    XLA conv on NHWC replaces im2col+gemm (paddle/function/GemmConvOp.cpp).
    ``*_y`` kwargs follow the reference convention: None means "same as x"."""
    name = name or gen_name("conv")
    kh, kw = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    if filter_size_y is not None:
        kh = filter_size_y
    if stride_y is not None:
        sh = stride_y
    if padding_y is not None:
        ph = padding_y
    c_in = num_channels or input.depth
    h_in, w_in = input.height, input.width
    if not (h_in and w_in):
        side = int(_pymath.sqrt(input.size // c_in))
        h_in = w_in = side
    if trans:
        h_out = (h_in - 1) * sh + kh - 2 * ph
        w_out = (w_in - 1) * sw + kw - 2 * pw
    else:
        h_out = _conv_out(h_in, kh, sh, ph)
        w_out = _conv_out(w_in, kw, sw, pw)
    wspec = _wspec(
        param_attr, name, "w0", (kh, kw, c_in // groups, num_filters), I.msra()
    )
    specs = [wspec]
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(
            bias_attr if isinstance(bias_attr, ParamAttr) else None,
            name, "wbias", (num_filters,), I.constant(0.0),
        )
        specs.append(bspec)
    # reference img_conv_layer default act is ReLU (layers.py:2374)
    activation = act_mod.get(act) if act is not None else act_mod.ReluActivation()

    def fwd(ctx, params, states, x):
        x = _to_nhwc(raw(x), c_in, h_in, w_in)
        if trans:
            enforce(groups == 1, "transposed conv does not support groups")
            # lax.conv_transpose(transpose_kernel=True) wants (kh,kw,co,ci)
            y = nn_ops.conv2d_transpose(
                x, params[wspec.name].transpose(0, 1, 3, 2), (sh, sw), (ph, pw))
        else:
            y = nn_ops.conv2d(
                x, params[wspec.name], (sh, sw), (ph, pw), dilation=dilation, groups=groups
            )
        if use_bias:
            y = y + params[bspec.name]
        return activation(y)

    return _maybe_dropout(
        LayerOutput(
            name=name,
            layer_type="exconvt" if trans else "exconv",
            size=num_filters * h_out * w_out,
            parents=(input,),
            param_specs=tuple(specs),
            fn=fwd,
            height=h_out,
            width=w_out,
            depth=num_filters,
            attrs={
                "filter_size": [kh, kw], "stride": [sh, sw], "padding": [ph, pw],
                "num_filters": num_filters, "groups": groups, "trans": trans,
                "channels": c_in, "active_type": activation.name,
            },
        ),
        layer_attr,
    )


img_conv_layer = img_conv


def img_pool(
    input: LayerOutput,
    pool_size,
    num_channels: int | None = None,
    pool_type=None,
    stride=1,
    padding=0,
    layer_attr: ExtraAttr | None = None,
    name: str | None = None,
    ceil_mode: bool = True,
) -> LayerOutput:
    """≅ img_pool_layer (layers.py:2576). Reference default is ceil mode."""
    name = name or gen_name("pool")
    kh, kw = (pool_size, pool_size) if isinstance(pool_size, int) else tuple(pool_size)
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    ptype = pool_mod.get(pool_type)
    c = num_channels or input.depth
    h_in, w_in = input.height, input.width
    if not (h_in and w_in):
        side = int(_pymath.sqrt(input.size // c))
        h_in = w_in = side

    def osz(sz, k, s, p):
        if ceil_mode:
            return int(_pymath.ceil((sz + 2 * p - k) / s)) + 1
        return (sz + 2 * p - k) // s + 1

    h_out, w_out = osz(h_in, kh, sh, ph), osz(w_in, kw, sw, pw)
    # extra right/bottom padding for ceil mode
    eh = max((h_out - 1) * sh + kh - 2 * ph - h_in, 0)
    ew = max((w_out - 1) * sw + kw - 2 * pw - w_in, 0)

    def fwd(ctx, params, states, x):
        x = _to_nhwc(raw(x), c, h_in, w_in)
        if ptype == "max":
            xp = jnp.pad(
                x, ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0)),
                constant_values=-jnp.inf,
            )
            return nn_ops.max_pool2d(xp, (kh, kw), (sh, sw), 0)
        # average pooling excludes padding from the divisor (the reference's
        # cuDNN EXCLUDE_PADDING mode): reduce a ones-mask alongside the data
        xp = jnp.pad(x, ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0)))
        summed = nn_ops.avg_pool2d(xp, (kh, kw), (sh, sw), 0) * (kh * kw)
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        counts = nn_ops.avg_pool2d(
            jnp.pad(ones, ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0))),
            (kh, kw), (sh, sw), 0,
        ) * (kh * kw)
        return summed / jnp.maximum(counts, 1.0)

    return _maybe_dropout(
        LayerOutput(
            name=name,
            layer_type="pool",
            size=c * h_out * w_out,
            parents=(input,),
            fn=fwd,
            height=h_out,
            width=w_out,
            depth=c,
            attrs={"pool_type": ptype, "pool_size": [kh, kw],
                   "stride": [sh, sw], "padding": [ph, pw],
                   "channels": c, "ceil_mode": ceil_mode},
        ),
        layer_attr,
    )


img_pool_layer = img_pool


def batch_norm(
    input: LayerOutput,
    act=None,
    num_channels: int | None = None,
    bias_attr=None,
    param_attr: ParamAttr | None = None,
    use_global_stats: bool | None = None,
    moving_average_fraction: float = 0.9,
    epsilon: float = 1e-5,
    layer_attr: ExtraAttr | None = None,
    img3D: bool = False,
    mean_var_names=None,
    batch_norm_type: str | None = None,
    name: str | None = None,
) -> LayerOutput:
    """≅ batch_norm_layer (layers.py:2841) over BatchNormalizationLayer.
    Moving stats are explicit StateSpecs (pure in/out), not hidden buffers."""
    name = name or gen_name("batch_norm")
    c = num_channels or (input.depth if input.depth > 1 else input.size)
    is_image = bool(input.height and input.width)
    gamma = _wspec(param_attr, name, "w0", (c,), I.constant(1.0))
    beta = _wspec(
        bias_attr if isinstance(bias_attr, ParamAttr) else None,
        name, "wbias", (c,), I.constant(0.0),
    )
    # reference ParameterConfig names for the moving stats (BatchNormLayer
    # appends two static inputs .w1/.w2, config_parser.py:2425)
    stat_names = tuple(mean_var_names) if mean_var_names else (
        f"_{name}.w1", f"_{name}.w2")
    mean_s = StateSpec(stat_names[0], (c,), 0.0)
    var_s = StateSpec(stat_names[1], (c,), 1.0)
    # reference batch_norm_layer default act is ReLU (layers.py:2975)
    activation = act_mod.get(act) if act is not None else act_mod.ReluActivation()

    def fwd(ctx, params, states, x):
        xr = raw(x)
        if is_image:
            xr = _to_nhwc(xr, c, input.height, input.width)
        training = ctx.is_train if use_global_stats is None else (not use_global_stats)
        y, nm, nv = nn_ops.batch_norm(
            xr, params[gamma.name], params[beta.name],
            states[mean_s.name], states[var_s.name],
            is_train=training, momentum=moving_average_fraction, eps=epsilon,
        )
        y = activation(y)
        return like(x, y) if not is_image else y, {mean_s.name: nm, var_s.name: nv}

    return _maybe_dropout(
        LayerOutput(
            name=name,
            layer_type="batch_norm",
            size=input.size,
            parents=(input,),
            param_specs=(gamma, beta),
            state_specs=(mean_s, var_s),
            fn=fwd,
            height=input.height,
            width=input.width,
            depth=input.depth,
            attrs={"channels": c, "epsilon": epsilon,
                   "active_type": activation.name,
                   "use_global_stats": use_global_stats,
                   "moving_average_fraction": moving_average_fraction,
                   "img3D": img3D,
                   "stat_param_names": (mean_s.name, var_s.name)},
        ),
        layer_attr,
    )


batch_norm_layer = batch_norm


def img_conv_bn(
    input: LayerOutput,
    filter_size,
    num_filters: int,
    num_channels: int | None = None,
    stride=1,
    padding=0,
    act=None,
    param_attr: ParamAttr | None = None,
    bn_param_attr: ParamAttr | None = None,
    bn_bias_attr=None,
    epsilon: float = 1e-5,
    moving_average_fraction: float = 0.9,
    use_global_stats: bool | None = None,
    layer_attr: ExtraAttr | None = None,
    name: str | None = None,
) -> LayerOutput:
    """Fused conv (no bias) + batch-norm + activation as ONE layer node,
    lowering to ``ops/nn.conv2d_bn_relu`` (the TPP fused kernel when the
    ``fused_kernels`` flag enables it; the exact img_conv -> batch_norm
    composition otherwise).

    Parameter/state naming mirrors the two-layer form the model zoo used
    before (conv under ``<name>_conv``, BN under ``<name>_bn`` with the
    reference's ``.w1``/``.w2`` moving-stat slots), so checkpoints and
    param counts are unchanged."""
    name = name or gen_name("conv_bn")
    kh, kw = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)
    c_in = num_channels or input.depth
    h_in, w_in = input.height, input.width
    if not (h_in and w_in):
        side = int(_pymath.sqrt(input.size // c_in))
        h_in = w_in = side
    h_out = _conv_out(h_in, kh, sh, ph)
    w_out = _conv_out(w_in, kw, sw, pw)
    wspec = _wspec(param_attr, name + "_conv", "w0",
                   (kh, kw, c_in, num_filters), I.msra())
    gamma = _wspec(bn_param_attr, name + "_bn", "w0", (num_filters,),
                   I.constant(1.0))
    beta = _wspec(
        bn_bias_attr if isinstance(bn_bias_attr, ParamAttr) else None,
        name + "_bn", "wbias", (num_filters,), I.constant(0.0))
    mean_s = StateSpec(f"_{name}_bn.w1", (num_filters,), 0.0)
    var_s = StateSpec(f"_{name}_bn.w2", (num_filters,), 1.0)
    activation = act_mod.get(act) if act is not None else act_mod.ReluActivation()

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c_in, h_in, w_in)
        training = (ctx.is_train if use_global_stats is None
                    else (not use_global_stats))
        y, nm, nv = nn_ops.conv2d_bn_relu(
            xr, params[wspec.name], params[gamma.name], params[beta.name],
            states[mean_s.name], states[var_s.name], is_train=training,
            momentum=moving_average_fraction, eps=epsilon,
            stride=(sh, sw), padding=(ph, pw),
            act="relu" if activation.name == "relu" else "")
        if activation.name not in ("relu", ""):
            y = activation(y)
        return y, {mean_s.name: nm, var_s.name: nv}

    return _maybe_dropout(
        LayerOutput(
            name=name,
            layer_type="conv_bn",
            size=num_filters * h_out * w_out,
            parents=(input,),
            param_specs=(wspec, gamma, beta),
            state_specs=(mean_s, var_s),
            fn=fwd,
            height=h_out,
            width=w_out,
            depth=num_filters,
            attrs={
                "filter_size": [kh, kw], "stride": [sh, sw],
                "padding": [ph, pw], "num_filters": num_filters,
                "channels": c_in, "epsilon": epsilon,
                "moving_average_fraction": moving_average_fraction,
                "active_type": activation.name,
                "stat_param_names": (mean_s.name, var_s.name),
            },
        ),
        layer_attr,
    )


def img_cmrnorm(
    input: LayerOutput, size: int = 5, scale: float = 0.0128, power: float = 0.75,
    num_channels: int | None = None, name: str | None = None,
) -> LayerOutput:
    """≅ img_cmrnorm_layer (LRN across channels, CMRProjectionNormLayer).
    The reference divides alpha by the window size (config_parser.py:1362
    ``norm_conf.scale /= norm.size``)."""
    name = name or gen_name("crmnorm")
    c = num_channels or input.depth
    eff_scale = scale / size

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, input.height, input.width)
        return nn_ops.cross_map_normal(xr, size, eff_scale, power)

    return LayerOutput(
        name=name, layer_type="norm", size=input.size, parents=(input,), fn=fwd,
        height=input.height, width=input.width, depth=input.depth,
        attrs={"size": size, "scale": scale, "power": power},
    )


img_cmrnorm_layer = img_cmrnorm


def maxout(input: LayerOutput, groups: int, num_channels: int | None = None,
           name: str | None = None) -> LayerOutput:
    """≅ maxout_layer (MaxOutLayer)."""
    name = name or gen_name("maxout_layer")
    c = num_channels or input.depth
    c_out = c // groups

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, input.height, input.width)
        return nn_ops.maxout(xr, groups)

    return LayerOutput(
        name=name, layer_type="maxout", size=input.size // groups,
        parents=(input,), fn=fwd,
        height=input.height, width=input.width, depth=c_out,
        attrs={"groups": groups, "channels": c},
    )


maxout_layer = maxout


def bilinear_interp(input: LayerOutput, out_size_x: int, out_size_y: int,
                    name: str | None = None) -> LayerOutput:
    """≅ bilinear_interp_layer."""
    name = name or gen_name("bilinear_interp_layer")
    c = input.depth

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, input.height, input.width)
        return nn_ops.bilinear_interp(xr, out_size_y, out_size_x)

    return LayerOutput(
        name=name, layer_type="bilinear_interp", size=c * out_size_x * out_size_y,
        parents=(input,), fn=fwd, height=out_size_y, width=out_size_x, depth=c,
        attrs={"out_size_x": out_size_x, "out_size_y": out_size_y,
               "channels": c},
    )


bilinear_interp_layer = bilinear_interp


def spp(input: LayerOutput, pyramid_height: int, num_channels: int | None = None,
        pool_type=None, name: str | None = None) -> LayerOutput:
    """≅ spp_layer (SpatialPyramidPoolLayer)."""
    name = name or gen_name("spp")
    c = num_channels or input.depth
    ptype = pool_mod.get(pool_type)
    bins = sum(4**i for i in range(pyramid_height))

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, input.height, input.width)
        return nn_ops.spatial_pyramid_pool(xr, pyramid_height, ptype)

    return LayerOutput(
        name=name, layer_type="spp", size=c * bins, parents=(input,), fn=fwd,
        height=1, width=bins, depth=c,
        attrs={"pyramid_height": pyramid_height, "channels": c,
               "pool_type": {"max": "max-projection",
                             "average": "avg-projection"}.get(
                   ptype, ptype + "-projection")},
    )


spp_layer = spp


def pad(input: LayerOutput, pad_c=None, pad_h=None, pad_w=None,
        name: str | None = None) -> LayerOutput:
    """≅ pad_layer (paddle/function PadOp)."""
    name = name or gen_name("pad")
    pc, ph, pw = pad_c or [0, 0], pad_h or [0, 0], pad_w or [0, 0]
    c, h, w = input.depth, input.height, input.width
    c2, h2, w2 = c + sum(pc), h + sum(ph), w + sum(pw)

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, h, w)
        return nn_ops.pad(xr, pc, ph, pw)

    return LayerOutput(
        name=name, layer_type="pad", size=c2 * h2 * w2, parents=(input,), fn=fwd,
        height=h2, width=w2, depth=c2,
        attrs={"pad_c": pc, "pad_h": ph, "pad_w": pw, "channels": c},
    )


pad_layer = pad


def crop(input: LayerOutput, offset, shape, name: str | None = None) -> LayerOutput:
    """≅ crop_layer (paddle/function CropOp)."""
    name = name or gen_name("crop_layer")
    c, h, w = input.depth, input.height, input.width
    oh, ow = shape

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, h, w)
        return nn_ops.crop(xr, offset, shape)

    return LayerOutput(
        name=name, layer_type="crop", size=c * oh * ow, parents=(input,), fn=fwd,
        height=oh, width=ow, depth=c, attrs={"offset": list(offset), "shape": list(shape)},
    )


crop_layer = crop


def rotate(input: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ rotate_layer."""
    name = name or gen_name("rotate_layer")
    c, h, w = input.depth, input.height, input.width

    def fwd(ctx, params, states, x):
        return nn_ops.rotate(_to_nhwc(raw(x), c, h, w))

    return LayerOutput(
        name=name, layer_type="rotate", size=input.size, parents=(input,), fn=fwd,
        height=w, width=h, depth=c,
    )


rotate_layer = rotate


def block_expand(input: LayerOutput, block_x: int, block_y: int,
                 stride_x: int, stride_y: int, padding_x: int = 0, padding_y: int = 0,
                 num_channels: int | None = None, name: str | None = None) -> LayerOutput:
    """≅ block_expand_layer (im2col -> sequence, used by OCR CRNN)."""
    name = name or gen_name("block_expand_layer")
    c = num_channels or input.depth
    h, w = input.height, input.width
    out_dim = block_x * block_y * c

    def fwd(ctx, params, states, x):
        xr = _to_nhwc(raw(x), c, h, w)
        patches, oh, ow = nn_ops.block_expand(
            xr, block_y, block_x, stride_y, stride_x, padding_y, padding_x
        )
        b = patches.shape[0]
        length = jnp.full((b,), patches.shape[1], jnp.int32)
        return SequenceBatch(data=patches, length=length)

    return LayerOutput(
        name=name, layer_type="blockexpand", size=out_dim, parents=(input,), fn=fwd,
        attrs={"block_x": block_x, "block_y": block_y, "stride_x": stride_x,
               "stride_y": stride_y, "padding_x": padding_x,
               "padding_y": padding_y, "channels": c},
    )


block_expand_layer = block_expand


# ---------------------------------------------------------------------------
# element-wise / structural
# ---------------------------------------------------------------------------


def addto(input, act=None, bias_attr=None, name: str | None = None,
          layer_attr: ExtraAttr | None = None) -> LayerOutput:
    """≅ addto_layer (AddtoLayer): elementwise sum of equal-shaped inputs."""
    inputs = _as_list(input)
    name = name or gen_name("addto")
    activation = act_mod.get(act)
    use_bias = isinstance(bias_attr, ParamAttr) or bias_attr is True
    specs = ()
    if use_bias:
        bspec = _wspec(
            bias_attr if isinstance(bias_attr, ParamAttr) else None,
            name, "wbias", (inputs[0].size,), I.constant(0.0),
        )
        specs = (bspec,)

    def fwd(ctx, params, states, *parents):
        y = raw(parents[0])
        for p in parents[1:]:
            y = y + raw(p)
        if use_bias:
            y = y + params[bspec.name]
        return like(parents[0], activation(y))

    return _maybe_dropout(
        LayerOutput(
            name=name, layer_type="addto", size=inputs[0].size, parents=tuple(inputs),
            param_specs=specs, fn=fwd,
            height=inputs[0].height, width=inputs[0].width, depth=inputs[0].depth,
            attrs={"active_type": activation.name},
        ),
        layer_attr,
    )


addto_layer = addto


def concat(input, act=None, name: str | None = None,
           layer_attr: ExtraAttr | None = None, bias_attr=None) -> LayerOutput:
    """≅ concat_layer (ConcatenateLayer); with Projection inputs it is the
    reference's ConcatenateLayer2 ('concat2': each projection computed then
    concatenated, not summed)."""
    from paddle_tpu.layers import mixed as mixed_mod

    inputs = _as_list(input)
    name = name or gen_name("concat")
    if inputs and isinstance(inputs[0], mixed_mod.Projection):
        return _concat_projections(inputs, act, name, bias_attr)
    activation = act_mod.get(act)
    total = sum(i.size for i in inputs)
    same_image = all(i.height == inputs[0].height and i.width == inputs[0].width
                     and i.height for i in inputs)

    def fwd(ctx, params, states, *parents):
        if same_image and all(raw(p).ndim == 4 for p in parents):
            y = jnp.concatenate([raw(p) for p in parents], axis=-1)
            return activation(y)
        vals = [raw(p) for p in parents]
        if is_sequence(parents[0]):
            y = jnp.concatenate(vals, axis=-1)
            return SequenceBatch(data=activation(y), length=parents[0].length)
        vals = [v.reshape(v.shape[0], -1) for v in vals]
        return activation(jnp.concatenate(vals, axis=-1))

    depth = sum(i.depth for i in inputs) if same_image else 1
    return _maybe_dropout(
        LayerOutput(
            name=name, layer_type="concat", size=total, parents=tuple(inputs), fn=fwd,
            height=inputs[0].height if same_image else 0,
            width=inputs[0].width if same_image else 0,
            depth=depth,
            attrs={"active_type": activation.name},
        ),
        layer_attr,
    )


concat_layer = concat


def _concat_projections(projs, act, name: str, bias_attr=None) -> LayerOutput:
    """'concat2' (ConcatenateLayer2): per-projection outputs concatenated.
    With conv projections, ``bias_attr`` is a shared per-channel bias of
    size sum(num_filters) (config_parser.py:3545-3553, ConvProjection
    ``calc_bias_size``); otherwise a plain full-size bias."""
    from paddle_tpu.core.parameters import ParamSpec  # noqa: F401
    from paddle_tpu.layers import mixed as mixed_mod

    activation = act_mod.get(act)
    slots, fns, specs, items = [], [], [], []
    for p in projs:
        enforce(not p.is_operator, "concat2 takes projections, not operators")
        enforce(p.size != 0,
                "concat2 projections need an explicit size (fc/table "
                "projections cannot elide size outside mixed_layer)")
        idx = len(slots)
        pname = f"_{name}.w{idx}"
        spec, fn = p.bind(pname)
        slots.append(p.inputs[0])
        if spec is not None:
            specs.append(spec)
        fns.append((fn, idx))
        items.append({
            "kind": "proj", "type": p.proj_type, "slot": idx,
            "pname": pname, "spec": spec,
            "input_size": p.inputs[0].size, "output_size": p.size,
            "param_dims": p.param_dims,
            "default_emit_attr": p.default_emit_attr,
            "proto": dict(p.proto),
        })
    total = sum(p.size for p in projs)

    use_bias = bias_attr is True or isinstance(bias_attr, ParamAttr)
    all_conv = all(p.proj_type in ("conv", "convt") for p in projs)
    bspec = None
    bias_size = 0
    if use_bias:
        if all_conv:
            bias_size = sum(p.proto["num_filters"] for p in projs)
        else:
            bias_size = total
        battr = bias_attr if isinstance(bias_attr, ParamAttr) else None
        bspec = _wspec(battr, name, "wbias", (bias_size,), I.constant(0.0))
        specs.append(bspec)

    def _add_shared_bias(outs, params):
        # per-channel bias over each conv projection's [co, oh*ow] block
        b = params[bspec.name]
        off = 0
        biased = []
        for p, o in zip(projs, outs):
            co = p.proto["num_filters"]
            spatial = p.size // co
            o = o.reshape(o.shape[0], co, spatial) + b[off:off + co][:, None]
            biased.append(o.reshape(o.shape[0], -1))
            off += co
        return biased

    def fwd(ctx, params, states, *vals):
        outs = [raw(fn(params, vals[i])) for fn, i in fns]
        template = next((v for v in vals if is_sequence(v)), None)
        if use_bias and all_conv:
            outs = _add_shared_bias(outs, params)
        y = jnp.concatenate(
            [o.reshape(o.shape[0], -1) if template is None else o for o in outs],
            axis=-1)
        if use_bias and not all_conv:
            y = y + params[bspec.name]
        y = activation(y)
        if template is not None:
            return SequenceBatch(data=y, length=template.length)
        return y

    return LayerOutput(
        name=name, layer_type="concat2", size=total, parents=tuple(slots),
        param_specs=tuple(specs), fn=fwd,
        attrs={"mixed_items": items, "active_type": activation.name,
               "bias_size": bias_size,
               "shared_biases": bool(use_bias and all_conv)},
    )


def dropout(input: LayerOutput, dropout_rate: float, name: str | None = None) -> LayerOutput:
    """≅ dropout_layer (layers.py:3650)."""
    name = name or gen_name("dropout")

    def fwd(ctx, params, states, x):
        if not ctx.is_train or dropout_rate <= 0:
            return x
        key = ctx.key_for(name)
        return map_data(lambda d: nn_ops.dropout(d, dropout_rate, key, True), x)

    return LayerOutput(
        name=name, layer_type="dropout", size=input.size, parents=(input,), fn=fwd,
        height=input.height, width=input.width, depth=input.depth,
        attrs={"dropout_rate": dropout_rate},
    )


dropout_layer = dropout


def slice(input: LayerOutput, start: int, end: int, name: str | None = None) -> LayerOutput:
    """≅ slice feature columns [start, end)."""
    name = name or gen_name("slice")

    def fwd(ctx, params, states, x):
        return map_data(lambda d: d[..., start:end], x)

    return LayerOutput(
        name=name, layer_type="slice", size=end - start, parents=(input,), fn=fwd,
        attrs={"start": start, "end": end},
    )


def cos_sim(a: LayerOutput, b: LayerOutput, scale=1, size: int = 1,
            name: str | None = None, layer_attr=None) -> LayerOutput:
    """≅ cos_sim (CosSimLayer); with size>1, b holds `size` vectors and the
    output is a similarity per vector (CosSimVecMatLayer, type 'cos_vm')."""
    name = name or gen_name("cos_sim")

    def fwd(ctx, params, states, xa, xb):
        if size > 1:
            va = raw(xa)
            vb = raw(xb).reshape(va.shape[0], size, -1)
            dots = jnp.einsum("bd,bsd->bs", va, vb,
                              precision=dt.dot_precision(va, vb))
            na = jnp.linalg.norm(va, axis=-1, keepdims=True)
            nb = jnp.linalg.norm(vb, axis=-1)
            return scale * dots / jnp.maximum(na * nb, 1e-12)
        return math_ops.cos_sim(raw(xa), raw(xb), scale)[:, None]

    return LayerOutput(
        name=name, layer_type="cos_vm" if size > 1 else "cos", size=size,
        parents=(a, b), fn=fwd, attrs={"scale": scale},
    )


def trans(input: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ trans_layer (TransLayer): matrix transpose of the feature block."""
    name = name or gen_name("trans_layer")

    def fwd(ctx, params, states, x):
        return jnp.swapaxes(raw(x), -1, -2)

    return LayerOutput(name=name, layer_type="trans", size=input.size,
                       parents=(input,), fn=fwd)


trans_layer = trans


def interpolation(input, weight: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ interpolation_layer: w*a + (1-w)*b."""
    a, b = input
    name = name or gen_name("interpolation_layer")

    def fwd(ctx, params, states, w, xa, xb):
        return math_ops.interpolation(raw(xa), raw(xb), raw(w))

    # reference InterpolationLayer input order: [weight, a, b]
    return LayerOutput(name=name, layer_type="interpolation", size=a.size,
                       parents=(weight, a, b), fn=fwd)


interpolation_layer = interpolation


def power(input: LayerOutput, weight: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ power_layer."""
    name = name or gen_name("power_layer")

    def fwd(ctx, params, states, w, x):
        return math_ops.power(raw(x), raw(w))

    # reference PowerLayer input order: [weight, input]
    return LayerOutput(name=name, layer_type="power", size=input.size,
                       parents=(weight, input), fn=fwd)


power_layer = power


def scaling(input: LayerOutput, weight: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ scaling_layer."""
    name = name or gen_name("scaling_layer")

    def fwd(ctx, params, states, w, x):
        return like(x, math_ops.scaling(raw(x), raw(w)))

    # reference ScalingLayer input order: [weight, input]
    return LayerOutput(name=name, layer_type="scaling", size=input.size,
                       parents=(weight, input), fn=fwd)


scaling_layer = scaling


def slope_intercept(input: LayerOutput, slope: float = 1.0, intercept: float = 0.0,
                    name: str | None = None) -> LayerOutput:
    """≅ slope_intercept_layer."""
    name = name or gen_name("slope_intercept_layer")

    def fwd(ctx, params, states, x):
        return map_data(lambda d: math_ops.slope_intercept(d, slope, intercept), x)

    return LayerOutput(name=name, layer_type="slope_intercept", size=input.size,
                       parents=(input,), fn=fwd,
                       attrs={"slope": slope, "intercept": intercept})


slope_intercept_layer = slope_intercept


def sum_to_one_norm(input: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ sum_to_one_norm_layer."""
    name = name or gen_name("sum_to_one_norm_layer")

    def fwd(ctx, params, states, x):
        return map_data(math_ops.sum_to_one_norm, x)

    return LayerOutput(name=name, layer_type="sum_to_one_norm", size=input.size,
                       parents=(input,), fn=fwd)


sum_to_one_norm_layer = sum_to_one_norm


def row_l2_norm(input: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ row_l2_norm_layer."""
    name = name or gen_name("row_l2_norm_layer")

    def fwd(ctx, params, states, x):
        return map_data(math_ops.l2_normalize, x)

    return LayerOutput(name=name, layer_type="row_l2_norm", size=input.size,
                       parents=(input,), fn=fwd)


row_l2_norm_layer = row_l2_norm


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------


def pooling(input: LayerOutput, pooling_type=None, name: str | None = None,
            agg_level: str = "non-seq", stride: int = -1,
            bias_attr=None, layer_attr: ExtraAttr | None = None) -> LayerOutput:
    """≅ pooling_layer (layers.py:1268, SequencePoolLayer): seq -> vector.
    ``agg_level`` 'seq' pools each inner sequence of a nested batch."""
    name = name or gen_name("seq_pooling")
    ptype = pool_mod.get(pooling_type) if pooling_type is not None else "max"
    out_max_index = bool(getattr(pooling_type, "output_max_index", False))

    fns = {
        "max": seq_ops.seq_pool_max,
        "average": seq_ops.seq_pool_avg,
        "sum": seq_ops.seq_pool_sum,
        "sqrt": seq_ops.seq_pool_sqrt,
    }

    mode = {"max": "max", "average": "average", "sum": "sum",
            "sqrt": "sqrt"}[ptype]

    def fwd(ctx, params, states, x):
        if isinstance(x, NestedSequenceBatch):
            enforce(not out_max_index and not (stride and stride > 0),
                    "pooling: output_max_index/stride unsupported on nested "
                    "sequence input")
            if agg_level == "seq":
                # pool each inner sequence -> one step per subsequence
                return seq_ops.seq_pool_inner(x, mode)
            return seq_ops.seq_pool_all_nested(x, mode)
        if out_max_index:
            enforce(not (stride and stride > 0),
                    "pooling: stride with output_max_index unsupported")
            return jnp.argmax(
                jnp.where(x.mask()[..., None] > 0, x.data, -jnp.inf), axis=1
            ).astype(jnp.float32)
        if stride and stride > 0:
            return seq_ops.seq_pool_windows(x, stride, mode)
        return fns[ptype](x)

    # proto type: max stays 'max'; average/sum/sqrt are 'average' with an
    # average_strategy (config_parser: 'average'/'sum'/'squarerootn')
    proto_type = "max" if ptype == "max" else "average"
    strategy = {"average": "average", "sum": "sum", "sqrt": "squarerootn"}.get(ptype)
    attrs = {"pool_type": ptype, "trans_type": agg_level, "stride": stride}
    if proto_type == "average":
        attrs["average_strategy"] = strategy
    if out_max_index:
        attrs["output_max_index"] = True
    return LayerOutput(
        name=name, layer_type=proto_type, size=input.size, parents=(input,),
        fn=fwd, attrs=attrs,
    )


pooling_layer = pooling


def last_seq(input: LayerOutput, name: str | None = None,
             agg_level: str = "non-seq", stride: int = -1, **kw) -> LayerOutput:
    """≅ last_seq (layers.py:1303, SequenceLastInstanceLayer)."""
    name = name or gen_name("last_seq")

    def fwd(ctx, params, states, x):
        if isinstance(x, NestedSequenceBatch):
            if agg_level == "seq":
                return seq_ops.seq_pool_inner(x, "last")
            return seq_ops.seq_pool_all_nested(x, "last")
        if stride and stride > 0:
            return seq_ops.seq_pool_windows(x, stride, "last")
        return seq_ops.seq_last(x)

    return LayerOutput(name=name, layer_type="seqlastins", size=input.size,
                       parents=(input,), fn=fwd,
                       attrs={"trans_type": agg_level, "stride": stride})


def first_seq(input: LayerOutput, name: str | None = None,
              agg_level: str = "non-seq", stride: int = -1, **kw) -> LayerOutput:
    """≅ first_seq (layers.py:1348); proto type is also 'seqlastins' with
    select_first (LayerConfig.select_first, ModelConfig.proto:462)."""
    name = name or gen_name("first_seq")

    def fwd(ctx, params, states, x):
        if isinstance(x, NestedSequenceBatch):
            if agg_level == "seq":
                return seq_ops.seq_pool_inner(x, "first")
            return seq_ops.seq_pool_all_nested(x, "first")
        if stride and stride > 0:
            return seq_ops.seq_pool_windows(x, stride, "first")
        return seq_ops.seq_first(x)

    return LayerOutput(name=name, layer_type="seqlastins", size=input.size,
                       parents=(input,), fn=fwd,
                       attrs={"trans_type": agg_level, "stride": stride,
                              "select_first": True})


def expand(input: LayerOutput, expand_as: LayerOutput, name: str | None = None,
           expand_level: str = "non-seq", bias_attr=None, **kw) -> LayerOutput:
    """≅ expand_layer (layers.py:1767, ExpandLayer)."""
    name = name or gen_name("expand_layer")

    def fwd(ctx, params, states, x, ref):
        if expand_level == "seq":
            # FROM_SEQUENCE: one vector per subsequence, repeated across
            # that subsequence's timesteps
            enforce(is_sequence(x) and isinstance(ref, NestedSequenceBatch),
                    "expand FROM_SEQUENCE needs sequence input + nested ref")
            t = ref.data.shape[2]
            data = jnp.broadcast_to(
                raw(x)[:, :, None, :],
                raw(x).shape[:2] + (t,) + raw(x).shape[2:],
            )
            return NestedSequenceBatch(data=data, seq_length=ref.seq_length,
                                       sub_length=ref.sub_length)
        enforce(not isinstance(ref, NestedSequenceBatch),
                "expand FROM_NO_SEQUENCE to nested target unsupported")
        return seq_ops.expand(raw(x) if not is_sequence(x) else seq_ops.seq_first(x), ref)

    return LayerOutput(name=name, layer_type="expand", size=input.size,
                       parents=(input, expand_as), fn=fwd,
                       attrs={"trans_type": expand_level})


expand_layer = expand


def seq_concat(a: LayerOutput, b: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ seq_concat_layer (SequenceConcatLayer)."""
    name = name or gen_name("seqconcat")

    def fwd(ctx, params, states, xa, xb):
        return seq_ops.seq_concat(xa, xb)

    return LayerOutput(name=name, layer_type="seqconcat", size=a.size,
                       parents=(a, b), fn=fwd)


seq_concat_layer = seq_concat


def seq_reshape(input: LayerOutput, reshape_size: int, name: str | None = None,
                **kw) -> LayerOutput:
    """≅ seq_reshape_layer (SequenceReshapeLayer)."""
    name = name or gen_name("seqreshape")

    def fwd(ctx, params, states, x):
        return seq_ops.seq_reshape(x, reshape_size)

    return LayerOutput(name=name, layer_type="seqreshape", size=reshape_size,
                       parents=(input,), fn=fwd, attrs={"reshape_size": reshape_size})


seq_reshape_layer = seq_reshape


def seq_slice(input: LayerOutput, starts=None, ends=None, name: str | None = None) -> LayerOutput:
    """≅ seq_slice_layer (SequenceSliceLayer); starts/ends are layers holding
    per-row indices."""
    name = name or gen_name("seq_slice_layer")
    parents = [input] + [p for p in (starts, ends) if p is not None]
    attrs = {"dfs_parents": (input,)}
    if len(parents) == 2:  # config_parser.py:3154 SeqSliceLayer
        attrs["select_first"] = starts is not None

    def fwd(ctx, params, states, x, *se):
        t = x.max_len
        s = raw(se[0]).reshape(-1).astype(jnp.int32) if starts is not None else jnp.zeros(
            (x.batch_size,), jnp.int32
        )
        e = (
            raw(se[-1]).reshape(-1).astype(jnp.int32)
            if ends is not None
            else x.length
        )
        return seq_ops.seq_slice(x, s, e)

    return LayerOutput(name=name, layer_type="seq_slice", size=input.size,
                       parents=tuple(parents), fn=fwd, attrs=attrs)


seq_slice_layer = seq_slice


def context_projection_layer(
    input: LayerOutput, context_len: int, context_start: int | None = None,
    padding_attr=False, name: str | None = None,
) -> LayerOutput:
    """Standalone context projection (≅ ContextProjection via mixed_layer)."""
    name = name or gen_name("context_projection")
    start = context_start if context_start is not None else -(context_len // 2)
    trainable = isinstance(padding_attr, ParamAttr) or padding_attr is True
    specs = ()
    if trainable:
        n_pad = max(-start, 0) + max(start + context_len - 1, 0)
        pspec = _wspec(
            padding_attr if isinstance(padding_attr, ParamAttr) else None,
            name, "w0", (max(n_pad, 1), input.size), I.constant(0.0),
        )
        specs = (pspec,)

    def fwd(ctx, params, states, x):
        pw = params[specs[0].name] if trainable else None
        return seq_ops.context_projection(x, context_len, start, pw)

    return LayerOutput(
        name=name, layer_type="context_projection", size=input.size * context_len,
        parents=(input,), param_specs=specs, fn=fwd,
        attrs={"context_len": context_len, "context_start": start},
    )


def row_conv(input: LayerOutput, context_len: int, act=None,
             param_attr: ParamAttr | None = None, name: str | None = None) -> LayerOutput:
    """≅ row_conv_layer (RowConvLayer, DeepSpeech2 lookahead)."""
    name = name or gen_name("row_conv_layer")
    wspec = _wspec(param_attr, name, "w0", (context_len, input.size), I.constant(0.0))
    activation = act_mod.get(act)

    def fwd(ctx, params, states, x):
        y = seq_ops.row_conv(x, params[wspec.name])
        return SequenceBatch(data=activation(y.data), length=y.length)

    return LayerOutput(name=name, layer_type="row_conv", size=input.size,
                       parents=(input,), param_specs=(wspec,), fn=fwd,
                       attrs={"context_len": context_len,
                              "active_type": activation.name})


row_conv_layer = row_conv


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------


def recurrent(input: LayerOutput, act=None, bias_attr=None,
              param_attr: ParamAttr | None = None, reverse: bool = False,
              name: str | None = None) -> LayerOutput:
    """≅ recurrent_layer (layers.py:3732, RecurrentLayer): input is the
    pre-projected sequence; only h_{t-1} @ U runs in the scan."""
    name = name or gen_name("recurrent_layer")
    d = input.size
    wspec = _wspec(param_attr, name, "w0", (d, d), I.paddle_default())
    specs = [wspec]
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(bias_attr if isinstance(bias_attr, ParamAttr) else None,
                       name, "wbias", (d,), I.constant(0.0))
        specs.append(bspec)
    activation = act_mod.get(act) if act is not None else act_mod.TanhActivation()

    def fwd(ctx, params, states, x):
        eye = jnp.eye(d, dtype=jnp.float32)
        b = params[bspec.name] if use_bias else None
        out, _ = rnn_ops.simple_rnn(
            x, eye, params[wspec.name], b, activation=activation, reverse=reverse
        )
        return out

    return LayerOutput(name=name, layer_type="recurrent", size=d, parents=(input,),
                       param_specs=tuple(specs), fn=fwd,
                       attrs={"reverse": reverse, "active_type": activation.name,
                              "reversed_field": True})


recurrent_layer = recurrent


def lstmemory(input: LayerOutput, reverse: bool = False, act=None,
              gate_act=None, state_act=None, bias_attr=None,
              param_attr: ParamAttr | None = None, name: str | None = None,
              **kw) -> LayerOutput:
    """≅ lstmemory (layers.py:1431, LstmLayer): expects input of size 4*D
    already projected (the reference requires a preceding fc/mixed of size
    4*size).  Output size D = input.size/4."""
    name = name or gen_name("lstmemory")
    d = input.size // 4
    wspec = _wspec(param_attr, name, "w0", (d, 4 * d), I.paddle_default())
    specs = [wspec]
    use_bias = bias_attr is not False
    if use_bias:
        # reference LstmLayer bias is 7*d (config_parser.py LstmLayer:
        # gate biases 4d + peephole weights W_ci/W_cf/W_co 3d) — kept as ONE
        # parameter so names/shapes match checkpoints and protostr
        bspec = _wspec(bias_attr if isinstance(bias_attr, ParamAttr) else None,
                       name, "wbias", (7 * d,), I.constant(0.0))
        specs.append(bspec)
    oa = act_mod.get(act) if act else act_mod.TanhActivation()
    ga = act_mod.get(gate_act) if gate_act else act_mod.SigmoidActivation()
    sa = act_mod.get(state_act) if state_act else act_mod.TanhActivation()

    def fwd(ctx, params, states, x):
        b_, t = x.batch_size, x.max_len
        xw = x.data.reshape(b_, t, 4 * d)
        peep = None
        if use_bias:
            full = params[bspec.name]
            xw = xw + full[: 4 * d]
            peep = full[4 * d:]
        init = rnn_ops.LSTMState(
            h=jnp.zeros((b_, d), jnp.float32), c=jnp.zeros((b_, d), jnp.float32)
        )

        # standard activations -> fused Pallas sequence kernel (peepholes
        # included); exotic activations keep the lax.scan cell
        if ga.name == "sigmoid" and sa.name == "tanh" and oa.name == "tanh":
            out, _ = rnn_ops.lstm_fused(
                SequenceBatch(xw, x.length), params[wspec.name], init,
                peephole=peep, reverse=reverse)
            return out

        def step(state, xt):
            return rnn_ops.lstm_cell(
                xt, state, params[wspec.name], ga, sa, out_act=oa, peephole=peep
            )

        last, ys = rnn_ops._masked_scan(
            step, SequenceBatch(xw, x.length), init, reverse=reverse
        )
        return SequenceBatch(data=ys.h, length=x.length)

    return LayerOutput(name=name, layer_type="lstmemory", size=d, parents=(input,),
                       param_specs=tuple(specs), fn=fwd,
                       attrs={"reverse": reverse, "reversed_field": True,
                              "active_type": oa.name,
                              "active_gate_type": ga.name,
                              "active_state_type": sa.name})


def grumemory(input: LayerOutput, reverse: bool = False, act=None,
              gate_act=None, bias_attr=None, param_attr: ParamAttr | None = None,
              name: str | None = None, **kw) -> LayerOutput:
    """≅ grumemory (layers.py:1593, GruLayer): input size 3*D pre-projected."""
    name = name or gen_name("gru")
    d = input.size // 3
    # single fused recurrent weight [d, 3d] like the reference GruLayer
    # parameter (dims [d, 3d]): [:, :2d] gates, [:, 2d:] candidate
    wspec = _wspec(param_attr, name, "w0", (d, 3 * d), I.paddle_default())
    specs = [wspec]
    use_bias = bias_attr is not False
    if use_bias:
        bspec = _wspec(bias_attr if isinstance(bias_attr, ParamAttr) else None,
                       name, "wbias", (3 * d,), I.constant(0.0))
        specs.append(bspec)
    ga = act_mod.get(gate_act) if gate_act else act_mod.SigmoidActivation()
    sa = act_mod.get(act) if act else act_mod.TanhActivation()

    def fwd(ctx, params, states, x):
        b_, t = x.batch_size, x.max_len
        xw = x.data.reshape(b_, t, 3 * d)
        if use_bias:
            xw = xw + params[bspec.name]
        init = jnp.zeros((b_, d), jnp.float32)
        w = params[wspec.name]

        # standard activations -> fused Pallas sequence kernel
        if ga.name == "sigmoid" and sa.name == "tanh":
            out, _ = rnn_ops.gru_fused(
                SequenceBatch(xw, x.length), w[:, : 2 * d], w[:, 2 * d:],
                init, reverse=reverse)
            return out

        def step(h, xt):
            return rnn_ops.gru_cell(xt, h, w[:, : 2 * d], w[:, 2 * d:], ga, sa)

        last, ys = rnn_ops._masked_scan(
            step, SequenceBatch(xw, x.length), init, reverse=reverse
        )
        return SequenceBatch(data=ys, length=x.length)

    return LayerOutput(name=name, layer_type="gated_recurrent", size=d,
                       parents=(input,), param_specs=tuple(specs), fn=fwd,
                       attrs={"reverse": reverse, "reversed_field": True,
                              "active_type": sa.name,
                              "active_gate_type": ga.name})


def bilstm(input: LayerOutput, size: int, name: str | None = None,
           param_attr: ParamAttr | None = None, bias_attr=None,
           inner_param_attr: ParamAttr | None = None,
           inner_bias_attr=None) -> LayerOutput:
    """Bidirectional LSTM (input projections included) as ONE layer node,
    lowering to ``ops/rnn.bilstm_fused``: with the ``fused_kernels``
    flag on (on TPU) both directions run in a single Pallas program over
    one residency of all four weight matrices (``bilstm_seq``) — the
    composed fc + lstmemory pair pays the input/weight streaming twice;
    otherwise the exact unfused composition.

    Parameter naming mirrors the composed ``networks.bidirectional_lstm``
    form: ``<name>_fw_transform.w0``/``.wbias`` (the 4*size input
    projection) and ``<name>_fw.w0``/``.wbias`` (recurrent weight + the
    reference's 7*size gate-bias+peephole bundle), same for ``_bw``.
    Output is the [fw, bw] feature concat (size 2*size)."""
    name = name or gen_name("bilstm")
    d = size
    use_proj_bias = bias_attr is not False
    use_inner_bias = inner_bias_attr is not False

    def dir_specs(suffix):
        proj_w = _wspec(param_attr, f"{name}_{suffix}_transform", "w0",
                        (input.size, 4 * d), I.xavier())
        specs = [proj_w]
        proj_b = None
        if use_proj_bias:
            proj_b = _wspec(
                bias_attr if isinstance(bias_attr, ParamAttr) else None,
                f"{name}_{suffix}_transform", "wbias", (4 * d,),
                I.constant(0.0))
            specs.append(proj_b)
        w = _wspec(inner_param_attr, f"{name}_{suffix}", "w0", (d, 4 * d),
                   I.paddle_default())
        specs.append(w)
        wb = None
        if use_inner_bias:
            wb = _wspec(
                inner_bias_attr if isinstance(inner_bias_attr, ParamAttr)
                else None,
                f"{name}_{suffix}", "wbias", (7 * d,), I.constant(0.0))
            specs.append(wb)
        return specs, proj_w, proj_b, w, wb

    fw_specs, fw_pw, fw_pb, fw_w, fw_wb = dir_specs("fw")
    bw_specs, bw_pw, bw_pb, bw_w, bw_wb = dir_specs("bw")

    def fwd(ctx, params, states, x):
        def bundle(proj_w, proj_b, w, wb):
            bias = params[proj_b.name] if proj_b is not None else None
            peep = None
            if wb is not None:
                full = params[wb.name]
                gate_b = full[: 4 * d]
                bias = gate_b if bias is None else bias + gate_b
                peep = full[4 * d:]
            return (params[proj_w.name], bias, params[w.name], peep)

        return rnn_ops.bilstm_fused(
            x, bundle(fw_pw, fw_pb, fw_w, fw_wb),
            bundle(bw_pw, bw_pb, bw_w, bw_wb))

    return LayerOutput(name=name, layer_type="bilstm", size=2 * d,
                       parents=(input,),
                       param_specs=tuple(fw_specs + bw_specs), fn=fwd,
                       attrs={"reversed_field": True})


def bigru(input: LayerOutput, size: int, name: str | None = None,
          param_attr: ParamAttr | None = None, bias_attr=None,
          inner_param_attr: ParamAttr | None = None,
          inner_bias_attr=None) -> LayerOutput:
    """Bidirectional GRU (input projections included) as ONE layer node,
    lowering to ``ops/rnn.bigru_fused``: with the ``fused_kernels`` flag
    on (on TPU) both directions run in a single Pallas program over one
    residency of all six weight matrices (``bigru_seq``) — the composed
    fc + grumemory pair pays the input/weight streaming twice;
    otherwise the exact unfused composition.

    Parameter naming mirrors the composed ``simple_gru2`` form:
    ``<name>_fw_transform.w0``/``.wbias`` (the 3*size input projection)
    and ``<name>_fw.w0``/``.wbias`` (the grumemory-convention [D, 3D]
    recurrent weight — [:, :2D] gates, [:, 2D:] candidate — plus the
    3*size gate bias), same for ``_bw``.  Output is the [fw, bw]
    feature concat (size 2*size)."""
    name = name or gen_name("bigru")
    d = size
    use_proj_bias = bias_attr is not False
    use_inner_bias = inner_bias_attr is not False

    def dir_specs(suffix):
        proj_w = _wspec(param_attr, f"{name}_{suffix}_transform", "w0",
                        (input.size, 3 * d), I.xavier())
        specs = [proj_w]
        proj_b = None
        if use_proj_bias:
            proj_b = _wspec(
                bias_attr if isinstance(bias_attr, ParamAttr) else None,
                f"{name}_{suffix}_transform", "wbias", (3 * d,),
                I.constant(0.0))
            specs.append(proj_b)
        w = _wspec(inner_param_attr, f"{name}_{suffix}", "w0", (d, 3 * d),
                   I.paddle_default())
        specs.append(w)
        wb = None
        if use_inner_bias:
            wb = _wspec(
                inner_bias_attr if isinstance(inner_bias_attr, ParamAttr)
                else None,
                f"{name}_{suffix}", "wbias", (3 * d,), I.constant(0.0))
            specs.append(wb)
        return specs, proj_w, proj_b, w, wb

    fw_specs, fw_pw, fw_pb, fw_w, fw_wb = dir_specs("fw")
    bw_specs, bw_pw, bw_pb, bw_w, bw_wb = dir_specs("bw")

    def fwd(ctx, params, states, x):
        def bundle(proj_w, proj_b, w, wb):
            bias = params[proj_b.name] if proj_b is not None else None
            if wb is not None:
                gate_b = params[wb.name]
                bias = gate_b if bias is None else bias + gate_b
            full = params[w.name]
            return (params[proj_w.name], bias, full[:, : 2 * d],
                    full[:, 2 * d:])

        return rnn_ops.bigru_fused(
            x, bundle(fw_pw, fw_pb, fw_w, fw_wb),
            bundle(bw_pw, bw_pb, bw_w, bw_wb))

    return LayerOutput(name=name, layer_type="bigru", size=2 * d,
                       parents=(input,),
                       param_specs=tuple(fw_specs + bw_specs), fn=fwd,
                       attrs={"reversed_field": True})


# ---------------------------------------------------------------------------
# output / decoding layers
# ---------------------------------------------------------------------------


def max_id(input: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ maxid_layer (MaxIdLayer)."""
    name = name or gen_name("maxid_layer")

    def fwd(ctx, params, states, x):
        return map_data(lambda d: jnp.argmax(d, axis=-1).astype(jnp.int32), x)

    return LayerOutput(name=name, layer_type="maxid", size=1, parents=(input,), fn=fwd)


maxid_layer = max_id


def sampling_id(input: LayerOutput, name: str | None = None) -> LayerOutput:
    """≅ sampling_id_layer (SamplingIdLayer): sample from the row distribution."""
    name = name or gen_name("sampling_id_layer")

    def fwd(ctx, params, states, x):
        key = ctx.key_for(name)
        logits = jnp.log(jnp.maximum(raw(x), 1e-20))
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    return LayerOutput(name=name, layer_type="sampling_id", size=input.size,
                       parents=(input,), fn=fwd)


sampling_id_layer = sampling_id


def eos(input: LayerOutput, eos_id: int, name: str | None = None) -> LayerOutput:
    """≅ eos_layer (EosIdCheckLayer)."""
    name = name or gen_name("eos_layer")

    def fwd(ctx, params, states, x):
        return (raw(x) == eos_id).astype(jnp.int32)

    return LayerOutput(name=name, layer_type="eos_id", size=1, parents=(input,),
                       fn=fwd, attrs={"eos_id": eos_id})


eos_layer = eos


# ---------------------------------------------------------------------------
# cost layers
# ---------------------------------------------------------------------------


def _cost_node(name, ltype, parents, fwd, attrs=None, specs=()):
    return LayerOutput(
        name=name, layer_type=ltype, size=1, parents=tuple(parents),
        param_specs=tuple(specs), fn=fwd, attrs=dict(attrs or {}),
    )


def _mean_over_batch(per_example):
    return jnp.mean(per_example)


def _seq_aware_ce(probs_value, label_value, ce_fn, weight_value=None):
    """Cross-entropy that treats each valid timestep of a sequence batch as
    one instance (the reference flattens sequences into instances via
    Argument; padding must not contribute).  ``weight_value`` (optional) is a
    per-sequence or per-timestep instance weight folded into the mask."""
    p = raw(probs_value)
    y = raw(label_value)
    if is_sequence(probs_value) or is_sequence(label_value):
        seq = probs_value if is_sequence(probs_value) else label_value
        v = p.shape[-1]
        ce = ce_fn(p.reshape(-1, v), y.reshape(-1))
        m = seq.mask().reshape(-1)
        if weight_value is not None:
            wv = raw(weight_value)
            if wv.ndim == 1 or wv.shape == (seq.batch_size, 1):
                wv = jnp.broadcast_to(
                    wv.reshape(seq.batch_size, 1),
                    (seq.batch_size, seq.max_len))
            m = m * wv.reshape(-1)
        return jnp.sum(ce * m) / jnp.clip(jnp.sum(m), 1e-9)
    return None  # caller falls back to dense path


def classification_cost(input: LayerOutput, label: LayerOutput, weight=None,
                        name: str | None = None, evaluator=None,
                        coeff: float = 1.0) -> LayerOutput:
    """≅ classification_cost (layers.py:4390): input is post-softmax probs;
    adds a classification-error metric like the reference's auto evaluator."""
    name = name or gen_name("cost")
    parents = [input, label] + ([weight] if weight is not None else [])

    logits_fn = input.attrs.get("__fc_logits__")
    specs = ()
    if logits_fn is not None:
        # fused-from-logits CE (lse(logits) - logits[y]): the producing
        # softmax fc (or a recurrent_group whose sunk tail ends in one)
        # exposes a logits closure with ITS parents/params; computing the
        # cost from it removes the [.., V] softmax round-trip and its
        # backward — when nothing else consumes the probs, XLA never
        # materialises them at all.  Identical to -log(p[y]) up to fp
        # rounding and the old path's +1e-10 guard.
        n_emit = len(parents)  # wire config shows only input/label/weight
        # ONE hidden node computes the logits, and the probs node is
        # REWIRED to softmax(logits): every consumer — this cost, the
        # auto error metric (argmax-invariant), eval fetches, any later
        # layer — shares the single heavy computation.  Two separate
        # logits closures would instead duplicate the producing scan
        # (XLA does not CSE while loops; measured 9.02 vs 7.28 ms on
        # NMT), and leaving probs on the original fn would re-run it
        # whenever anything kept the probs live (eval steps always do).
        logits_node = input.attrs.get("__logits_node__")
        if logits_node is None:
            logits_node = LayerOutput(
                name=name + "#logits", layer_type="fc",
                size=input.size, parents=input.parents,
                param_specs=input.param_specs,
                state_specs=input.state_specs, fn=logits_fn,
                attrs={"__hidden__": True})
            softmax_act = act_mod.SoftmaxActivation()

            def probs_fn(ctx, params, states, lg):
                y = softmax_act(raw(lg))
                if isinstance(lg, SequenceBatch):
                    return SequenceBatch(data=y, length=lg.length)
                return y

            # emission still prints the ORIGINAL wiring (the companion is
            # a runtime artifact); dfs_parents keeps outputs() inference
            # walking the real graph
            input.attrs["__emit_parent_nodes__"] = input.parents
            input.attrs.setdefault("dfs_parents", input.parents)
            input.attrs["__logits_node__"] = logits_node
            input.parents = (logits_node,)
            input.state_specs = ()  # companion owns the state updates
            input.fn = probs_fn
        parents = parents + [logits_node]
        specs = ()  # the logits node carries the fc/group params
        n_w = 1 if weight is not None else 0

        def _logits_ce(lg2d, y):
            lse = jax.nn.logsumexp(lg2d.astype(jnp.float32), axis=-1)
            tgt = jnp.take_along_axis(
                lg2d, y.reshape(-1, 1).astype(jnp.int32), axis=-1)[:, 0]
            return lse - tgt.astype(jnp.float32)

        def fwd(ctx, params, states, probs, lbl, *rest):
            w = rest[0] if n_w else None
            logits = rest[-1]
            seq_ce = _seq_aware_ce(logits, lbl, _logits_ce, w)
            if seq_ce is not None:
                return coeff * seq_ce
            ce = _logits_ce(raw(logits), raw(lbl).reshape(-1))
            if w is not None:
                ce = ce * raw(w).reshape(-1)
            return coeff * _mean_over_batch(ce)
    else:
        def fwd(ctx, params, states, probs, lbl, *w):
            seq_ce = _seq_aware_ce(probs, lbl, loss_ops.cross_entropy,
                                   w[0] if w else None)
            if seq_ce is not None:
                return coeff * seq_ce
            p = raw(probs)
            y = raw(lbl).reshape(-1)
            ce = loss_ops.cross_entropy(p, y)
            if w:
                ce = ce * raw(w[0]).reshape(-1)
            return coeff * _mean_over_batch(ce)

    node = _cost_node(name, "multi-class-cross-entropy", parents, fwd,
                      {"coeff": coeff}, specs=specs)
    ev_inputs = [input.name, label.name]
    if weight is not None:
        ev_inputs.append(weight.name)
    node.attrs["metric"] = ("classification_error", ev_inputs)
    if logits_fn is not None:
        node.attrs["__emit_parents__"] = n_emit
        # runtime metric reads the logits (argmax-equal); the emitted
        # evaluator block keeps the reference's probs-layer name
        # logits_node.name, NOT name+"#logits": a second cost on the same
        # softmax layer reuses the FIRST call's companion
        node.attrs["metric_runtime"] = (
            "classification_error", [logits_node.name, label.name])
    node.attrs["v1_cost"] = True  # LayerType.COST — outputs() DFS predicate
    return node


def cross_entropy_cost(input: LayerOutput, label: LayerOutput,
                       name: str | None = None, coeff: float = 1.0) -> LayerOutput:
    """≅ cross_entropy (CostLayer MultiClassCrossEntropy)."""
    name = name or gen_name("cross_entropy")

    def fwd(ctx, params, states, probs, lbl):
        seq_ce = _seq_aware_ce(probs, lbl, loss_ops.cross_entropy)
        if seq_ce is not None:
            return coeff * seq_ce
        return coeff * _mean_over_batch(
            loss_ops.cross_entropy(raw(probs), raw(lbl).reshape(-1))
        )

    return _cost_node(name, "multi-class-cross-entropy", [input, label], fwd)


cross_entropy = cross_entropy_cost


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha: float = 0.1,
                                name=None) -> LayerOutput:
    """≅ cross_entropy_with_selfnorm (CostLayer)."""
    name = name or gen_name("cross_entropy_with_selfnorm")

    def fwd(ctx, params, states, probs, lbl):
        p = raw(probs)
        ce = loss_ops.cross_entropy(p, raw(lbl).reshape(-1))
        z = jnp.sum(p, axis=-1)
        return _mean_over_batch(ce + softmax_selfnorm_alpha * jnp.log(z) ** 2)

    return _cost_node(name, "multi_class_cross_entropy_with_selfnorm",
                      [input, label], fwd)


def square_error_cost(input: LayerOutput, label: LayerOutput, weight=None,
                      name: str | None = None, coeff: float = 1.0) -> LayerOutput:
    """≅ square_error_cost / regression_cost (SumOfSquaresCostLayer)."""
    name = name or gen_name("square_error_cost")
    parents = [input, label] + ([weight] if weight is not None else [])

    def fwd(ctx, params, states, pred, lbl, *w):
        c = loss_ops.square_error(raw(pred), raw(lbl))
        if w:
            c = c * raw(w[0]).reshape(-1)
        return coeff * _mean_over_batch(c)

    node = _cost_node(name, "square_error", parents, fwd)
    node.attrs["v1_cost"] = True  # LayerType.COST (layers.py:4335)
    return node


regression_cost = square_error_cost


def mse_cost(input, label, name=None, coeff: float = 1.0):
    return square_error_cost(input, label, name=name, coeff=coeff)


def multi_binary_label_cross_entropy(input, label, name=None,
                                     coeff: float = 1.0) -> LayerOutput:
    """≅ multi_binary_label_cross_entropy (MultiBinaryLabelCrossEntropy)."""
    name = name or gen_name("multi_binary_label_cross_entropy")

    def fwd(ctx, params, states, p, lbl):
        return coeff * _mean_over_batch(
            loss_ops.binary_cross_entropy(raw(p), raw(lbl))
        )

    return _cost_node(name, "multi_binary_label_cross_entropy", [input, label], fwd)


def smooth_l1_cost(input, label, name=None, coeff: float = 1.0) -> LayerOutput:
    """≅ smooth_l1_cost (SmoothL1CostLayer)."""
    name = name or gen_name("smooth_l1_cost")

    def fwd(ctx, params, states, p, lbl):
        return coeff * _mean_over_batch(loss_ops.smooth_l1(raw(p), raw(lbl)))

    return _cost_node(name, "smooth_l1", [input, label], fwd)


def huber_regression_cost(input, label, delta: float = 1.0, name=None,
                          coeff: float = 1.0) -> LayerOutput:
    """≅ huber_regression_cost."""
    name = name or gen_name("huber_regression_cost")

    def fwd(ctx, params, states, p, lbl):
        return coeff * _mean_over_batch(loss_ops.huber_regression(raw(p), raw(lbl), delta))

    return _cost_node(name, "huber_regression", [input, label], fwd)


def huber_classification_cost(input, label, name=None, coeff: float = 1.0) -> LayerOutput:
    """≅ huber_classification_cost (HuberTwoClassification)."""
    name = name or gen_name("huber_classification_cost")

    def fwd(ctx, params, states, p, lbl):
        return coeff * _mean_over_batch(
            loss_ops.huber_classification(raw(p), raw(lbl))
        )

    return _cost_node(name, "huber_classification", [input, label], fwd)


def rank_cost(left: LayerOutput, right: LayerOutput, label: LayerOutput,
              weight=None, name=None, coeff: float = 1.0) -> LayerOutput:
    """≅ rank_cost (RankingCost)."""
    name = name or gen_name("rank_cost")
    parents = [left, right, label] + ([weight] if weight is not None else [])

    def fwd(ctx, params, states, l, r, lbl, *w):
        c = loss_ops.rank_cost(raw(l), raw(r), raw(lbl))
        if w:
            c = c * raw(w[0]).reshape(-1)
        return coeff * _mean_over_batch(c)

    return _cost_node(name, "rank-cost", parents, fwd)


def lambda_cost(input: LayerOutput, score: LayerOutput, NDCG_num: int = 5,
                max_sort_size: int = -1, name=None) -> LayerOutput:
    """≅ lambda_cost (LambdaCost) over a sequence of scores."""
    name = name or gen_name("lambda_cost")

    def fwd(ctx, params, states, x, s):
        return _mean_over_batch(
            loss_ops.lambda_cost(raw(x), raw(s), x.mask() if is_sequence(x) else
                                 jnp.ones(raw(x).shape[:2]), NDCG_num)
        )

    return _cost_node(name, "lambda_cost", [input, score], fwd)


def sum_cost(input: LayerOutput, name=None) -> LayerOutput:
    """≅ sum_cost (SumCostLayer)."""
    name = name or gen_name("sum_cost")

    def fwd(ctx, params, states, x):
        return jnp.mean(loss_ops.sum_cost(raw(x)))

    return _cost_node(name, "sum_cost", [input], fwd)


def nce(input, label, num_classes: int | None = None, num_neg_samples: int = 10,
        weight=None, neg_distribution=None, act=None,
        param_attr=None, bias_attr=None, name=None, layer_attr=None) -> LayerOutput:
    """≅ nce_layer (NCELayer) with uniform (or given) noise sampling.
    ``num_classes`` defaults to the label layer's size (layers.py:5489)."""
    name = name or gen_name("nce_layer")
    inputs = _as_list(input)
    if num_classes is None:
        num_classes = label.size
    pattrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    wspecs = [
        _wspec(pa, name, f"w{i}", (num_classes, inp.size), I.paddle_default())
        for i, (inp, pa) in enumerate(zip(inputs, pattrs))
    ]
    bspec = _wspec(bias_attr if isinstance(bias_attr, ParamAttr) else None,
                   name, "wbias", (num_classes,), I.constant(0.0))
    parents = inputs + [label] + ([weight] if weight is not None else [])
    if neg_distribution is not None:
        neg_distribution = list(neg_distribution)
        enforce(len(neg_distribution) == num_classes,
                "nce: neg_distribution length must equal num_classes")
        enforce(abs(sum(neg_distribution) - 1.0) < 1e-5,
                "nce: neg_distribution must sum to 1")
    nd = None if neg_distribution is None else jnp.asarray(
        neg_distribution, jnp.float32)

    def fwd(ctx, params, states, *vals):
        xs = vals[: len(inputs)]
        lbl = vals[len(inputs)]
        wgt = vals[len(inputs) + 1:]
        key = ctx.key_for(name)
        x = jnp.concatenate(
            [raw(v).reshape(raw(v).shape[0], -1) for v in xs], axis=-1
        )
        w = jnp.concatenate([params[ws.name] for ws in wspecs], axis=-1)
        b = x.shape[0]
        if nd is None:
            noise = jax.random.randint(key, (b, num_neg_samples), 0, num_classes)
        else:
            noise = jax.random.categorical(
                key, jnp.log(jnp.maximum(nd, 1e-20)), shape=(b, num_neg_samples)
            )
        c = loss_ops.nce_loss(x, w, params[bspec.name],
                              raw(lbl).reshape(-1).astype(jnp.int32), noise,
                              num_classes, noise_probs=nd)
        if wgt:
            c = c * raw(wgt[0]).reshape(-1)
        return _mean_over_batch(c)

    node = _cost_node(name, "nce", parents, fwd, specs=wspecs + [bspec])
    node.attrs.update(
        num_classes=num_classes, num_neg_samples=num_neg_samples,
        neg_sampling_dist=neg_distribution,
        n_inputs=len(inputs),
    )
    return node


nce_layer = nce


def hsigmoid(input, label, num_classes: int | None = None, param_attr=None,
             bias_attr=None, name=None, layer_attr=None) -> LayerOutput:
    """≅ hsigmoid (HierarchicalSigmoidLayer)."""
    name = name or gen_name("hsigmoid")
    inputs = _as_list(input)
    if num_classes is None:
        num_classes = label.size  # reference defaults to label layer size
    pattrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    wspecs = [
        _wspec(pa, name, f"w{i}", (num_classes - 1, inp.size),
               I.paddle_default())
        for i, (inp, pa) in enumerate(zip(inputs, pattrs))
    ]
    bspec = _wspec(bias_attr if isinstance(bias_attr, ParamAttr) else None,
                   name, "wbias", (num_classes - 1,), I.constant(0.0))

    def fwd(ctx, params, states, *parents):
        xs = [raw(p) for p in parents[:-1]]
        x = jnp.concatenate([v.reshape(v.shape[0], -1) for v in xs], axis=-1)
        w = jnp.concatenate([params[ws.name] for ws in wspecs], axis=-1)
        lbl = raw(parents[-1]).reshape(-1).astype(jnp.int32)
        return _mean_over_batch(
            loss_ops.hsigmoid_loss(x, w, params[bspec.name], lbl, num_classes)
        )

    node = _cost_node(name, "hsigmoid", inputs + [label], fwd,
                      specs=wspecs + [bspec])
    node.attrs["num_classes"] = num_classes
    return node


hsigmoid_layer = hsigmoid


# populated lazily to avoid import cycles: crf/ctc/recurrent_group live in
# sibling modules re-exported here at bottom of file.


def mixed(*args, **kwargs):
    from paddle_tpu.layers import mixed as _m

    return _m.mixed(*args, **kwargs)


def __getattr__(name):
    # lazy re-exports from sibling modules (mixed/crf/ctc/recurrent_group/attention)
    import importlib

    for modname in ("mixed", "extras", "recurrent_group", "more", "detection"):
        try:
            mod = importlib.import_module(f"paddle_tpu.layers.{modname}")
        except ImportError:
            continue
        if hasattr(mod, name):
            obj = getattr(mod, name)
            globals()[name] = obj
            return obj
    raise AttributeError(f"module 'paddle_tpu.layer' has no attribute {name!r}")
