"""Composite network helpers — successor of
``trainer_config_helpers/networks.py`` (simple_img_conv_pool, img_conv_group,
simple_lstm, bidirectional_lstm, simple_gru, vgg_16_network …)."""

from __future__ import annotations

from paddle_tpu.layers import activation as act_mod
from paddle_tpu.layers import api as layer
from paddle_tpu.layers import pooling as pool_mod
from paddle_tpu.layers.attr import ExtraAttr


def simple_img_conv_pool(input, filter_size, num_filters, pool_size, name=None,
                         pool_type=None, act=None, groups=1, conv_stride=1,
                         conv_padding=0, num_channel=None, param_attr=None,
                         pool_stride=1, pool_padding=0, **kw):
    """≅ networks.simple_img_conv_pool."""
    conv = layer.img_conv(
        input=input, filter_size=filter_size, num_filters=num_filters,
        num_channels=num_channel, stride=conv_stride, padding=conv_padding,
        groups=groups, act=act, param_attr=param_attr,
        name=f"{name}_conv" if name else None,
    )
    return layer.img_pool(
        input=conv, pool_size=pool_size, pool_type=pool_type,
        stride=pool_stride, padding=pool_padding,
        name=f"{name}_pool" if name else None,
    )


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0,
                   pool_stride=1, pool_type=None, **kw):
    """≅ networks.img_conv_group (the VGG building block)."""
    tmp = input
    if not isinstance(conv_padding, list):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, list):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = layer.img_conv(
            input=tmp, filter_size=conv_filter_size, num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            padding=conv_padding[i],
            act=act_mod.LinearActivation() if conv_with_batchnorm else conv_act,
        )
        if conv_with_batchnorm:
            tmp = layer.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layer.dropout(input=tmp, dropout_rate=conv_batchnorm_drop_rate[i])
    return layer.img_pool(input=tmp, pool_size=pool_size, stride=pool_stride,
                          pool_type=pool_type or pool_mod.MaxPooling())


def small_vgg(input_image, num_channels, num_classes):
    """≅ networks.small_vgg (networks.py:438): four BN'd VGG conv groups
    (64x2, 128x2, 256x3, 512x3) + pool/dropout/fc/BN head -> softmax."""
    def _vgg(ipt, num_filter, times, dropouts, num_channels_=None):
        return img_conv_group(
            input=ipt, num_channels=num_channels_, pool_size=2,
            pool_stride=2, conv_num_filter=[num_filter] * times,
            conv_filter_size=3, conv_act=act_mod.ReluActivation(),
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type=pool_mod.MaxPooling())

    tmp = _vgg(input_image, 64, 2, [0.3, 0], num_channels)
    tmp = _vgg(tmp, 128, 2, [0.4, 0])
    tmp = _vgg(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = _vgg(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = layer.img_pool(input=tmp, stride=2, pool_size=2,
                         pool_type=pool_mod.MaxPooling())
    tmp = layer.dropout(input=tmp, dropout_rate=0.5)
    tmp = layer.fc(input=tmp, size=512, act=act_mod.LinearActivation(),
                   layer_attr=ExtraAttr(drop_rate=0.5))
    tmp = layer.batch_norm(input=tmp, act=act_mod.ReluActivation())
    return layer.fc(input=tmp, size=num_classes,
                    act=act_mod.SoftmaxActivation())


def simple_lstm(input, size, name=None, reverse=False, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None, act=None,
                gate_act=None, state_act=None, **kw):
    """≅ networks.simple_lstm: fc(4*size) -> lstmemory."""
    fc = layer.fc(input=input, size=size * 4, act=act_mod.LinearActivation(),
                  param_attr=mat_param_attr, bias_attr=bias_param_attr,
                  name=f"{name}_transform" if name else None)
    return layer.lstmemory(input=fc, reverse=reverse, param_attr=inner_param_attr,
                           act=act, gate_act=gate_act, state_act=state_act,
                           name=name)


def simple_gru(input, size, name=None, reverse=False,
               mixed_param_attr=None, mixed_bias_param_attr=None,
               mixed_layer_attr=None, gru_param_attr=None,
               gru_bias_attr=None, act=None, gate_act=None,
               gru_layer_attr=None, **kw):
    """≅ networks.simple_gru (networks.py:1047): mixed Wx transform +
    gru_group (the in-group gru, each step addressable)."""
    from paddle_tpu.layers.base import gen_name
    from paddle_tpu.layers.mixed import full_matrix_projection, mixed_layer
    from paddle_tpu.layers.recurrent_group import gru_group

    name = name or gen_name("simple_gru")
    with mixed_layer(name=f"{name}_transform", size=size * 3,
                     bias_attr=mixed_bias_param_attr,
                     layer_attr=mixed_layer_attr) as m:
        m += full_matrix_projection(input=input, param_attr=mixed_param_attr)
    return gru_group(name=name, size=size, input=m, reverse=reverse, act=act,
                     gate_act=gate_act, gru_bias_attr=gru_bias_attr,
                     gru_param_attr=gru_param_attr,
                     gru_layer_attr=gru_layer_attr)


def simple_gru2(input, size, name=None, reverse=False,
                mixed_param_attr=None, mixed_bias_attr=None,
                mixed_layer_attr=None, gru_param_attr=None,
                gru_bias_attr=None, act=None, gate_act=None,
                gru_cell_attr=None, **kw):
    """≅ networks.simple_gru2 (networks.py:1111): mixed Wx transform +
    single-layer grumemory (faster than the in-group form)."""
    from paddle_tpu.layers.base import gen_name
    from paddle_tpu.layers.mixed import full_matrix_projection, mixed_layer

    name = name or gen_name("simple_gru2")
    with mixed_layer(name=f"{name}_transform", size=size * 3,
                     bias_attr=mixed_bias_attr,
                     layer_attr=mixed_layer_attr) as m:
        m += full_matrix_projection(input=input, param_attr=mixed_param_attr)
    return layer.grumemory(input=m, reverse=reverse, name=name,
                           bias_attr=gru_bias_attr, param_attr=gru_param_attr,
                           act=act, gate_act=gate_act,
                           layer_attr=gru_cell_attr)


def bidirectional_lstm(input, size, name=None, return_seq=False, **kw):
    """≅ networks.bidirectional_lstm: fwd+bwd simple_lstm, concat."""
    fwd = simple_lstm(input=input, size=size, name=f"{name}_fw" if name else None)
    bwd = simple_lstm(input=input, size=size, reverse=True,
                      name=f"{name}_bw" if name else None)
    if return_seq:
        return layer.concat(input=[fwd, bwd])
    f_last = layer.last_seq(input=fwd)
    b_first = layer.first_seq(input=bwd)
    return layer.concat(input=[f_last, b_first])


def bidirectional_gru(input, size, name=None, return_seq=False,
                      last_seq_attr=None, first_seq_attr=None,
                      concat_attr=None, concat_act=None, **kw):
    """≅ networks.bidirectional_gru (networks.py:1130): fw/bw simple_gru2,
    concatenated (whole sequences or last/first steps)."""
    from paddle_tpu.layers.base import gen_name

    name = name or gen_name("bidirectional_gru")
    fw_args = {k[len("fwd_"):]: v for k, v in kw.items()
               if k.startswith("fwd_")}
    bw_args = {k[len("bwd_"):]: v for k, v in kw.items()
               if k.startswith("bwd_")}
    fw = simple_gru2(input=input, size=size, name=f"{name}_fw", **fw_args)
    bw = simple_gru2(input=input, size=size, reverse=True,
                     name=f"{name}_bw", **bw_args)
    if return_seq:
        return layer.concat(name=name, input=[fw, bw], act=concat_act,
                            layer_attr=concat_attr)
    f_last = layer.last_seq(name=f"{name}_fw_last", input=fw)
    b_first = layer.first_seq(name=f"{name}_bw_last", input=bw)
    return layer.concat(name=name, input=[f_last, b_first], act=concat_act,
                        layer_attr=concat_attr)


def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None, context_proj_param_attr=None,
                       fc_param_attr=None, fc_act=None, **kw):
    """≅ networks.sequence_conv_pool (text conv: context window + fc + pool)."""
    proj = layer.context_projection_layer(
        input=input, context_len=context_len, context_start=context_start,
        padding_attr=context_proj_param_attr or False,
        name=f"{name}_proj" if name else None,
    )
    fc = layer.fc(input=proj, size=hidden_size, act=fc_act or act_mod.TanhActivation(),
                  param_attr=fc_param_attr, name=f"{name}_fc" if name else None)
    return layer.pooling(input=fc, pooling_type=pool_type or pool_mod.MaxPooling(),
                         name=f"{name}_pool" if name else None)


def text_conv_pool(input, context_len=5, hidden_size=128, **kw):
    return sequence_conv_pool(input, context_len, hidden_size, **kw)


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """≅ networks.vgg_16_network."""
    tmp = input_image
    for i, (n, nf) in enumerate([(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[nf] * n, pool_size=2,
            num_channels=num_channels if i == 0 else None,
            conv_act=act_mod.ReluActivation(), pool_stride=2,
            pool_type=pool_mod.MaxPooling(),
        )
    tmp = layer.fc(input=tmp, size=4096, act=act_mod.ReluActivation())
    tmp = layer.dropout(input=tmp, dropout_rate=0.5)
    tmp = layer.fc(input=tmp, size=4096, act=act_mod.ReluActivation())
    tmp = layer.dropout(input=tmp, dropout_rate=0.5)
    return layer.fc(input=tmp, size=num_classes, act=act_mod.SoftmaxActivation())


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None):
    """Bahdanau additive attention context (≅ networks.simple_attention:1304):

        e_ij = v_a . f(W_a s_{i-1} + U_a h_j);  a_ij = softmax_j(e_ij);
        c_i = sum_j a_ij h_j

    where U_a h_j is precomputed outside the loop as ``encoded_proj``.  The
    reference assembles this from mixed/expand/seq-softmax/scaling/pooling
    layers; here it is one fused node (one small matmul + masked softmax +
    weighted sum — XLA fuses the lot), with the same parameters: W_a
    (transform) and v_a (softmax weight).  Works inside recurrent_group steps:
    ``encoded_sequence``/``encoded_proj`` enter via StaticInput, and
    ``decoder_state`` is a memory."""
    import jax.numpy as jnp

    from paddle_tpu.core import dtype as dt
    from paddle_tpu.core import initializer as I
    from paddle_tpu.layers.api import _wspec
    from paddle_tpu.layers.base import LayerOutput, gen_name

    name = name or gen_name("simple_attention")
    proj_size = encoded_proj.size
    w_spec = _wspec(transform_param_attr, f"{name}_transform", "w",
                    (decoder_state.size, proj_size), I.paddle_default())
    v_spec = _wspec(softmax_param_attr, f"{name}_softmax", "w",
                    (proj_size, 1), I.paddle_default())
    wact = act_mod.get(weight_act) if weight_act else act_mod.TanhActivation()

    def fwd(ctx, params, states, enc_seq, enc_proj, dec_state):
        # enc_seq: SequenceBatch [B,T,D]; enc_proj: SequenceBatch [B,T,P];
        # dec_state: [B,S] (memory value inside a recurrent step)
        comb = wact(
            (dec_state @ params[w_spec.name])[:, None, :] + enc_proj.data)
        scores = (comb @ params[v_spec.name])[..., 0]  # [B, T]
        mask = enc_seq.mask()
        scores = jnp.where(mask > 0, scores, -1e9)
        attn = jnp.exp(scores - scores.max(axis=1, keepdims=True)) * mask
        attn = attn / jnp.clip(attn.sum(axis=1, keepdims=True), 1e-9)
        return jnp.einsum("bt,btd->bd", attn, enc_seq.data,
                          precision=dt.dot_precision(attn, enc_seq.data))

    return LayerOutput(
        name=name, layer_type="simple_attention", size=encoded_sequence.size,
        parents=(encoded_sequence, encoded_proj, decoder_state),
        param_specs=(w_spec, v_spec), fn=fwd,
        attrs={"proj_size": proj_size})
