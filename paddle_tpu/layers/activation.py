"""Activation objects — successor of ``trainer_config_helpers/activations.py``
(TanhActivation() etc. passed as ``act=`` to layer helpers)."""

from __future__ import annotations

import dataclasses
from typing import Callable

from paddle_tpu.ops import activations as _ops


@dataclasses.dataclass(frozen=True)
class BaseActivation:
    name: str
    fn: Callable

    def __call__(self, x):
        return self.fn(x)


def _mk(name: str) -> Callable[[], BaseActivation]:
    def ctor(**kwargs):
        fn = _ops.get(name)
        if kwargs:
            base = fn
            fn = lambda x: base(x, **kwargs)  # noqa: E731
        return BaseActivation(name=name, fn=fn)

    ctor.__name__ = name
    return ctor


LinearActivation = _mk("")  # reference IdentityActivation proto name is ""
IdentityActivation = LinearActivation
SigmoidActivation = _mk("sigmoid")
TanhActivation = _mk("tanh")
ReluActivation = _mk("relu")
BReluActivation = _mk("brelu")
SoftReluActivation = _mk("softrelu")
STanhActivation = _mk("stanh")
AbsActivation = _mk("abs")
SquareActivation = _mk("square")
ExpActivation = _mk("exponential")
LogActivation = _mk("log")
SoftmaxActivation = _mk("softmax")
SequenceSoftmaxActivation = _mk("sequence_softmax")
ELUActivation = _mk("elu")
LeakyReluActivation = _mk("leaky_relu")
GeluActivation = _mk("gelu")
SwishActivation = _mk("swish")
SqrtActivation = _mk("sqrt")
ReciprocalActivation = _mk("reciprocal")


def get(act):
    """Normalize act argument: None -> linear; str -> registry; object -> itself."""
    if act is None:
        return BaseActivation("", _ops.identity)
    if isinstance(act, str):
        return BaseActivation(act, _ops.get(act))
    return act
