"""Goodput ledger — wall-clock badput attribution.

The reference's ``paddle/utils/Stat.h`` timer dumps answered "how long
did X take on average" but never "what fraction of the run was
productive, and where did the rest go" — the aggregates don't compose
into one wall-clock account.  This module does that composition: a
:class:`GoodputLedger` classifies **every wall-clock second** between
``start()`` and ``finish()`` into productive ``compute`` vs. named
badput buckets:

``input_wait``
    the trainer blocked on the feed (``feed`` spans — the consumer-side
    wait, NOT the prefetch producer thread, which overlaps compute);
``fence``
    device sync at flush boundaries (``fence`` spans);
``recompile``
    ``compute`` spans stamped ``compile=True`` by the trainer when the
    dispatch built a new executable for an unseen signature;
``checkpoint_save`` / ``checkpoint_restore``
    cursor/final checkpoint writes (``checkpoint`` spans) and state
    restores (the trainer's retrospective ``restore`` span, cut from
    the SAME ``perf_counter`` reading that already feeds the
    ``checkpoint_restore_ms`` gauge — no new timing source);
``guard_rescue``
    NaN-guard rollback handling (``guard_rescue`` spans, minus any
    nested restore time so the two buckets never double-count);
``restart``
    supervisor fault-to-retraining overhead (the ``restarts`` counter
    delta between folds prices the ``recovery_ms`` gauge in);
``elastic_drain`` / ``elastic_reshard``
    the drain checkpoint before a live mesh rebuild (``drain`` spans)
    and the rebuild itself (``gather``/``reshard``/``rebuild`` spans);
``idle``
    whatever remains: wall-clock not covered by any classified span
    (build/placement before step 0, pass turnaround, ring overflow).

The ledger is a **fold over signals that already exist** — tracewire
spans and resilience counters.  It introduces no clocks of its own, so
a disabled run pays nothing and an enabled run's training trajectory is
bit-identical (asserted in ``tests/test_goodput.py``).  ``fold()`` is
incremental (the trainer calls it from its flush cadence): each call
classifies only spans that entered the ring since the previous call,
so the ring can wrap between run start and run end without losing the
account — only spans older than one whole ring per fold interval can
drop, and the closing record carries the tracer's drop counter so a
truncated account is visible, not silent.

``finish()`` emits one ``kind="ledger"`` telemetry record (schema /12)
with the bucket seconds, ``goodput_fraction`` (= compute / wall), the
serving cost split when serving counters are present (prefill/decode
compute-seconds, queue-seconds, KV-page occupancy-seconds,
cost-per-token — see ``serving/engine.py``), sets the
``goodput_fraction`` gauge (surfaced on ``/healthz`` and rolled up
fleet-wide by ``FleetRouter.scrape_replicas``), and appends the record
to ``<ledger_dir>/ledger.jsonl`` when a path is armed.  Render with
``tools/goodput_report.py`` or the "Goodput" table of
``tools/metrics_to_md.py``; guard regressions with
``tools/bench_sentinel.py``.
"""

from __future__ import annotations

import json
import os
import threading

# leaf span name -> badput bucket.  Parent spans ("step", "elastic",
# "request") and overlapping producer-thread spans ("prefetch") are
# deliberately absent: the ledger counts each wall-clock second once,
# from the consumer-side leaf that blocked the train loop.
_LEAF_BUCKET = {
    "feed": "input_wait",
    "fence": "fence",
    "checkpoint": "checkpoint_save",
    "restore": "checkpoint_restore",
    "guard_rescue": "guard_rescue",
    "drain": "elastic_drain",
    "gather": "elastic_reshard",
    "reshard": "elastic_reshard",
    "rebuild": "elastic_reshard",
}

BADPUT_BUCKETS = ("input_wait", "fence", "recompile", "checkpoint_save",
                  "checkpoint_restore", "guard_rescue", "restart",
                  "elastic_drain", "elastic_reshard", "idle")
BUCKETS = ("compute",) + BADPUT_BUCKETS

# restore intervals remembered for the nested-in-guard_rescue
# subtraction; a run with more restores than this merely double-counts
# the excess into guard_rescue instead of growing without bound
_MAX_RESTORE_INTERVALS = 256


class GoodputLedger:
    """Incremental wall-clock classifier over the trace-span ring.

    :param registry: metrics registry the closing record lands in;
        default the process registry.
    :param tracer: span source; default the process tracer (which must
        be enabled for the ledger to see anything — the trainer arms
        tracing when ``--goodput_ledger`` is set).
    :param clock: seconds clock for the wall measurement; default the
        TRACER's clock, so a fake-clock test drives spans and wall from
        one timeline.
    """

    def __init__(self, registry=None, tracer=None, clock=None):
        if registry is None:
            from paddle_tpu.telemetry.registry import get_default_registry

            registry = get_default_registry()
        if tracer is None:
            from paddle_tpu.telemetry.tracing import get_tracer

            tracer = get_tracer()
        self.registry = registry
        self.tracer = tracer
        self.clock = clock or tracer.clock
        self._lock = threading.Lock()
        self._buckets = {b: 0.0 for b in BUCKETS}
        self._seen_ids: set[int] = set()   # span ids of the last fold
        self._restores: list[tuple[float, float]] = []
        self._restarts_seen = 0.0
        self._spans_folded = 0
        self._t0: float | None = None
        self.record: dict | None = None

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "GoodputLedger":
        with self._lock:
            self._t0 = self.clock()
        return self

    @property
    def started(self) -> bool:
        with self._lock:
            return self._t0 is not None

    # -- the fold --------------------------------------------------------------
    def _classify(self, span) -> None:
        dur = max(0.0, span.t_end - span.t_start)
        name = span.name
        if name == "compute":
            which = "recompile" if span.args.get("compile") else "compute"
            self._buckets[which] += dur
            return
        bucket = _LEAF_BUCKET.get(name)
        if bucket is None:
            return
        if name == "restore":
            if len(self._restores) < _MAX_RESTORE_INTERVALS:
                self._restores.append((span.t_start, span.t_end))
        elif name == "guard_rescue":
            # a rollback that restored from checkpoint nests a restore
            # span inside this one; subtract it so the second lands in
            # checkpoint_restore, not twice
            for (r0, r1) in self._restores:
                if r0 >= span.t_start and r1 <= span.t_end:
                    dur -= (r1 - r0)
            dur = max(0.0, dur)
        self._buckets[bucket] += dur

    def _counter_total(self, name: str) -> float:
        m = self.registry.get(name)
        if m is None:
            return 0.0
        try:
            return float(sum(s["value"] for s in m.snapshot()))
        except (TypeError, KeyError):
            return 0.0

    def _fold_restarts(self) -> None:
        """Price supervisor restarts from the counters they already
        keep: each ``restarts`` increment observed since the last fold
        charges the last-set non-elastic ``recovery_ms`` gauge value
        (the supervisor sets it right before re-entering train)."""
        total = self._counter_total("restarts")
        delta = total - self._restarts_seen
        if delta <= 0:
            return
        self._restarts_seen = total
        g = self.registry.get("recovery_ms")
        if g is None:
            return
        vals = [s["value"] for s in g.snapshot()
                if s.get("run") != "elastic"]
        if vals:
            self._buckets["restart"] += delta * max(vals) / 1e3

    def fold(self) -> int:
        """Classify spans that entered the ring since the last fold;
        returns how many were classified this call.  Cheap enough for
        the trainer's flush cadence: one ring snapshot + a set diff,
        bounded by the ring capacity."""
        with self._lock:
            if self._t0 is None:
                return 0
            spans = self.tracer.spans
            cur = {s.span_id for s in spans}
            new = [s for s in spans if s.span_id not in self._seen_ids]
            self._seen_ids = cur
            for s in new:
                self._classify(s)
            self._fold_restarts()
            self._spans_folded += len(new)
            return len(new)

    # -- reading / closing -----------------------------------------------------
    def snapshot(self) -> dict:
        """Current bucket seconds (idle excluded — it only exists
        relative to a wall measurement, which ``finish`` takes)."""
        with self._lock:
            return dict(self._buckets)

    def finish(self, wall_s: float | None = None,
               path: str | None = None) -> dict:
        """Close the account: one final fold, ``idle`` = wall minus
        everything classified (clamped at 0), emit the ``ledger``
        record, set the ``goodput_fraction`` gauge, and append to
        ``path`` (a ledger.jsonl) when given.  Idempotent-ish: callable
        once per run; returns the record."""
        self.fold()
        with self._lock:
            if self._t0 is None:
                raise RuntimeError("GoodputLedger.finish before start")
            wall = (self.clock() - self._t0 if wall_s is None
                    else float(wall_s))
            classified = sum(v for b, v in self._buckets.items()
                             if b != "idle")
            self._buckets["idle"] = max(0.0, wall - classified)
            buckets = {b: round(self._buckets[b], 6) for b in BUCKETS}
            goodput = (self._buckets["compute"] / wall) if wall > 0 else 0.0
            rec = {
                "wall_s": round(wall, 6),
                "buckets_s": buckets,
                "goodput_fraction": round(goodput, 6),
                "badput_fraction": round(max(0.0, 1.0 - goodput), 6),
                "spans_folded": self._spans_folded,
                "spans_dropped": self.tracer.dropped,
            }
        costs = serving_costs(self.registry)
        if costs:
            rec["serving"] = costs
        self.registry.gauge(
            "goodput_fraction",
            "productive compute / wall-clock of the closing "
            "goodput ledger").set(goodput)
        if self.registry.active:
            rec = self.registry.emit(dict(rec), kind="ledger")
        if path:
            append_jsonl(rec, path)
        self.record = rec
        return rec


def serving_costs(registry) -> dict:
    """Per-token cost split from the serving engine's accumulators
    (``serving/engine.py`` folds per-request queue/prefill/decode/KV
    seconds into these counters as requests retire).  Empty dict when
    the process served nothing — a pure training run's ledger record
    carries no serving section."""
    def total(name: str) -> float:
        m = registry.get(name)
        if m is None:
            return 0.0
        try:
            return float(sum(s["value"] for s in m.snapshot()))
        except (TypeError, KeyError):
            return 0.0

    prefill = total("serve_prefill_compute_s")
    decode = total("serve_decode_compute_s")
    queue = total("serve_queue_s")
    kv = total("serve_kv_page_s")
    tokens = total("serve_tokens")
    if not (prefill or decode or queue or kv):
        return {}
    out = {
        "prefill_compute_s": round(prefill, 6),
        "decode_compute_s": round(decode, 6),
        "queue_s": round(queue, 6),
        "kv_page_s": round(kv, 6),
        "tokens": tokens,
    }
    if tokens > 0:
        out["cost_per_token_s"] = round((prefill + decode) / tokens, 9)
        out["cost_per_token_prefill_s"] = round(prefill / tokens, 9)
        out["cost_per_token_decode_s"] = round(decode / tokens, 9)
        out["cost_per_token_queue_s"] = round(queue / tokens, 9)
    return out


def append_jsonl(rec: dict, path: str) -> str:
    """Append one record to a ledger.jsonl (parent dirs created) — the
    per-run file ``tools/goodput_report.py`` and
    ``tools/bench_sentinel.py`` consume."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path
