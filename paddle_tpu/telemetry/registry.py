"""Metric primitives + the registry that owns them.

One structured stream unifies what used to be scattered (``core/stat.py``
scope timers, ``profiler.py`` MFU accounting, bench JSONL): a
:class:`MetricsRegistry` holds named counters / gauges / histograms with
labeled series (pull side, cheap in-process aggregates) and a list of
pluggable sinks (push side: one dict per emitted record — JSONL file,
in-memory for tests, logging).  The per-step train records of
``SGD.train`` / ``trainer/cli.py`` and the rows of ``bench.py`` flow
through the same :meth:`MetricsRegistry.emit`, so operators and offline
tooling (``tools/metrics_to_md.py``, ``tools/bench_to_md.py``) read one
schema.

Comm accounting: the collective wrappers in ``parallel/collective.py``
call :func:`record_comm` while XLA traces the program, so the counters
hold bytes-moved-per-executed-step of each compiled program (shapes are
static; one trace per compile signature).  ``comm_snapshot()`` flattens
them into the per-step records.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any

# /2 added the input-pipeline fields: per-step input_wait_ms (host time
# the step loop blocked waiting for a feed) and host_stall_ms (amortized
# device-fence wait per step under deferred fencing) — see
# reader/prefetch.py and SGD.train(sync_period=).
# /3 added the fault-tolerance stream (paddle_tpu/resilience/): counters
# faults_injected{kind} / faults_recovered / batches_skipped / rollbacks
# / restarts / retries{scope} / checkpoint_write_failures /
# heartbeat_stale, gauges recovery_ms / checkpoint_restore_ms, and two
# record kinds — "fault" (the numeric guard's nan_skip/nan_rollback
# events) and "recovery" (one per supervisor restart)
# /4 added the serving stream (paddle_tpu/serving/): record kinds
# "serve" (one per completed request: queue_wait_ms/ttft_ms/tpot_ms/
# total_ms) and "serve_summary" (latency histogram rollup), histograms
# serve_queue_wait_ms / serve_prefill_ms / serve_decode_step_ms /
# serve_ttft_ms / serve_tpot_ms / serve_dense_batch / serve_dense_ms,
# counters serve_requests{reason} / serve_tokens / serve_dense_requests,
# gauges serve_active_slots / serve_free_pages; histogram summaries grew
# interpolated percentile fields (p50/p90/p99)
# /5: step records carry a ``fused_kernels`` bool — whether the step's
# program routed the conv/BN/optimizer hot paths through the TPP fused
# Pallas kernels (ops/pallas/tpp), so bench streams and flight
# recordings identify which path produced a trajectory
# /6 added the elastic-fleet stream (resilience/elastic.py): record kind
# "elastic_event" — one per live mesh rebuild, carrying event
# (host_loss|scale_up), old_dp/new_dp, recovery_ms (drain→resume wall
# time), shard_source (live|checkpoint), the drain cursor and the ZeRO
# respec report — plus the elastic_events{kind} counter, the shared
# recovery_ms gauge labeled run="elastic", and the serving engine's
# serve_loop_crashes counter (background loop deaths that failed
# pending requests)
# /7 added the static-analysis stream (paddle_tpu/analysis): record
# kind "preflight" — one per `trainer --preflight` / analysis-CLI run,
# carrying the per-pass finding counts, the unsuppressed finding ids
# and whether the run was clean — plus the preflight_findings{rule}
# counter.  RECORD_KINDS (below) became the registered kind set the
# GL-SCHEMA drift pass checks every emitted record against.
# /8 added the serving-fleet stream (serving/router.py): record kind
# "fleet" — one per fleet event (replica_down with its failover
# requeue count, swap / swap_rollback for rolling weight swaps, and
# the summary availability rollup whose requests_lost must be 0) —
# plus the fleet_failovers / fleet_requeued / fleet_shed{reason} /
# fleet_swaps / fleet_swap_rollbacks / fleet_deadline_expired /
# fleet_redial_exhausted / fleet_duplicate_results /
# fleet_replica_down{reason} counters and the fleet_alive_replicas /
# fleet_queue_depth gauges.
# /10 added the per-step input padding signal (sequence bucketing):
# step records carry ``padding_ratio`` (padded/total timesteps across
# the feed's SequenceBatch slots, omitted for non-sequence feeds) plus
# the matching pull-side padding_ratio gauge — rendered by
# tools/metrics_to_md.py with a flag when >25% of fed timesteps are
# padding (the signal that the reader should bucket by length).  No new
# record kinds.
# /9 extended the "preflight" record with the GL-P-MEM static memory
# report (graftlint v2): a ``memory`` dict carrying the per-device byte
# accounting of the built step — params_bytes, opt_state_bytes (under
# the active zero mode's state_specs layout), states_bytes, feed_bytes,
# activation_bytes (+ activation_source: jaxpr-liveness or
# xla-memory-analysis), total_bytes, dp, zero and the per-pallas_call
# pallas_vmem footprints — rendered as a budget table by
# tools/metrics_to_md.py.  No new record kinds.
# /11 added the live-introspection stream (telemetry/tracing.py,
# telemetry/introspect.py): record kind "profile" — one per
# --profile_steps windowed jax.profiler capture, carrying
# start_step/end_step, trace_dir, wall_ms and (with --trace_spans) the
# tracer's per-phase duration summary {phase: {count, total_ms, p50_ms,
# p99_ms, max_ms}} rendered by tools/metrics_to_md.py's "Trace spans"
# table.  Histogram summaries became None-safe at zero observations
# (min/max clamp to 0 instead of leaking ±inf into JSON).
# /12 added the goodput ledger (telemetry/goodput.py): record kind
# "ledger" — one per run close, classifying every wall-clock second
# into productive compute vs. named badput buckets (input_wait, fence,
# recompile, checkpoint_save, checkpoint_restore, guard_rescue,
# restart, elastic_drain, elastic_reshard, idle) folded from existing
# tracewire spans and resilience counters, plus the serving cost
# split (prefill/decode compute-seconds, queue-seconds, KV-page
# occupancy-seconds, cost_per_token).  The "serve" record gained
# queue_s/prefill_s/decode_s/kv_page_s/cost_per_token fields and the
# fleet rollup gained cost-per-token components; rendered by
# tools/goodput_report.py and metrics_to_md.py's "Goodput" table,
# regression-guarded by tools/bench_sentinel.py.
# /13 extended the "preflight" record with the GL-P-COST static
# roofline (graftlint v3): a ``cost`` dict carrying the predicted
# step_ms / mfu_pct / compute_ms / comm_ms / overlap_headroom_ms, the
# per-op-class FLOPs+bytes breakdown (by_class), per-pallas_call
# compute, the collective wire model (collectives) and the named
# ``bottleneck`` under the selected --hw_profile — rendered by
# tools/metrics_to_md.py's "Static cost" table.  No new record kinds.
# /14 added prefix caching + chunked prefill to the serving path: the
# "serve" record gained cached_tokens (prompt tokens mapped from the
# prefix cache instead of recomputed) and prefill_chunks (incremental
# prefill passes this request took); "serve_summary" gained a "prefix"
# dict (hits/misses/hit_tokens/prompt_tokens/hit_rate/
# request_hit_rate/evictions/inserts/cached_pages/flops_saved) and a
# top-level prefill_chunks when either flag is on.  New counters
# serve_prefix_hit_tokens / serve_prefill_flops_saved /
# serve_prefill_chunks and gauge serve_cached_pages.  No new record
# kinds; flag-off runs emit the /13 field set plus the two zero-valued
# serve fields.
# /15 added the train→serve control plane (paddle_tpu/deploy): record
# kind "deploy" — one per DeploymentController rollout attempt
# (checkpoint, uuid, attempt, export_ms/swap_ms/total_ms, outcome
# deployed|rolled_back|export_failed) — and record kind "autoscale" —
# one per SloAutoscaler action (scale_up/scale_down with the
# triggering signals and scale_ms) and per PoolArbiter shift
# (pool_borrow/pool_return with the trainer/serving host split).  New
# counters deploys_succeeded / deploys_rolled_back /
# deploys_export_failed / autoscale_actions{action} /
# pool_shifts{event} / fleet_replicas_added / fleet_replicas_retired /
# fleet_scrape_errors / client_backoffs.
SCHEMA = "paddle_tpu.metrics/15"

# every record kind the schema knows.  The GL-SCHEMA codebase pass
# (paddle_tpu/analysis) cross-checks this against the tree: an emitted
# kind missing here — or an entry here nothing produces — is drift.
RECORD_KINDS = ("step", "bench", "fault", "recovery", "serve",
                "serve_summary", "elastic_event", "preflight", "fleet",
                "profile", "ledger", "deploy", "autoscale")

# histogram bucket upper bounds (ms-oriented default; values above the
# last edge land in the +Inf bucket)
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0, 5000.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        self.name = name
        self.help = help
        self._registry = registry
        self._series: dict[tuple, Any] = {}

    def _lock(self):
        return self._registry._lock

    def labels_of(self) -> list[dict]:
        return [dict(k) for k in self._series]


class Counter(_Metric):
    """Monotonically increasing value per label set."""

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name}: negative inc {value}")
        key = _label_key(labels)
        with self._lock():
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels) -> float:
        return self._series.get(_label_key(labels), 0.0)

    def snapshot(self) -> list[dict]:
        with self._lock():
            return [{**dict(k), "value": v} for k, v in self._series.items()]


class Gauge(_Metric):
    """Last-set value per label set."""

    def set(self, value: float, **labels) -> None:
        with self._lock():
            self._series[_label_key(labels)] = float(value)

    def value(self, **labels) -> float | None:
        return self._series.get(_label_key(labels))

    def snapshot(self) -> list[dict]:
        with self._lock():
            return [{**dict(k), "value": v} for k, v in self._series.items()]


@dataclasses.dataclass
class _Hist:
    count: int = 0
    total: float = 0.0
    min: float = float("inf")
    max: float = float("-inf")
    buckets: list[int] = dataclasses.field(default_factory=list)


class Histogram(_Metric):
    """Fixed-bucket distribution per label set (bucket edges are upper
    bounds; one overflow bucket beyond the last edge)."""

    def __init__(self, name, help, registry, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, registry)
        self.bucket_edges = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock():
            h = self._series.get(key)
            if h is None:
                h = self._series[key] = _Hist(
                    buckets=[0] * (len(self.bucket_edges) + 1))
            h.count += 1
            h.total += value
            h.min = min(h.min, value)
            h.max = max(h.max, value)
            for i, edge in enumerate(self.bucket_edges):
                if value <= edge:
                    h.buckets[i] += 1
                    break
            else:
                h.buckets[-1] += 1

    def _percentile_of(self, h: _Hist, q: float) -> float:
        """Linear-interpolated q-th percentile from the bucket counts.

        Within the bucket containing the target rank, values are assumed
        uniform between the bucket's bounds (first bucket's lower bound =
        observed min; overflow bucket's upper bound = observed max), so
        the estimate is exact at bucket edges and clamped to [min, max]
        — good enough to assert SLOs against (tests) and render (the
        metrics_to_md "Serving latency" table)."""
        rank = (q / 100.0) * h.count
        cum = 0
        lower = h.min
        for i, cnt in enumerate(h.buckets):
            upper = (self.bucket_edges[i] if i < len(self.bucket_edges)
                     else h.max)
            if cnt:
                cum += cnt
                if cum >= rank:
                    lo = max(lower, h.min)
                    hi = min(upper, h.max)
                    frac = (rank - (cum - cnt)) / cnt
                    return float(min(max(lo + (hi - lo) * frac, h.min),
                                     h.max))
            lower = upper
        return float(h.max)

    def percentile(self, q: float, **labels) -> float | None:
        """Estimated q-th percentile (0..100) for a label set, or None
        with no observations — lets tests/SLO checks assert e.g.
        ``hist.percentile(99) < 250``."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock():
            h = self._series.get(_label_key(labels))
            if h is None or not h.count:
                return None
            return self._percentile_of(h, q)

    def summary(self, **labels) -> dict | None:
        with self._lock():
            h = self._series.get(_label_key(labels))
            if h is None:
                return None
            pct = ({f"p{q}": self._percentile_of(h, q)
                    for q in (50, 90, 99)}
                   if h.count else {"p50": 0.0, "p90": 0.0, "p99": 0.0})
            # zero observations: min/max are the ±inf init sentinels —
            # clamp to 0 so an empty histogram's summary stays JSON-safe
            # (Infinity is not JSON) and SLO checks read 0, not -inf
            return {"count": h.count, "sum": h.total,
                    "avg": h.total / h.count if h.count else 0.0,
                    "min": h.min if h.count else 0.0,
                    "max": h.max if h.count else 0.0, **pct,
                    "buckets": dict(zip(
                        [str(e) for e in self.bucket_edges]
                        + ["+Inf"], h.buckets))}

    def snapshot(self) -> list[dict]:
        with self._lock():
            return [{**dict(k), **self.summary(**dict(k))}
                    for k in list(self._series)]


class MetricsRegistry:
    """Named metrics + sink fan-out.

    ``counter/gauge/histogram`` are get-or-create (re-registering the
    same name with a different type is an error).  ``emit`` stamps the
    record with schema/ts/host and writes it to every sink; with no
    sinks it is a no-op, so instrumented code paths can always call it
    (``active`` lets callers skip expensive record assembly entirely).
    """

    def __init__(self, name: str = "default"):
        self.name = name
        self._lock = threading.RLock()
        self._metrics: dict[str, _Metric] = {}
        self._sinks: list = []

    # -- metric construction --------------------------------------------------
    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, self, **kw)
            elif type(m) is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    # -- sinks ----------------------------------------------------------------
    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def clear_sinks(self) -> None:
        with self._lock:
            for s in self._sinks:
                with swallow("sink_close", self):
                    s.close()
            self._sinks = []

    @property
    def sinks(self) -> list:
        return list(self._sinks)

    @property
    def active(self) -> bool:
        return bool(self._sinks)

    # -- the structured stream ------------------------------------------------
    def emit(self, record: dict, kind: str | None = None) -> dict:
        """Stamp + fan a record out to every sink; returns the stamped
        record (emitted or not, so callers can reuse it — e.g. the
        flight recorder keeps records the sinks never saw)."""
        rec = dict(record)
        rec.setdefault("schema", SCHEMA)
        if kind is not None:
            rec.setdefault("kind", kind)
        rec.setdefault("ts", time.time())
        if "host" not in rec:
            rec["host"] = host_index()
        for sink in self._sinks:
            try:
                sink.write(rec)
            except Exception as e:
                # telemetry must never abort training: a full disk or a
                # revoked path drops records, not the run (warn once per
                # sink so a long run doesn't drown in repeats)
                if not getattr(sink, "_write_failed", False):
                    try:
                        sink._write_failed = True
                        from paddle_tpu.core import logger

                        logger.get_logger("paddle_tpu.metrics").warning(
                            "metrics sink %s write failed (%s); further "
                            "records to it may be lost",
                            type(sink).__name__, e)
                    except Exception:
                        pass
        return rec

    def flush(self) -> None:
        for sink in self._sinks:
            with swallow("sink_flush", self):
                sink.flush()

    def snapshot(self) -> dict:
        """{metric name: list of labeled series dicts} — the pull-side
        view of every counter/gauge/histogram."""
        with self._lock:
            return {name: m.snapshot() for name, m in self._metrics.items()}


def host_index() -> int:
    """This process's host/worker index — ``jax.process_index`` whenever
    it can be read WITHOUT forcing backend init (telemetry must be
    importable before ``jax.distributed.initialize``); falls back to
    PADDLE_TPU_TRAINER_ID.  The single implementation step records AND
    flight dumps stamp with, so cross-host comparisons line up.

    Standard TPU pods auto-detect multihost without
    ``jax.distributed.is_initialized()`` ever flipping true, so the real
    gate is "has a backend already been created" — by emit/dump time in
    a train loop it always has, and ``process_index`` is then correct
    and free.  One exception: a LOCAL fleet (``distributed.launch`` on
    a CPU/dev box) runs each rank as its own single-process jax world,
    where ``process_index()`` is a constant 0 on every rank — there the
    launcher's ``PADDLE_TPU_TRAINER_ID`` stamp is the identity, or
    every rank's trace/flight dump would land on ``*-host0`` and
    clobber its peers'."""
    try:
        import jax

        if getattr(jax.distributed, "is_initialized", None) and \
                jax.distributed.is_initialized():
            return jax.process_index()
        from jax._src import xla_bridge

        if xla_bridge._backends:  # initialized already: reading is safe
            if jax.process_count() > 1:
                return jax.process_index()
            # single-process backend: a launcher-stamped fleet identity
            # (local ranks) outranks the backend's constant 0 — fall
            # through to the env read
    except (ImportError, AttributeError, RuntimeError):
        # jax absent/too old, or a backend probe that refuses before
        # init — the env-var fallback below is the answer either way
        pass
    import os

    return int(os.environ.get("PADDLE_TPU_TRAINER_ID", "0") or 0)


# -- the default (process-global) registry ------------------------------------

_default = MetricsRegistry()


def get_default_registry() -> MetricsRegistry:
    return _default


def safe_inc(name: str, help: str = "", amount: float = 1.0,
             registry: MetricsRegistry | None = None, **labels) -> None:
    """Best-effort counter increment for fault/recovery paths: accounting
    must never break the operation it observes (a retry, an injected
    fault, a failing checkpoint write), so every failure is swallowed."""
    try:
        (registry or _default).counter(name, help).inc(amount, **labels)
    except Exception:
        pass


@contextlib.contextmanager
def swallow(scope: str, registry: MetricsRegistry | None = None):
    """Accounting guard for telemetry/observability side work — the
    multi-statement sibling of :func:`safe_inc`: the operation being
    observed (a rebuild, a fault injection, a collective trace) must
    never die of its own bookkeeping.  A failure inside the block is
    logged at debug, counted (``telemetry_errors{scope}``) and
    swallowed.  Use this instead of ad-hoc ``except Exception: pass``
    blocks around accounting — the GL-EXCEPT static-analysis pass
    rejects those."""
    try:
        yield
    except Exception as e:
        try:
            from paddle_tpu.core import logger

            logger.get_logger("paddle_tpu.metrics").debug(
                "telemetry accounting failed in %s: %s: %s", scope,
                type(e).__name__, e)
            (registry or _default).counter(
                "telemetry_errors",
                "accounting failures swallowed by telemetry.swallow").inc(
                1.0, scope=scope)
        except Exception:
            pass  # the guard of last resort stays silent by design


# -- comm accounting (called by parallel/collective.py at trace time) ---------
#
# jax traces a program's Python body ONCE per signature (lower() and the
# jit call share the trace cache), so record_comm fires exactly once per
# compiled program.  Two consumers ride that single firing:
# - a scoped capture (capture_comm): StepTelemetry lowers a program under
#   it to get THAT program's per-execution payload, {"op/axis": bytes} —
#   what step records carry;
# - the global counters: every trace increments them (captured or not),
#   so they accumulate across compiles — a cumulative pull-side metric,
#   NOT a per-step number.
# Caveat for both: a collective inside a lax.scan/fori_loop body is
# traced once but executed once per iteration, so loop-carried comm is
# undercounted by the trip count.

_capture = threading.local()


@contextlib.contextmanager
def capture_comm():
    """Collect record_comm events into a {"op/axis": bytes} dict for the
    duration (typically one jit lowering).  The global counters still
    accumulate — the trace cache guarantees this is the program's only
    trace, so there is no double count.  NOTE: a capture over a program
    whose signature was already traced (e.g. a second lowering of the
    same jit object) stays empty — the cached trace skips the Python
    body entirely."""
    stack = getattr(_capture, "stack", None)
    if stack is None:
        stack = _capture.stack = []
    acc: dict[str, float] = {}
    stack.append(acc)
    try:
        yield acc
    finally:
        stack.pop()


def record_comm(op: str, axis: str, nbytes: int, registry=None) -> None:
    """One collective call site traced: bytes are the per-shard payload of
    one execution of the traced program body."""
    key = f"{op}/{axis}"
    for acc in getattr(_capture, "stack", None) or ():
        acc[key] = acc.get(key, 0.0) + float(nbytes)
    reg = registry or _default
    reg.counter("comm_bytes",
                "payload bytes of traced collectives (cumulative over "
                "traces)").inc(float(nbytes), op=op, axis=axis)
    reg.counter("comm_calls", "traced collective call sites").inc(
        1.0, op=op, axis=axis)


def comm_snapshot(registry=None) -> dict[str, float]:
    """Flatten the cumulative comm counters into {"op/axis": bytes}."""
    reg = registry or _default
    c = reg.get("comm_bytes")
    if c is None:
        return {}
    return {f"{s['op']}/{s['axis']}": s["value"] for s in c.snapshot()}


def census_by_kind(comm: dict[str, float]) -> dict[str, dict]:
    """Roll a {"op/axis": bytes} comm map (a step record's per-program
    payload, or :func:`comm_snapshot`'s cumulative counters) up to
    {kind: {"bytes", "sites", "axes"}} — the collective census.

    Under ZeRO-2 this is the table that PROVES the collective swap: the
    gradient flow's ``all_reduce`` bytes drop to (near) zero, replaced by
    ``reduce_scatter`` + ``all_gather`` whose per-device payloads are 1/n
    of the replicated run's all-reduce.  ``sites`` counts distinct
    op/axis call sites, not per-step executions (a collective in a scan
    body is traced once)."""
    out: dict[str, dict] = {}
    for key, nbytes in (comm or {}).items():
        kind, _, axis = key.partition("/")
        row = out.setdefault(kind, {"bytes": 0.0, "sites": 0, "axes": []})
        row["bytes"] += float(nbytes)
        row["sites"] += 1
        if axis and axis not in row["axes"]:
            row["axes"].append(axis)
    return out
