"""Span tracing — the live timeline behind the introspection server.

The reference dumped ``paddle/utils/Stat.h`` timer aggregates to the log
at pass end; ``core/stat.py`` reproduces those aggregates but, like
them, throws the *timeline* away — by the time an operator asks "what
was the fleet doing at second 43" only averages remain.  This module
keeps the timeline: a :class:`Tracer` records :class:`Span`\\ s (named,
categorized, nested intervals) into a bounded ring, cheap enough to
stay on in production and exactly ``None`` overhead when disabled (the
``--trace_spans`` flag; ``span()`` returns a shared no-op context
manager without allocating, so a disabled run's trajectory and event
stream are bit-identical to an untraced one — asserted in
``tests/test_introspect.py``).

Instrumented phase boundaries (all behind the same flag):

- trainer step loop — ``step`` spans with nested ``feed`` / ``compute``
  / ``fence`` / ``checkpoint`` / ``guard_rescue`` children;
- ``DevicePrefetcher`` producer — ``prefetch`` spans on the worker
  thread (they land in their own lane: spans carry the thread name);
- ``ServingEngine`` — live ``serve_prefill`` / ``serve_decode`` batch
  spans plus a per-request retrospective ``request`` span with
  ``queue`` / ``prefill`` / ``decode`` children reconstructed from the
  request's own timestamps at retire time;
- ``FleetRouter`` — ``failover`` (with nested ``requeue``), ``route``
  and per-replica ``swap`` spans;
- ``ElasticCoordinator`` — an ``elastic`` span with ``drain`` /
  ``gather`` / ``reshard`` / ``rebuild`` children around a live mesh
  rebuild.

Span identity is DETERMINISTIC: ``span_id = rank * 2**32 + seq`` where
``seq`` is the per-tracer allocation counter — two runs of the same
single-threaded program allocate the same ids, and a fleet's merged
timeline (``tools/trace_merge.py``) never collides across ranks.  The
clock is injectable (``Tracer(clock=...)``) so tests drive spans from a
fake clock and assert exact durations.

Export is Chrome-trace-event JSON (``chrome_trace()`` / ``dump()``),
loadable in Perfetto / ``chrome://tracing``: one complete ("ph": "X")
event per span, ``pid`` = rank (the lane), ``tid`` = thread.  The
introspection server's ``/trace`` endpoint drains the ring through the
same exporter, and ``tools/trace_merge.py`` merges per-rank dumps into
one fleet timeline.

:class:`ProfileWindow` brackets a ``--profile_steps A:B`` window of the
train loop with ``jax.profiler`` device tracing, wrapping each step's
dispatch in a ``jax.profiler.TraceAnnotation`` so the host-side step
spans line up with the device timeline in xprof, and emits one
``kind="profile"`` telemetry record (schema /11) carrying the window,
the trace directory and the tracer's per-phase duration summary.
"""

from __future__ import annotations

import collections
import threading
import time

# spans the ring keeps by default; at ~120 bytes/span this is ~1 MB
DEFAULT_RING = 8192

# rank multiplier for deterministic span ids: ids never collide across
# ranks in a merged timeline, and (rank, seq) is recoverable from the id
_RANK_STRIDE = 1 << 32


class Span:
    """One completed named interval."""

    __slots__ = ("name", "cat", "span_id", "parent_id", "rank", "thread",
                 "t_start", "t_end", "args")

    def __init__(self, name, cat, span_id, parent_id, rank, thread,
                 t_start, t_end, args):
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.rank = rank
        self.thread = thread
        self.t_start = t_start      # tracer-clock seconds
        self.t_end = t_end
        self.args = args

    @property
    def dur_ms(self) -> float:
        return (self.t_end - self.t_start) * 1e3

    def to_event(self) -> dict:
        """One Chrome-trace complete event (timestamps in microseconds,
        the trace-event unit)."""
        args = {"id": self.span_id}
        if self.parent_id is not None:
            args["parent"] = self.parent_id
        if self.args:
            args.update(self.args)
        return {
            "name": self.name, "cat": self.cat, "ph": "X",
            "ts": round(self.t_start * 1e6, 3),
            "dur": round((self.t_end - self.t_start) * 1e6, 3),
            "pid": self.rank, "tid": self.thread, "args": args,
        }


class _OpenSpan:
    """Token handed out by :meth:`Tracer.begin`; closed by ``end`` /
    ``cancel`` (or used as a context manager via :meth:`Tracer.span`)."""

    __slots__ = ("tracer", "name", "cat", "span_id", "parent_id",
                 "t_start", "args", "_done")

    def __init__(self, tracer, name, cat, span_id, parent_id, t_start,
                 args):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start = t_start
        self.args = args
        self._done = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.tracer.end(self)
        return False


class _NullSpan:
    """The disabled-tracer fast path: one shared, allocation-free
    context manager.  ``span()`` on a disabled tracer returns this very
    object, so tracing-off call sites cost a method call and an
    attribute read — nothing that could perturb a trajectory."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-aware span recorder over a bounded ring.

    :param enabled: record spans (False = every entry point is a no-op).
    :param rank: the ``pid`` lane of exported events and the high bits
        of every span id; default: the telemetry host index.
    :param clock: seconds-returning monotonic clock (injectable so tests
        drive spans deterministically); default ``time.perf_counter``.
    :param capacity: completed spans kept (oldest dropped first).
    """

    def __init__(self, enabled: bool = False, rank: int | None = None,
                 clock=None, capacity: int = DEFAULT_RING):
        if rank is None:
            from paddle_tpu.telemetry.registry import host_index

            rank = host_index()
        self.rank = int(rank)
        self.clock = clock or time.perf_counter
        self._enabled = bool(enabled)
        self._lock = threading.RLock()
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=max(int(capacity), 1))
        self._seq = 0
        self._stack = threading.local()  # per-thread open-span stack
        self._dropped = 0

    # -- configuration ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: bool | None = None, clock=None,
                  rank: int | None = None) -> "Tracer":
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if clock is not None:
                self.clock = clock
            if rank is not None:
                self.rank = int(rank)
        return self

    # -- recording -------------------------------------------------------------
    def _tstack(self) -> list:
        s = getattr(self._stack, "open", None)
        if s is None:
            s = self._stack.open = []
        return s

    def _next_id(self) -> int:
        with self._lock:
            sid = self.rank * _RANK_STRIDE + self._seq
            self._seq += 1
        return sid

    def begin(self, name: str, cat: str = "phase", **args) -> _OpenSpan | None:
        """Open a span (returns None when disabled).  The span nests
        under this THREAD's innermost open span."""
        if not self._enabled:
            return None
        stack = self._tstack()
        parent = stack[-1].span_id if stack else None
        tok = _OpenSpan(self, name, cat, self._next_id(), parent,
                        self.clock(), args)
        stack.append(tok)
        return tok

    def end(self, tok: _OpenSpan | None, **args) -> Span | None:
        """Close a span opened by :meth:`begin` (None token = no-op, so
        call sites don't re-check the enabled flag)."""
        if tok is None or tok._done:
            return None
        tok._done = True
        t_end = self.clock()
        stack = self._tstack()
        if tok in stack:
            # closing a non-top token truncates the stack above it:
            # anything still open there was abandoned by an exception
            # path, and leaving it would mis-parent the rest of the run
            del stack[stack.index(tok):]
        if args:
            tok.args.update(args)
        span = Span(tok.name, tok.cat, tok.span_id, tok.parent_id,
                    self.rank, threading.current_thread().name,
                    tok.t_start, t_end, tok.args)
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(span)
        return span

    def cancel(self, tok: _OpenSpan | None) -> None:
        """Discard an open span without recording it (e.g. the feed pull
        that turned out to be the end-of-pass sentinel)."""
        if tok is None or tok._done:
            return
        tok._done = True
        stack = self._tstack()
        if tok in stack:
            del stack[stack.index(tok):]

    def span(self, name: str, cat: str = "phase", **args):
        """Context-manager form.  Disabled tracers return one shared
        no-op object — the hot-loop guard the bit-identical-trajectory
        test pins down."""
        if not self._enabled:
            return _NULL_SPAN
        return self.begin(name, cat, **args)

    def add_span(self, name: str, t_start: float, t_end: float,
                 cat: str = "phase", parent_id: int | None = None,
                 **args) -> int | None:
        """Record a RETROSPECTIVE span from explicit clock readings (the
        serving engine reconstructs a request's queue/prefill/decode
        phases from its own timestamps at retire time).  Returns the
        span id (usable as ``parent_id`` for children), or None when
        disabled."""
        if not self._enabled:
            return None
        sid = self._next_id()
        span = Span(name, cat, sid, parent_id, self.rank,
                    threading.current_thread().name,
                    float(t_start), float(t_end), args)
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(span)
        return sid

    # -- reading ---------------------------------------------------------------
    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def seq_watermark(self) -> int:
        """The next seq this tracer will allocate — a stable "spans
        from here on" marker.  Positional ring indices are invalidated
        by a concurrent ``/trace`` drain or a ring wrap; the seq
        embedded in every span id is not."""
        with self._lock:
            return self._seq

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0

    def drain(self) -> list[Span]:
        """Pop every completed span (the ``/trace`` endpoint's read —
        each scrape gets the ring once, so a polling scraper streams
        the timeline instead of re-downloading it)."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    # -- export ----------------------------------------------------------------
    def chrome_trace(self, spans: list[Span] | None = None,
                     drain: bool = False) -> dict:
        """Chrome-trace-event JSON dict (Perfetto / chrome://tracing
        loadable): the spans as complete events plus process/thread
        metadata naming this rank's lane."""
        if spans is None:
            spans = self.drain() if drain else self.spans
        events = [{
            "name": "process_name", "ph": "M", "pid": self.rank, "tid": 0,
            "args": {"name": f"rank {self.rank}"},
        }]
        threads = []
        for s in spans:
            if s.thread not in threads:
                threads.append(s.thread)
                events.append({
                    "name": "thread_name", "ph": "M", "pid": s.rank,
                    "tid": s.thread, "args": {"name": s.thread}})
            events.append(s.to_event())
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"rank": self.rank, "spans": len(spans),
                              "dropped": self.dropped}}

    def dump(self, path: str, drain: bool = False) -> str:
        """Write :meth:`chrome_trace` to ``path`` (parent dirs created)
        — the per-rank file ``tools/trace_merge.py`` consumes."""
        import json
        import os

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(drain=drain), f)
        return path

    def phase_summary(self, spans: list[Span] | None = None) -> dict:
        """{span name: {count, total_ms, p50_ms, p99_ms, max_ms}} over
        the current ring — the "Trace spans" table of
        ``tools/metrics_to_md.py`` and the ``profile`` record's span
        attachment.  Percentiles are exact (computed from the raw
        durations, not histogram buckets)."""
        by_name: dict[str, list[float]] = {}
        for s in (self.spans if spans is None else spans):
            by_name.setdefault(s.name, []).append(s.dur_ms)
        out = {}
        for name, durs in sorted(by_name.items()):
            durs.sort()
            out[name] = {
                "count": len(durs),
                "total_ms": round(sum(durs), 3),
                "p50_ms": round(_pctl(durs, 50.0), 3),
                "p99_ms": round(_pctl(durs, 99.0), 3),
                "max_ms": round(durs[-1], 3),
            }
        return out


def _pctl(sorted_vals: list[float], q: float) -> float:
    """Interpolated percentile over pre-sorted values."""
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    rank = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (rank - lo)


# -- the process-global tracer -------------------------------------------------

_default: Tracer | None = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer every built-in instrumentation point
    uses; created on first use with ``--trace_spans`` /
    ``PADDLE_TPU_TRACE_SPANS`` deciding whether it records."""
    global _default
    with _default_lock:
        if _default is None:
            from paddle_tpu.core import flags

            _default = Tracer(enabled=bool(flags.get("trace_spans")),
                              capacity=int(flags.get("trace_ring_size")))
        return _default


def configure_tracing(enabled: bool | None = None, clock=None,
                      rank: int | None = None) -> Tracer:
    """Flip the global tracer's switches (tests, notebooks).  The
    trainer re-reads the ``trace_spans`` flag at ``train()`` entry via
    this, so a flag set after import still takes effect."""
    return get_tracer().configure(enabled=enabled, clock=clock, rank=rank)


# -- windowed device profiling (--profile_steps A:B) ---------------------------


def parse_profile_steps(spec: str | None) -> tuple[int, int] | None:
    """``"A:B"`` -> (A, B), the half-open dispatch-step window
    [A, B) to capture; None/empty = no profiling.  A bare ``"N"`` means
    one step, [N, N+1)."""
    if not spec:
        return None
    s = str(spec).strip()
    if ":" in s:
        a, b = s.split(":", 1)
        lo, hi = int(a), int(b)
    else:
        lo, hi = int(s), int(s) + 1
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"--profile_steps must be 'A:B' with 0 <= A < B, got {spec!r}")
    return lo, hi


class ProfileWindow:
    """Bracket dispatch steps [start, stop) of a train loop with a
    ``jax.profiler`` trace, so the capture holds exactly the steps the
    operator asked for instead of a whole run's worth of profile data.

    The trainer calls :meth:`maybe_start` before dispatching step ``n``
    and :meth:`maybe_stop` after; :meth:`annotation` wraps the dispatch
    in a ``jax.profiler.TraceAnnotation`` while the window is open, so
    the device timeline carries host step markers that line up with the
    tracer's ``step`` spans.  :meth:`close` stops a window left open by
    a run shorter than B.  One ``kind="profile"`` record (schema /11)
    is emitted when the window closes: the step range, the trace
    directory and the tracer's per-phase duration summary.

    Profiling must never kill training: start/stop failures are logged
    and the window deactivates itself.
    """

    def __init__(self, spec: str | None, trace_dir: str | None = None,
                 registry=None, tracer: Tracer | None = None):
        self.window = parse_profile_steps(spec)
        self.trace_dir = trace_dir
        self.registry = registry
        self.tracer = tracer
        self.active = False
        self.emitted: dict | None = None
        self._t0 = 0.0
        self._span_floor = 0

    def _resolve_dir(self) -> str:
        if self.trace_dir:
            return self.trace_dir
        import os
        import tempfile

        from paddle_tpu.telemetry.registry import host_index

        return os.path.join(tempfile.gettempdir(),
                            f"paddle_tpu_profile_host{host_index()}")

    def maybe_start(self, step: int) -> bool:
        if self.window is None or self.active or step != self.window[0]:
            return False
        import jax

        from paddle_tpu.core import logger as log

        self.trace_dir = self._resolve_dir()
        try:
            jax.profiler.start_trace(self.trace_dir)
        except Exception as e:
            log.warning("--profile_steps: start_trace failed (%s: %s); "
                        "profiling disabled for this run",
                        type(e).__name__, e)
            self.window = None
            return False
        self.active = True
        self._t0 = time.perf_counter()
        if self.tracer is not None:
            # a SEQ watermark, not a ring index: a mid-window /trace
            # drain or ring wrap shifts positions but not span ids
            self._span_floor = self.tracer.seq_watermark()
        return True

    def annotation(self, step: int):
        """A device-trace step marker while the window is open (a no-op
        context manager outside it)."""
        if not self.active:
            return _NULL_SPAN
        import jax

        return jax.profiler.TraceAnnotation(f"train_step_{step}")

    def maybe_stop(self, step: int, fence=None) -> dict | None:
        """Close the window once ``step`` (the NEXT step to dispatch)
        reaches B; returns the emitted profile record.  ``fence`` — an
        array from the window's last step — is blocked on before the
        trace stops, so the capture holds the device work it brackets
        (dispatch is async; values are untouched, only timing)."""
        if not self.active or step < self.window[1]:
            return None
        if fence is not None:
            import jax

            from paddle_tpu.core import logger as log

            try:
                jax.block_until_ready(fence)
            except Exception as e:
                log.debug("--profile_steps: fence before stop_trace "
                          "failed (%s); capture may truncate the last "
                          "step", e)
        return self.close()

    def close(self) -> dict | None:
        if not self.active:
            return None
        import jax

        from paddle_tpu.core import logger as log

        self.active = False
        wall_ms = (time.perf_counter() - self._t0) * 1e3
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("--profile_steps: stop_trace failed (%s: %s); the "
                        "device capture may be incomplete",
                        type(e).__name__, e)
        rec = {
            "start_step": self.window[0], "end_step": self.window[1],
            "steps": self.window[1] - self.window[0],
            "trace_dir": self.trace_dir,
            "wall_ms": round(wall_ms, 3),
        }
        if self.tracer is not None and self.tracer.enabled:
            # summarize only spans recorded DURING the window (seq at
            # or past the start watermark), so the profile record's
            # phase table matches the device capture even when a
            # /trace scrape drained the ring mid-window
            spans = [s for s in self.tracer.spans
                     if s.span_id % _RANK_STRIDE >= self._span_floor]
            rec["spans"] = self.tracer.phase_summary(spans)
        if self.registry is None:
            from paddle_tpu.telemetry.registry import get_default_registry

            self.registry = get_default_registry()
        if self.registry.active:
            rec = self.registry.emit(rec, kind="profile")
        log.info("--profile_steps: captured steps [%d, %d) to %s "
                 "(%.1f ms)", self.window[0], self.window[1],
                 self.trace_dir, wall_ms)
        self.emitted = rec
        return rec
