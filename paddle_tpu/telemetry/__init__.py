"""paddle_tpu.telemetry — the unified metrics/observability layer.

See :mod:`paddle_tpu.metrics` (the user-facing facade) for the overview;
this package holds the implementation:

- ``registry``     — MetricsRegistry + Counter/Gauge/Histogram + comm
  accounting used by the collective wrappers;
- ``sinks``        — JsonlSink / MemorySink / LoggingSink;
- ``step_metrics`` — StepTelemetry, the per-step record builder behind
  ``SGD.train`` and ``trainer/cli.py``;
- ``tracing``      — Span/Tracer phase timeline (Chrome-trace export)
  + the ``--profile_steps`` ProfileWindow;
- ``introspect``   — the per-process ``--status_port`` HTTP server
  (/metrics /healthz /snapshot /trace) + the Prometheus scrape
  helpers the fleet aggregator uses.
"""

from paddle_tpu.telemetry.registry import (  # noqa: F401
    SCHEMA,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    capture_comm,
    census_by_kind,
    comm_snapshot,
    get_default_registry,
    host_index,
    record_comm,
    safe_inc,
    swallow,
)
from paddle_tpu.telemetry.sinks import (  # noqa: F401
    JsonlSink,
    LoggingSink,
    MemorySink,
    json_default,
)
from paddle_tpu.telemetry.step_metrics import (  # noqa: F401
    StepTelemetry,
    tokens_in_feed,
)
from paddle_tpu.telemetry.tracing import (  # noqa: F401
    ProfileWindow,
    Span,
    Tracer,
    configure_tracing,
    get_tracer,
    parse_profile_steps,
)
