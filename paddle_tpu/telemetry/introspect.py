"""Per-process live introspection server — scrape a *running* rank.

Every observability surface before this PR was post-hoc: step records
land in JSONL, the flight ring is dumped only on crash, serve
histograms are scraped from files.  This module makes the same state
inspectable while the process runs, over a dependency-free stdlib HTTP
server (one daemon thread; request handling is thread-per-connection,
and every shared structure it reads — the registry, the tracer ring,
the flight ring — is already lock-guarded):

- ``/metrics``   Prometheus text exposition rendered from the
  :class:`~paddle_tpu.telemetry.registry.MetricsRegistry`: counters and
  gauges with their label sets, histograms as cumulative ``_bucket`` /
  ``_sum`` / ``_count`` series.  Histogram series with zero
  observations are SKIPPED (an empty histogram has no quantiles — a
  NaN row would poison a Prometheus scrape).
- ``/healthz``   JSON liveness: newest heartbeat age/tag from the
  flight ring, per-loop liveness verdicts from registered probes
  (``add_health``: the serve loop, the fleet pump), the elastic
  membership epoch, pid/host/uptime.  Returns 503 when any registered
  probe says dead — a load balancer can act on it.
- ``/snapshot``  JSON: the flight ring (records + heartbeats — the
  crash dump, inspectable BEFORE the crash), the collective census
  (:func:`~paddle_tpu.telemetry.registry.census_by_kind`), and the
  full registry snapshot with interpolated histogram percentiles.
- ``/trace``     drain the span ring as a Chrome trace (see
  :mod:`~paddle_tpu.telemetry.tracing`); ``/trace?keep=1`` peeks
  without draining.

Wiring: ``--status_port N`` (``PADDLE_TPU_STATUS_PORT``) arms the
server in ``SGD.train`` and the serving CLI; ``distributed.launch
--status_port_base N`` stamps ``N + rank`` into each child's
environment (and substitutes ``{status_port}`` in the command line), so
every rank of a fleet serves on its own port.  Port 0 binds an
ephemeral port — :meth:`IntrospectionServer.start` returns the real
one.

The scrape side lives here too: :func:`scrape` (GET a URL),
:func:`parse_prometheus` (text -> {(name, labels): value}) and
:func:`aggregate_prometheus` (sum counters/gauges across replicas) —
what ``FleetRouter.scrape_replicas`` uses to fold per-replica
``/metrics`` into the fleet summary, and what tests use as the
"tiny exposition parser".
"""

from __future__ import annotations

import json
import threading
import time

from paddle_tpu.core import logger as log


# -- Prometheus text exposition ------------------------------------------------


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{_prom_name(str(k))}="{_escape(str(v))}"'
        for k, v in sorted(labels.items()))
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_prometheus(registry) -> str:
    """The registry's pull-side state in Prometheus text exposition
    format (version 0.0.4).  Empty histogram series are skipped — no
    samples beats NaN quantiles on the scraper's side."""
    from paddle_tpu.telemetry.registry import Counter, Gauge, Histogram

    lines: list[str] = []
    snap = registry.snapshot()
    for name in sorted(snap):
        metric = registry.get(name)
        series = snap[name]
        pname = _prom_name(name)
        if isinstance(metric, Counter):
            lines.append(f"# HELP {pname} {metric.help or name}")
            lines.append(f"# TYPE {pname} counter")
            for s in series:
                labels = {k: v for k, v in s.items() if k != "value"}
                lines.append(
                    f"{pname}{_prom_labels(labels)} {_num(s['value'])}")
        elif isinstance(metric, Gauge):
            lines.append(f"# HELP {pname} {metric.help or name}")
            lines.append(f"# TYPE {pname} gauge")
            for s in series:
                labels = {k: v for k, v in s.items() if k != "value"}
                lines.append(
                    f"{pname}{_prom_labels(labels)} {_num(s['value'])}")
        elif isinstance(metric, Histogram):
            live = [s for s in series if s.get("count")]
            if not live:
                continue  # zero observations: no samples, not NaNs
            lines.append(f"# HELP {pname} {metric.help or name}")
            lines.append(f"# TYPE {pname} histogram")
            for s in live:
                labels = {k: v for k, v in s.items()
                          if k not in ("count", "sum", "avg", "min",
                                       "max", "p50", "p90", "p99",
                                       "buckets")}
                cum = 0
                for edge, cnt in s["buckets"].items():
                    cum += cnt
                    le = {"le": edge if edge != "+Inf" else "+Inf"}
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels({**labels, **le})} {cum}")
                lines.append(
                    f"{pname}_sum{_prom_labels(labels)} {_num(s['sum'])}")
                lines.append(
                    f"{pname}_count{_prom_labels(labels)} {s['count']}")
    return "\n".join(lines) + "\n"


def _num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def parse_prometheus(text: str) -> dict[tuple, float]:
    """Text exposition -> {(metric name, sorted label tuple): value} —
    the tiny parser tests and the fleet aggregator share.  Comment and
    blank lines are skipped; a malformed sample line raises (a torn
    scrape must not read as a clean one)."""
    out: dict[tuple, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            body, val = rest.rsplit("}", 1)
            labels = []
            for part in _split_labels(body):
                k, v = part.split("=", 1)
                labels.append((k, v.strip('"')))
            out[(name, tuple(sorted(labels)))] = float(val)
        else:
            name, val = line.rsplit(None, 1)
            out[(name, ())] = float(val)
    return out


def _split_labels(body: str) -> list[str]:
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, cur, quoted = [], "", False
    for ch in body:
        if ch == '"':
            quoted = not quoted
        if ch == "," and not quoted:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        parts.append(cur)
    return parts


def aggregate_prometheus(texts: list[str]) -> dict[tuple, float]:
    """Sum samples across replica scrapes (counters add; gauges add
    too, which is the right fleet semantic for the occupancy gauges —
    fleet_queue_depth, serve_active_slots, serve_free_pages are
    per-replica quantities whose fleet view is the sum)."""
    out: dict[tuple, float] = {}
    for text in texts:
        for key, val in parse_prometheus(text).items():
            out[key] = out.get(key, 0.0) + val
    return out


def scrape(url: str, timeout: float = 5.0) -> str:
    """GET a text endpoint (the fleet aggregator's fetch)."""
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", errors="replace")


# -- the server ----------------------------------------------------------------


class IntrospectionServer:
    """One per process; binds ``host:port`` and serves the four
    endpoints from a daemon thread.

    :param registry: MetricsRegistry (default: the process-global one).
    :param tracer: Tracer for ``/trace`` (default: the global tracer).
    :param flight: FlightRecorder for ``/healthz``/``/snapshot``
        (default: the process-global ring).
    :param port: TCP port; 0 = ephemeral (``start()`` returns the real
        one, exposed as ``.port``).
    """

    def __init__(self, registry=None, tracer=None, flight=None,
                 port: int = 0, host: str = "127.0.0.1"):
        if registry is None:
            from paddle_tpu.telemetry.registry import get_default_registry

            registry = get_default_registry()
        self.registry = registry
        self.tracer = tracer
        self.flight = flight
        self.host = host
        self._requested_port = int(port)
        # _httpd/_port are written by start() (consumer) and read by the
        # serve thread and stop(); every access holds _lock (the
        # GL-THREAD audited contract)
        self._lock = threading.Lock()
        self._httpd = None
        self._port: int | None = None
        self._thread: threading.Thread | None = None
        self._started_at = time.time()
        self._health: dict[str, object] = {}
        self._scrapes = 0

    # -- liveness probes -------------------------------------------------------
    def add_health(self, name: str, probe) -> None:
        """Register a liveness probe (zero-arg callable -> truthy =
        alive) surfaced under ``/healthz`` ``loops``; any dead probe
        turns the endpoint 503.  The trainer registers nothing (its
        liveness IS the heartbeat age); serving registers its loop."""
        with self._lock:
            self._health[str(name)] = probe

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port.
        Idempotent — a second start() returns the live port."""
        from http.server import ThreadingHTTPServer

        handler = _make_handler(self)
        # check-and-create under ONE lock hold: two racing start()s must
        # not both bind (fixed port: EADDRINUSE for the loser; port 0:
        # an orphaned socket whose serve thread never stops)
        with self._lock:
            if self._httpd is not None:
                return self._port
            httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                        handler)
            httpd.daemon_threads = True
            self._httpd = httpd
            self._port = httpd.server_address[1]
            self._started_at = time.time()
        self._thread = threading.Thread(
            target=self._serve, name="paddle-tpu-introspect", daemon=True)
        self._thread.start()
        log.info("introspection server on http://%s:%d (/metrics /healthz "
                 "/snapshot /trace)", self.host, self._port)
        return self._port

    def _serve(self) -> None:
        with self._lock:
            httpd = self._httpd
        if httpd is not None:
            httpd.serve_forever(poll_interval=0.1)

    def stop(self) -> None:
        with self._lock:
            httpd, self._httpd = self._httpd, None
        t, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if t is not None:
            t.join(timeout=5.0)

    @property
    def port(self) -> int | None:
        with self._lock:
            return self._port

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- endpoint payloads (also the in-process API) ---------------------------
    def metrics_text(self) -> str:
        return render_prometheus(self.registry)

    def healthz(self) -> tuple[int, dict]:
        """(http status, payload).  503 when a registered loop probe
        reports dead — heartbeat AGE is reported, not judged (the
        stale threshold is the watchdog's call, not the scraper's)."""
        import os

        with self._lock:
            probes = dict(self._health)
            scrapes = self._scrapes
        loops = {}
        ok = True
        for name, probe in sorted(probes.items()):
            try:
                alive = bool(probe())
            except Exception as e:
                log.warning("introspection health probe %r raised "
                            "(%s: %s); reporting dead", name,
                            type(e).__name__, e)
                alive = False
            loops[name] = alive
            ok = ok and alive
        payload: dict = {
            "ok": ok,
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self._started_at, 3),
            "scrapes": scrapes,
            "loops": loops,
        }
        from paddle_tpu.telemetry.registry import host_index

        payload["host"] = host_index()
        from paddle_tpu.distributed.multihost import rendezvous_epoch

        payload["elastic_epoch"] = rendezvous_epoch()
        # the goodput ledger's closing fraction (telemetry/goodput.py),
        # when one has been taken — absent otherwise, so scrapers can
        # tell "no ledger" from "goodput 0"
        g = self.registry.get("goodput_fraction") \
            if self.registry is not None else None
        if g is not None:
            frac = g.value()
            if frac is not None:
                payload["goodput_fraction"] = round(frac, 6)
        if self.flight is not None:
            beats = self.flight.heartbeats
            if beats:
                hb = beats[-1]
                payload["heartbeat"] = {
                    "age_s": round(time.time() - hb["ts"], 3),
                    "tag": hb.get("tag", ""),
                    **{k: v for k, v in hb.items()
                       if k not in ("ts", "tag")},
                }
        return (200 if ok else 503), payload

    def snapshot(self) -> dict:
        from paddle_tpu.telemetry.registry import (
            census_by_kind,
            comm_snapshot,
        )

        out: dict = {
            "census": census_by_kind(comm_snapshot(self.registry)),
            "metrics": self.registry.snapshot(),
        }
        if self.flight is not None:
            out["flight"] = {"records": self.flight.records,
                             "heartbeats": self.flight.heartbeats}
        return out

    def trace(self, drain: bool = True) -> dict:
        tracer = self.tracer
        if tracer is None:
            from paddle_tpu.telemetry.tracing import get_tracer

            tracer = get_tracer()
        return tracer.chrome_trace(drain=drain)

    def _count_scrape(self) -> None:
        with self._lock:
            self._scrapes += 1


def _make_handler(srv: IntrospectionServer):
    """Build the request-handler class over a closed-over server ref
    (the stdlib handler is instantiated per connection by the HTTP
    server, so state rides the closure, not handler attributes)."""
    from http.server import BaseHTTPRequestHandler

    from paddle_tpu.telemetry.sinks import json_default

    class Handler(BaseHTTPRequestHandler):
        server_version = "paddle-tpu-introspect/1"

        def log_message(self, fmt, *args):  # stderr -> the glog logger
            log.debug("introspect: " + fmt, *args)

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload) -> None:
            self._send(code, json.dumps(
                payload, default=json_default).encode(),
                "application/json")

        def do_GET(self):  # noqa: N802 - stdlib handler contract
            path, _, query = self.path.partition("?")
            srv._count_scrape()
            try:
                if path == "/metrics":
                    self._send(200, srv.metrics_text().encode(),
                               "text/plain; version=0.0.4")
                elif path == "/healthz":
                    code, payload = srv.healthz()
                    self._send_json(code, payload)
                elif path == "/snapshot":
                    self._send_json(200, srv.snapshot())
                elif path == "/trace":
                    keep = "keep=1" in query
                    self._send_json(200, srv.trace(drain=not keep))
                elif path in ("/", ""):
                    self._send_json(200, {
                        "endpoints": ["/metrics", "/healthz", "/snapshot",
                                      "/trace"]})
                else:
                    self._send_json(404, {"error": f"no route {path}"})
            except Exception as e:
                # a scrape must never kill the serving thread pool; the
                # error goes back to the scraper AND the log
                log.warning("introspection handler failed on %s "
                            "(%s: %s)", path, type(e).__name__, e)
                try:
                    self._send_json(
                        500, {"error": f"{type(e).__name__}: {e}"})
                except OSError as e2:
                    log.debug("introspect: error reply failed too (%s)",
                              e2)

    return Handler


def server_from_flags(registry=None, flight=None) -> IntrospectionServer | None:
    """Build-and-start an introspection server when ``--status_port`` /
    ``PADDLE_TPU_STATUS_PORT`` is armed (the one wiring point
    ``SGD.train`` and the serving CLI share); None when the flag is 0.
    Port -1 means "ephemeral" (tests: real scrapes, no port race)."""
    from paddle_tpu.core import flags

    port = int(flags.get("status_port") or 0)
    if port == 0:
        return None
    srv = IntrospectionServer(registry=registry, flight=flight,
                              port=max(port, 0))
    srv.start()
    return srv
