"""Sinks — where emitted metric records go.

Every sink takes dict records from :meth:`MetricsRegistry.emit` and is
safe to fan out to several at once:

- :class:`JsonlSink`   — one JSON object per line, to a path or an open
  file object (``bench.py`` hands it stdout so bench rows and trainer
  step records share one schema);
- :class:`MemorySink`  — list of records, for tests and notebooks;
- :class:`LoggingSink` — compact per-record lines through
  ``paddle_tpu.core.logger`` (the operator's tail -f view).
"""

from __future__ import annotations

import json
import os
import threading


def json_default(o):
    """Numpy scalars/arrays and other non-JSON leaves -> plain Python."""
    import numpy as np

    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if hasattr(o, "item"):
        try:
            return o.item()
        except (TypeError, ValueError):  # non-scalar .item() (size > 1)
            pass
    return str(o)


class JsonlSink:
    """One JSON line per record.  ``target`` is a filesystem path (opened
    lazily, append mode, parent dirs created) or an open file object
    (not closed by :meth:`close` — the caller owns it, e.g. stdout)."""

    def __init__(self, target):
        self._lock = threading.Lock()
        if hasattr(target, "write"):
            self._fh, self._owns, self.path = target, False, None
        else:
            self._fh, self._owns, self.path = None, True, str(target)

    def _handle(self):
        if self._fh is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", buffering=1)
        return self._fh

    def write(self, record: dict) -> None:
        line = json.dumps(record, default=json_default)
        with self._lock:
            fh = self._handle()
            fh.write(line + "\n")

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and self._owns:
                self._fh.close()
                self._fh = None


class MemorySink:
    """Records accumulate in ``.records`` (the test sink)."""

    def __init__(self):
        self.records: list[dict] = []

    def write(self, record: dict) -> None:
        self.records.append(record)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def by_kind(self, kind: str) -> list[dict]:
        return [r for r in self.records if r.get("kind") == kind]


class LoggingSink:
    """Human-oriented one-liners via the glog-style logger."""

    def __init__(self, logger_name: str = "paddle_tpu.metrics"):
        from paddle_tpu.core import logger

        self._log = logger.get_logger(logger_name)

    def write(self, record: dict) -> None:
        kind = record.get("kind", "point")
        body = {k: v for k, v in record.items()
                if k not in ("schema", "ts", "kind")}
        self._log.info("%s %s", kind,
                       json.dumps(body, default=json_default, sort_keys=True))

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass
