"""Per-step train telemetry: one structured record per optimizer step.

``SGD.train`` and ``trainer/cli.py`` hand this class the raw
observables of a step — loss, wall ms, batch size, token count — and it
derives the operator-facing rates (examples/sec, tokens/sec, achieved
MFU% against :func:`paddle_tpu.profiler.device_peak_flops`, HBM GB/s
from XLA cost-analysis byte counts), updates the pull-side aggregates
(step-latency histogram, loss gauge, throughput counters), attaches the
comm-bytes snapshot from the collective wrappers, emits through the
registry sinks, and appends to the flight recorder so the last N steps
survive a crash.

FLOP/byte counts come from ``jitted.lower(...).compile().cost_analysis()``
cached per compile signature (:meth:`cost_for`) — lowering re-traces but
hits the executable cache, so the analysis is paid once per feed-shape
bucket, exactly like compilation itself.
"""

from __future__ import annotations

import time

from paddle_tpu.core import logger as log


class StepTelemetry:
    """Builds/emits step records for one training run.

    :param registry: MetricsRegistry (default: the process-global one).
    :param run: label for this stream ("train", "time", ...).
    :param flight: optional FlightRecorder receiving every record.
    :param cost_cache: optional dict to hold per-signature cost results.
        Pass a dict owned by the jitted step's owner (SGD does) so a
        SECOND run over the same compiled program reuses the first run's
        analysis — the trace cache means re-lowering an already-traced
        program yields an empty comm capture.
    """

    def __init__(self, registry=None, run: str = "train", flight=None,
                 cost_cache: dict | None = None):
        from paddle_tpu.telemetry import registry as reg_mod

        self.registry = registry or reg_mod.get_default_registry()
        self.run = run
        self.flight = flight
        self._cost_cache = cost_cache if cost_cache is not None else {}
        self._peak_flops: float | None = None
        self.global_step = 0
        # schema/5: stamp which kernel path produced this run's records
        # (resolved once — routing is a build-time decision per step fn)
        try:
            from paddle_tpu.ops.pallas import tpp

            self.fused_kernels = bool(tpp.fused_enabled())
        except Exception as e:
            log.debug("fused-kernel routing unknown (%s); stamping "
                      "fused_kernels=False", e)
            self.fused_kernels = False

    # -- hardware / program constants -----------------------------------------
    def peak_flops(self) -> float:
        if self._peak_flops is None:
            try:
                from paddle_tpu import profiler

                self._peak_flops = profiler.device_peak_flops()
            except Exception as e:
                log.debug("device peak FLOPs unavailable (%s); MFU will "
                          "read 0", e)
                self._peak_flops = 0.0
        return self._peak_flops

    def cost_for(self, sig, lower_fn) -> tuple[float, float, dict]:
        """(flops, bytes_accessed, comm_bytes) of one step execution,
        cached by ``sig`` (the feed signature).  ``lower_fn`` must return
        a jax ``Lowered`` (e.g. ``lambda: jitted.lower(*args)``); any
        failure degrades to (0, 0, {}) — a record without MFU beats no
        record.

        The lowering runs under ``capture_comm``, so the collective
        wrappers traced in THIS program report its per-execution payload
        (and the global comm counters are left to the program's own jit
        trace).  Cost analysis is read from the ``Lowered`` when the
        installed jax supports it (unoptimized HLO analysis — no second
        compilation); only as a fallback is ``.compile()`` forced."""
        if sig in self._cost_cache:
            return self._cost_cache[sig]
        from paddle_tpu.telemetry import registry as reg_mod

        flops, nbytes, comm = 0.0, 0.0, {}
        try:
            with reg_mod.capture_comm() as comm:
                lowered = lower_fn()
            cost = None
            try:
                cost = lowered.cost_analysis()
            except Exception as e:  # capability probe: older jax only
                log.debug("Lowered.cost_analysis unsupported (%s); "
                          "forcing compile()", e)
            if not cost:
                cost = lowered.compile().cost_analysis()
            if isinstance(cost, list):  # older jax returns [dict]
                cost = cost[0]
            if cost:
                flops = float(cost.get("flops", 0.0) or 0.0)
                nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
        except Exception as e:
            # documented degrade: a record without MFU beats no record
            log.debug("cost analysis failed for signature (%s); step "
                      "records carry no FLOPs/bytes", e)
        self._cost_cache[sig] = (flops, nbytes, dict(comm))
        return self._cost_cache[sig]

    # -- the per-step record ---------------------------------------------------
    def record_step(self, *, loss: float, step_ms: float,
                    examples: int | None = None, tokens: int | None = None,
                    flops: float = 0.0, bytes_accessed: float = 0.0,
                    pass_id: int | None = None, batch_id: int | None = None,
                    metrics: dict | None = None, step: int | None = None,
                    comm: dict | None = None,
                    input_wait_ms: float | None = None,
                    host_stall_ms: float | None = None,
                    padding_ratio: float | None = None,
                    extra: dict | None = None) -> dict:
        """Assemble, aggregate, emit and flight-record one step record.

        ``comm``: per-execution collective payload of this step's program
        ({"op/axis": bytes}, from :meth:`cost_for`); when None, the
        registry's CUMULATIVE comm counters stand in (clearly weaker —
        they sum over every traced program).

        ``input_wait_ms``: host time the step loop spent blocked waiting
        for this batch's feed (0 when the prefetcher kept up — the
        host-starvation signal).  ``host_stall_ms``: amortized per-step
        device-fence wait (the ``sync_period`` readback backlog divided
        across its window).  Both are schema/2 fields and also land as
        pull-side gauges.

        Returns the stamped record.  Emission is skipped when the
        registry has no sinks; the flight recorder gets the record
        either way (it is the crash dump, not the live stream)."""
        from paddle_tpu.telemetry import registry as reg_mod

        if step is None:
            step = self.global_step
        self.global_step = step + 1
        sec = max(step_ms, 1e-9) / 1e3
        rec: dict = {
            "kind": "step",
            "run": self.run,
            "step": step,
            "loss": float(loss),
            "step_ms": round(float(step_ms), 4),
            "fused_kernels": self.fused_kernels,
        }
        if pass_id is not None:
            rec["pass_id"] = pass_id
        if batch_id is not None:
            rec["batch_id"] = batch_id
        rec["examples_per_sec"] = (
            round(examples / sec, 2) if examples else 0.0)
        if tokens:
            rec["tokens_per_sec"] = round(tokens / sec, 1)
        peak = self.peak_flops()
        rec["mfu_pct"] = (
            round(flops / sec / peak * 100.0, 2) if flops and peak else 0.0)
        if flops:
            rec["flops"] = flops
        if bytes_accessed:
            rec["hbm_gbps"] = round(bytes_accessed / sec / 1e9, 2)
        if input_wait_ms is not None:
            rec["input_wait_ms"] = round(float(input_wait_ms), 4)
        if host_stall_ms is not None:
            rec["host_stall_ms"] = round(float(host_stall_ms), 4)
        if padding_ratio is not None:
            # padded/total timesteps of this step's sequence feeds — the
            # bucketing signal (schema/10; >25% means most-of-a-quarter
            # of the recurrent flops ran on padding)
            rec["padding_ratio"] = round(float(padding_ratio), 4)
        if comm is None:
            comm = reg_mod.comm_snapshot(self.registry)
        if comm:
            rec["comm_bytes"] = comm
        if metrics:
            rec["metrics"] = {k: float(v) for k, v in metrics.items()}
        if extra:
            rec.update(extra)

        # pull-side aggregates ride along for snapshot()/operator scrapes
        r = self.registry
        r.histogram("step_ms", "train step wall ms").observe(
            float(step_ms), run=self.run)
        r.gauge("loss", "last step loss").set(float(loss), run=self.run)
        if examples:
            r.counter("examples", "examples consumed").inc(
                float(examples), run=self.run)
        if tokens:
            r.counter("tokens", "tokens consumed").inc(
                float(tokens), run=self.run)
        r.counter("steps", "optimizer steps taken").inc(1.0, run=self.run)
        if input_wait_ms is not None:
            r.gauge("input_wait_ms",
                    "host ms the step loop waited for input").set(
                float(input_wait_ms), run=self.run)
        if host_stall_ms is not None:
            r.gauge("host_stall_ms",
                    "amortized device-fence ms per step").set(
                float(host_stall_ms), run=self.run)
        if padding_ratio is not None:
            r.gauge("padding_ratio",
                    "padded/total timesteps of the step's feeds").set(
                float(padding_ratio), run=self.run)

        if r.active:
            rec = r.emit(rec)
        else:
            rec.setdefault("ts", time.time())
        if self.flight is not None:
            self.flight.record(rec)
        return rec


def tokens_in_feed(feed: dict) -> int | None:
    """Sum of sequence lengths across SequenceBatch feed slots (None when
    the feed carries no sequences) — the tokens/sec numerator."""
    total, seen = 0, False
    for v in feed.values():
        length = getattr(v, "length", None)
        if length is not None:
            try:
                import numpy as np

                total += int(np.sum(np.asarray(length)))
                seen = True
            except (TypeError, ValueError):  # ragged/exotic length slot
                pass
    return total if seen else None
