"""v1 ``settings()`` (≅ trainer_config_helpers/optimizers.py:28-358):
records the global optimization config; ``get_settings_optimizer()`` turns
it into a paddle_tpu optimizer for the trainer."""

from __future__ import annotations

_SETTINGS: dict = {}


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             learning_rate_decay_a=0.0, learning_rate_decay_b=0.0,
             learning_rate_schedule="constant", model_average=None, **kw):
    _SETTINGS.clear()
    _SETTINGS.update(dict(
        batch_size=batch_size, learning_rate=learning_rate,
        learning_method=learning_method, regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold,
        learning_rate_decay_a=learning_rate_decay_a,
        learning_rate_decay_b=learning_rate_decay_b,
        learning_rate_schedule=learning_rate_schedule,
        model_average=model_average, **kw))


def get_settings() -> dict:
    return dict(_SETTINGS)


def get_settings_optimizer():
    """Build a paddle_tpu optimizer from the last ``settings()`` call."""
    import paddle_tpu.optimizer as opt

    method = _SETTINGS.get("learning_method")
    kwargs = dict(
        learning_rate=_SETTINGS.get("learning_rate", 1e-3),
        regularization=_SETTINGS.get("regularization"),
        gradient_clipping_threshold=_SETTINGS.get(
            "gradient_clipping_threshold"),
        learning_rate_schedule=_SETTINGS.get("learning_rate_schedule",
                                             "constant"),
        learning_rate_decay_a=_SETTINGS.get("learning_rate_decay_a", 0.0),
        learning_rate_decay_b=_SETTINGS.get("learning_rate_decay_b", 0.0),
    )
    table = {
        None: opt.SGD, "sgd": opt.SGD, "momentum": opt.Momentum,
        "adam": opt.Adam, "adamax": opt.Adamax, "adagrad": opt.AdaGrad,
        "adadelta": opt.AdaDelta, "rmsprop": opt.RMSProp,
        "decayed_adagrad": opt.DecayedAdaGrad,
    }
    cls = opt.SGD
    if isinstance(method, str) or method is None:
        cls = table.get(method if method is None else method.lower(), opt.SGD)
    else:
        # v1 passes method OBJECTS (MomentumOptimizer(momentum=...)); map by
        # class name and forward its kwargs (momentum, beta1, rho, ...)
        cname = type(method).__name__.lower()
        # longest key first so 'adamax' wins over its prefix 'adam'
        for key in sorted((k for k in table if k), key=len, reverse=True):
            if cname.startswith(key):
                cls = table[key]
                break
        kwargs.update(getattr(method, "kw", {}))
    return cls(**{k: v for k, v in kwargs.items() if v is not None})


# v1 method-object names accepted by settings(learning_method=...)
class _Method:
    def __init__(self, **kw):
        self.kw = kw


class MomentumOptimizer(_Method):
    pass


class AdamOptimizer(_Method):
    pass


class AdamaxOptimizer(_Method):
    pass


class AdaGradOptimizer(_Method):
    pass


class AdaDeltaOptimizer(_Method):
    pass


class RMSPropOptimizer(_Method):
    pass
