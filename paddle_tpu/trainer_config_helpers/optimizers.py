"""v1 ``settings()`` (≅ trainer_config_helpers/optimizers.py:28-358):
records the global optimization config; ``get_settings_optimizer()`` turns
it into a paddle_tpu optimizer for the trainer."""

from __future__ import annotations

_SETTINGS: dict = {}


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             learning_rate_decay_a=0.0, learning_rate_decay_b=0.0,
             learning_rate_schedule="poly", learning_rate_args="",
             model_average=None, is_async=False, **kw):
    _SETTINGS.clear()
    _SETTINGS.update(dict(
        batch_size=batch_size, learning_rate=learning_rate,
        learning_method=learning_method, regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold,
        learning_rate_decay_a=learning_rate_decay_a,
        learning_rate_decay_b=learning_rate_decay_b,
        learning_rate_schedule=learning_rate_schedule,
        learning_rate_args=learning_rate_args,
        model_average=model_average, is_async=is_async, **kw))


def get_settings() -> dict:
    return dict(_SETTINGS)


def get_settings_optimizer():
    """Build a paddle_tpu optimizer from the last ``settings()`` call."""
    import paddle_tpu.optimizer as opt

    method = _SETTINGS.get("learning_method")
    kwargs = dict(
        learning_rate=_SETTINGS.get("learning_rate", 1e-3),
        regularization=_SETTINGS.get("regularization"),
        gradient_clipping_threshold=_SETTINGS.get(
            "gradient_clipping_threshold"),
        learning_rate_schedule=_SETTINGS.get("learning_rate_schedule",
                                             "constant"),
        learning_rate_decay_a=_SETTINGS.get("learning_rate_decay_a", 0.0),
        learning_rate_decay_b=_SETTINGS.get("learning_rate_decay_b", 0.0),
    )
    ma = _SETTINGS.get("model_average")
    if ma is not None:
        # accept the v1 shim ModelAverage (kw dict) or the optimizer-level
        # dataclass directly, so settings(model_average=...) actually keeps
        # an average (consumed by trainer.test's apply-at-eval)
        if isinstance(ma, opt.ModelAverage):
            kwargs["model_average"] = ma
        else:
            mkw = getattr(ma, "kw", None) or {}
            kwargs["model_average"] = opt.ModelAverage(
                average_window=mkw.get("average_window", 0.0),
                max_average_window=mkw.get("max_average_window") or 10000)
    # single source of truth: the optimizer registry + its aliases
    # (paddle_tpu.optimizer.OPTIMIZERS), so the two surfaces cannot drift
    table = {None: opt.SGD, **opt.OPTIMIZERS,
             **{alias: opt.OPTIMIZERS[target]
                for alias, target in opt.OPTIMIZER_ALIASES.items()}}
    cls = opt.SGD
    if isinstance(method, str) or method is None:
        key = method if method is None else method.lower()
        if key not in table:
            # ≅ ParameterOptimizer::create's CHECK on learning_method — an
            # advertised-surface config must never die in a bare KeyError
            raise ValueError(
                f"settings(learning_method={method!r}) is not a supported "
                f"learning method; supported: {sorted(k for k in table if k)}")
        cls = table[key]
        if cls in (opt.Momentum, opt.SparseMomentum) \
                and _SETTINGS.get("momentum") is not None:
            # settings(learning_method='momentum', momentum=X) — the string
            # path must carry the coefficient too
            kwargs["momentum"] = _SETTINGS["momentum"]
    else:
        # v1 passes method OBJECTS (MomentumOptimizer(momentum=...)); map by
        # class name and forward its kwargs (momentum, beta1, rho, ...)
        cname = type(method).__name__.lower()
        # longest key first so 'adamax' wins over its prefix 'adam'
        for key in sorted((k for k in table if k), key=len, reverse=True):
            if cname.startswith(key):
                cls = table[key]
                break
        mkw = dict(getattr(method, "kw", {}))
        # MomentumOptimizer(momentum, sparse=True) selects the
        # sparse_momentum method (reference optimizers.py:100)
        if mkw.pop("sparse", False) and cls is opt.Momentum:
            cls = opt.SparseMomentum
        kwargs.update(mkw)
    return cls(**{k: v for k, v in kwargs.items() if v is not None})


# v1 method-object names accepted by settings(learning_method=...)
class _Method:
    proto_name = "momentum"
    #: positional parameter names in the reference class's __init__ order
    #: (e.g. MomentumOptimizer(0.9) — optimizers.py:104)
    pos_args: tuple = ()

    def __init__(self, *args, **kw):
        if len(args) > len(self.pos_args):
            raise TypeError(
                f"{type(self).__name__} takes at most "
                f"{len(self.pos_args)} positional arguments "
                f"({', '.join(self.pos_args) or 'none'}), got {len(args)}")
        self.kw = dict(zip(self.pos_args, args))
        self.kw.update(kw)

    def to_setting_kwargs(self) -> dict:
        """OptimizationConfig fields (≅ Optimizer.to_setting_kwargs)."""
        return {"learning_method": self.proto_name}


class MomentumOptimizer(_Method):
    proto_name = "momentum"
    pos_args = ("momentum", "sparse")

    def to_setting_kwargs(self):
        if self.kw.get("sparse"):
            return {"learning_method": "sparse_momentum"}
        return {"learning_method": "momentum"}


class AdamOptimizer(_Method):
    proto_name = "adam"
    pos_args = ("beta1", "beta2", "epsilon")

    def to_setting_kwargs(self):
        return {
            "learning_method": "adam",
            "adam_beta1": self.kw.get("beta1", 0.9),
            "adam_beta2": self.kw.get("beta2", 0.999),
            "adam_epsilon": self.kw.get("epsilon", 1e-8),
        }


class AdamaxOptimizer(_Method):
    proto_name = "adamax"
    pos_args = ("beta1", "beta2")

    def to_setting_kwargs(self):
        return {
            "learning_method": "adamax",
            "adam_beta1": self.kw.get("beta1", 0.9),
            "adam_beta2": self.kw.get("beta2", 0.999),
        }


class AdaGradOptimizer(_Method):
    proto_name = "adagrad"


class DecayedAdaGradOptimizer(_Method):
    proto_name = "decayed_adagrad"
    pos_args = ("rho", "epsilon")

    def to_setting_kwargs(self):
        return {
            "learning_method": "decayed_adagrad",
            "ada_rou": self.kw.get("rho", 0.95),
            "ada_epsilon": self.kw.get("epsilon", 1e-6),
        }


class AdaDeltaOptimizer(_Method):
    proto_name = "adadelta"
    pos_args = ("rho", "epsilon")

    def to_setting_kwargs(self):
        return {
            "learning_method": "adadelta",
            "ada_rou": self.kw.get("rho", 0.95),
            "ada_epsilon": self.kw.get("epsilon", 1e-6),
        }


class RMSPropOptimizer(_Method):
    proto_name = "rmsprop"
    pos_args = ("rho", "epsilon")

    def to_setting_kwargs(self):
        return {
            "learning_method": "rmsprop",
            "ada_rou": self.kw.get("rho", 0.95),
            "ada_epsilon": self.kw.get("epsilon", 1e-6),
        }


class BaseRegularization:
    def to_setting_kwargs(self):
        return {}


class L1Regularization(BaseRegularization):
    def __init__(self, rate):
        self.rate = rate

    def to_setting_kwargs(self):
        return {"l1weight": self.rate}


class L2Regularization(BaseRegularization):
    def __init__(self, rate):
        self.rate = rate

    def to_setting_kwargs(self):
        return {"l2weight": self.rate}


class ModelAverage:
    def __init__(self, average_window, max_average_window=None,
                 do_average_in_cpu=False):
        self.kw = {
            "average_window": average_window,
            "max_average_window": max_average_window,
            "do_average_in_cpu": do_average_in_cpu,
        }

    def to_setting_kwargs(self):
        return dict(self.kw)


# ≅ config_parser DEFAULT_SETTING (config_parser.py:4046): update_g_config
# copies every non-None entry into OptimizationConfig
DEFAULT_SETTING = dict(
    batch_size=None,
    mini_batch_size=None,
    algorithm="async_sgd",
    async_lagged_grad_discard_ratio=1.5,
    learning_method="momentum",
    gradient_clipping_threshold=None,
    num_batches_per_send_parameter=None,
    num_batches_per_get_parameter=None,
    center_parameter_update_method=None,
    learning_rate=1.0,
    learning_rate_decay_a=0.0,
    learning_rate_decay_b=0.0,
    learning_rate_schedule="poly",
    learning_rate_args="",
    l1weight=0.1,
    l2weight=0.0,
    l2weight_zero_iter=0,
    c1=0.0001,
    backoff=0.5,
    owlqn_steps=10,
    max_backoff=5,
    average_window=0,
    do_average_in_cpu=False,
    max_average_window=None,
    ada_epsilon=1e-6,
    ada_rou=0.95,
    delta_add_rate=1.0,
    shrink_parameter_value=0,
    adam_beta1=0.9,
    adam_beta2=0.999,
    adam_epsilon=1e-8,
)


def proto_settings() -> dict:
    """The OptimizationConfig field dict the reference's settings() +
    update_g_config produce (optimizers.py:358-441)."""
    s = dict(DEFAULT_SETTING)
    cfg = _SETTINGS
    if not cfg:
        return s
    method = cfg.get("learning_method")
    if method is None or isinstance(method, str):
        mobj = _Method()
        mobj.proto_name = method or "momentum"
        if method in (None, "momentum"):
            mobj = MomentumOptimizer()
    else:
        mobj = method
    s["algorithm"] = "async_sgd" if cfg.get("is_async") else "sgd"
    for key in ("batch_size", "learning_rate", "learning_rate_decay_a",
                "learning_rate_decay_b", "learning_rate_schedule",
                "learning_rate_args", "gradient_clipping_threshold"):
        if key in cfg and cfg[key] is not None:
            s[key] = cfg[key]
    s.update(mobj.to_setting_kwargs())
    reg = cfg.get("regularization")
    regs = reg if isinstance(reg, (list, tuple)) else ([reg] if reg else [])
    for r in regs:
        if hasattr(r, "to_setting_kwargs"):
            s.update(r.to_setting_kwargs())
    ma = cfg.get("model_average")
    if ma is not None and hasattr(ma, "to_setting_kwargs"):
        s.update(ma.to_setting_kwargs())
    return s
