"""v1 declarative evaluator surface — the 15 ``*_evaluator`` wrappers
(≅ ``python/paddle/trainer_config_helpers/evaluators.py:161-774``) usable
inside unmodified reference config files.

Each call records an :class:`EvaluatorSpec` (picked up by proto emission
into ``ModelConfig.evaluators`` and by the trainer loops for execution)
and returns it.  Auto-naming follows the reference's ``wrap_name_default``
pattern (``__maxid_printer_evaluator_0__``).
"""

from __future__ import annotations

from paddle_tpu.evaluator.declare import EvaluatorSpec, declare
from paddle_tpu.layers import base as layer_base
from paddle_tpu.layers.base import LayerOutput

__all__ = [
    "evaluator_base", "classification_error_evaluator", "auc_evaluator",
    "pnpair_evaluator", "precision_recall_evaluator", "ctc_error_evaluator",
    "chunk_evaluator", "sum_evaluator", "column_sum_evaluator",
    "value_printer_evaluator", "gradient_printer_evaluator",
    "maxid_printer_evaluator", "maxframe_printer_evaluator",
    "seqtext_printer_evaluator", "classification_error_printer_evaluator",
    "detection_map_evaluator",
]


def _name(name, default_func):
    return name or layer_base.gen_name(default_func)


def _names(inputs) -> list[str]:
    out = []
    for i in (inputs if isinstance(inputs, (list, tuple)) else [inputs]):
        out.append(i.name if isinstance(i, LayerOutput) else str(i))
    return out


def evaluator_base(input, type, label=None, weight=None, name=None, **fields):
    """≅ evaluators.py:62 evaluator_base: normalize inputs, record spec."""
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    if label is not None:
        inputs.append(label)
    if weight is not None:
        inputs.append(weight)
    fields = {k: v for k, v in fields.items() if v is not None}
    return declare(EvaluatorSpec(
        name=name, type=type, input_layers=_names(inputs), fields=fields))


def classification_error_evaluator(input, label, name=None, weight=None,
                                   top_k=None, threshold=None):
    """≅ evaluators.py:211 (ClassificationErrorEvaluator)."""
    return evaluator_base(
        input=input, type="classification_error", label=label, weight=weight,
        name=_name(name, "classification_error_evaluator"),
        classification_threshold=threshold, top_k=top_k)


def auc_evaluator(input, label, name=None, weight=None):
    """≅ evaluators.py:263 (AucEvaluator)."""
    return evaluator_base(input=input, type="last-column-auc", label=label,
                          weight=weight, name=_name(name, "auc_evaluator"))


def pnpair_evaluator(input, label, info=None, weight=None, name=None,
                     query_id=None):
    """≅ evaluators.py:295 (PnpairEvaluator).

    Input order matches the reference's ``evalImp``
    (Evaluator.cpp:880-887): [score, label, info, weight?].  ``query_id``
    is accepted as an alias for ``info``.
    """
    if info is None:
        info = query_id
    if info is None:
        raise TypeError("pnpair_evaluator requires an info (query id) layer")
    if isinstance(input, (list, tuple)):
        if len(input) != 1:
            # the runtime (and the reference's evalImp, which reads
            # arguments[0..3] positionally) require exactly one score input
            raise ValueError("pnpair_evaluator takes a single score input")
        input = input[0]
    inputs = [input, label, info]
    return evaluator_base(input=inputs, type="pnpair", weight=weight,
                          name=_name(name, "pnpair_evaluator"))


def precision_recall_evaluator(input, label, positive_label=None, weight=None,
                               name=None):
    """≅ evaluators.py:340 (PrecisionRecallEvaluator)."""
    return evaluator_base(
        input=input, type="precision_recall", label=label, weight=weight,
        name=_name(name, "precision_recall_evaluator"),
        positive_label=positive_label)


def ctc_error_evaluator(input, label, name=None):
    """≅ evaluators.py:385 (CTCErrorEvaluator)."""
    return evaluator_base(input=input, type="ctc_edit_distance", label=label,
                          name=_name(name, "ctc_error_evaluator"))


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None):
    """≅ evaluators.py:412 (ChunkEvaluator)."""
    return evaluator_base(
        input=input, type="chunk", label=label,
        name=_name(name, "chunk_evaluator"), chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types)


def sum_evaluator(input, name=None, weight=None):
    """≅ evaluators.py:519 (SumEvaluator)."""
    return evaluator_base(input=input, type="sum", weight=weight,
                          name=_name(name, "sum_evaluator"))


def column_sum_evaluator(input, name=None, weight=None):
    """≅ evaluators.py:545 (ColumnSumEvaluator)."""
    return evaluator_base(input=input, type="last-column-sum", weight=weight,
                          name=_name(name, "column_sum_evaluator"))


def detection_map_evaluator(input, label, overlap_threshold=0.5,
                            background_id=0, evaluate_difficult=False,
                            ap_type="11point", name=None):
    """≅ evaluators.py:161 (DetectionMAPEvaluator)."""
    return evaluator_base(
        input=input, type="detection_map", label=label,
        name=_name(name, "detection_map_evaluator"),
        overlap_threshold=overlap_threshold, background_id=background_id,
        evaluate_difficult=evaluate_difficult, ap_type=ap_type)


# ---- printer family (Evaluator.cpp:1018-1357) -------------------------------

def value_printer_evaluator(input, name=None):
    """≅ evaluators.py:576 (ValuePrinter: print input values per batch)."""
    return evaluator_base(input=input, type="value_printer",
                          name=_name(name, "value_printer_evaluator"))


def gradient_printer_evaluator(input, name=None):
    """≅ evaluators.py:599 (GradientPrinter: print d(cost)/d(input))."""
    return evaluator_base(input=input, type="gradient_printer",
                          name=_name(name, "gradient_printer_evaluator"))


def maxid_printer_evaluator(input, num_results=None, name=None):
    """≅ evaluators.py:622 (MaxIdPrinter: top-k ids per sample)."""
    return evaluator_base(input=input, type="max_id_printer",
                          name=_name(name, "maxid_printer_evaluator"),
                          num_results=num_results)


def maxframe_printer_evaluator(input, num_frames=None, name=None):
    """≅ evaluators.py:651 (MaxFramePrinter: frames with max value)."""
    return evaluator_base(input=input, type="max_frame_printer",
                          name=_name(name, "maxframe_printer_evaluator"),
                          num_results=num_frames)


def seqtext_printer_evaluator(input, result_file, id_input=None,
                              dict_file=None, delimited=None, name=None):
    """≅ evaluators.py:684 (SequenceTextPrinter: write generated sequences
    to ``result_file``, id-prefixed, tokens via ``dict_file``)."""
    assert isinstance(result_file, str)
    inputs = [input] if id_input is None else [id_input, input]
    return evaluator_base(
        input=inputs, type="seq_text_printer",
        name=_name(name, "seqtext_printer_evaluator"),
        dict_file=dict_file, result_file=result_file, delimited=delimited)


def classification_error_printer_evaluator(input, label, threshold=0.5,
                                           name=None):
    """≅ evaluators.py:774 (ClassificationErrorPrinter)."""
    return evaluator_base(
        input=input, type="classification_error_printer", label=label,
        name=_name(name, "classification_error_printer_evaluator"),
        classification_threshold=threshold)
