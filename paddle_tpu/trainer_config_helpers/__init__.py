"""v1 ``trainer_config_helpers`` compatibility surface.

The reference's v1 declarative API (``python/paddle/trainer_config_helpers``)
is the oldest user contract: config .py files calling ``*_layer`` functions
plus ``settings()``.  This shim maps those names onto the v2-style layer
API (paddle_tpu.layers.api) so 2017-era config files import-and-build
against the TPU runtime: ``fc_layer`` == ``layer.fc`` etc.
"""

from __future__ import annotations

from paddle_tpu.layers import api as _api
from paddle_tpu.layers import extras as _extras
from paddle_tpu.layers import more as _more
from paddle_tpu.layers.activation import *  # noqa: F401,F403 (…Activation)
from paddle_tpu.layers.attr import (  # noqa: F401
    ExtraAttr,
    ExtraLayerAttribute,
    ParamAttr,
    ParameterAttribute,
)
from paddle_tpu.layers.networks import *  # noqa: F401,F403
from paddle_tpu.layers.pooling import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers import optimizers  # noqa: F401
from paddle_tpu.trainer_config_helpers.optimizers import (  # noqa: F401
    AdaDeltaOptimizer,
    AdaGradOptimizer,
    AdamaxOptimizer,
    AdamOptimizer,
    MomentumOptimizer,
    RMSPropOptimizer,
    settings,
)


def _export_v1_names():
    g = globals()
    for mod in (_api, _extras, _more):
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            g.setdefault(name, fn)
            # v1 naming: every layer helper also exists as <name>_layer
            if not name.endswith("_layer"):
                g.setdefault(name + "_layer", fn)


_export_v1_names()
