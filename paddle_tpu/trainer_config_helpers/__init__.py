"""v1 ``trainer_config_helpers`` compatibility surface.

The reference's v1 declarative API (``python/paddle/trainer_config_helpers``)
is the oldest user contract: config .py files calling ``*_layer`` functions
plus ``settings()``.  This shim maps those names onto the v2-style layer
API (paddle_tpu.layers.api) so 2017-era config files import-and-build
against the TPU runtime: ``fc_layer`` == ``layer.fc`` etc.
"""

from __future__ import annotations

from paddle_tpu.layers import api as _api
from paddle_tpu.layers import extras as _extras
from paddle_tpu.layers import more as _more
from paddle_tpu.layers.activation import *  # noqa: F401,F403 (…Activation)
from paddle_tpu.layers.attr import (  # noqa: F401
    ExtraAttr,
    ExtraLayerAttribute,
    ParamAttr,
    ParameterAttribute,
)
from paddle_tpu.layers.networks import *  # noqa: F401,F403
from paddle_tpu.layers.pooling import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers import optimizers  # noqa: F401
from paddle_tpu.trainer_config_helpers.optimizers import (  # noqa: F401
    AdaDeltaOptimizer,
    AdaGradOptimizer,
    AdamaxOptimizer,
    AdamOptimizer,
    MomentumOptimizer,
    RMSPropOptimizer,
    settings,
)


def _export_v1_names():
    g = globals()
    for mod in (_api, _extras, _more):
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            g.setdefault(name, fn)
            # v1 naming: every layer helper also exists as <name>_layer
            if not name.endswith("_layer"):
                g.setdefault(name + "_layer", fn)


_export_v1_names()


_CONFIG_ARGS: dict = {}


def set_config_args(args: dict) -> None:
    """Inject CLI key=values for configs to read (≅ --config_args)."""
    _CONFIG_ARGS.clear()
    _CONFIG_ARGS.update(args)


def get_config_arg(name: str, type_=str, default=None):
    """≅ get_config_arg (config_parser): read a CLI-provided config knob
    (see v1_api_demo/mnist/light_mnist.py:17)."""
    if name not in _CONFIG_ARGS:
        return default
    v = _CONFIG_ARGS[name]
    if type_ is bool and isinstance(v, str):
        # bool("0") is True; CLI strings need real parsing
        return v.strip().lower() not in ("", "0", "false", "no", "off")
    return type_(v)
