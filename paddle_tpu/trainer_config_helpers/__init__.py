"""v1 ``trainer_config_helpers`` compatibility surface.

The reference's v1 declarative API (``python/paddle/trainer_config_helpers``)
is the oldest user contract: config .py files calling ``*_layer`` functions
plus ``settings()``.  This shim maps those names onto the v2-style layer
API (paddle_tpu.layers.api) so 2017-era config files import-and-build
against the TPU runtime: ``fc_layer`` == ``layer.fc`` etc.
"""

from __future__ import annotations

from paddle_tpu.layers import api as _api
from paddle_tpu.layers import detection as _detection
from paddle_tpu.layers import extras as _extras
from paddle_tpu.layers import mixed as _mixed
from paddle_tpu.layers import more as _more
from paddle_tpu.layers import recurrent_group as _rg
from paddle_tpu.layers.activation import *  # noqa: F401,F403 (…Activation)
from paddle_tpu.layers.attr import (  # noqa: F401
    ExtraAttr,
    ExtraLayerAttribute,
    ParamAttr,
    ParameterAttribute,
)
from paddle_tpu.layers.networks import *  # noqa: F401,F403
from paddle_tpu.layers.pooling import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers import optimizers  # noqa: F401
from paddle_tpu.trainer_config_helpers.evaluators import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.optimizers import (  # noqa: F401
    AdaDeltaOptimizer,
    AdaGradOptimizer,
    AdamaxOptimizer,
    AdamOptimizer,
    BaseRegularization,
    L1Regularization,
    L2Regularization,
    ModelAverage,
    MomentumOptimizer,
    RMSPropOptimizer,
    settings,
)


def _export_v1_names():
    g = globals()
    mods = (_api, _extras, _more, _mixed, _detection, _rg)
    real = set()
    # pass 1: real names win (a hand-written foo_layer wrapper must not be
    # shadowed by the automatic alias for foo)
    for mod in mods:
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            g.setdefault(name, fn)
            real.add(name)
    # pass 2: v1 naming — every layer helper also exists as <name>_layer
    for mod in mods:
        for name in dir(mod):
            if name.startswith("_") or name.endswith("_layer"):
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            alias = name + "_layer"
            if alias not in real:
                g.setdefault(alias, fn)


_export_v1_names()


class AggregateLevel:
    """≅ layers.py:280 — pooling aggregation level."""

    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    """≅ layers.py:1768 — expansion source level."""

    FROM_NO_SEQUENCE = "non-seq"
    FROM_SEQUENCE = "seq"
    FROM_TIMESTEP = FROM_NO_SEQUENCE


def data_layer(name, size=None, depth=None, height=None, width=None,
               layer_attr=None, type=None):
    """v1 data_layer (layers.py:919): size-only declaration; the input TYPE
    comes from the data provider at runtime, so a dense vector is assumed
    until a feeder binds richer types.  Accepts the v2 ``type=`` form too
    (the alias is exported under both APIs)."""
    from paddle_tpu.layers import data_type as _dt

    if type is not None:
        return _api.data(name=name, type=type, height=height or 0,
                         width=width or 0)
    node = _api.data(
        name=name,
        type=_dt.dense_vector(size),
        height=height or 0,
        width=width or 0,
    )
    if height and width:
        node.attrs["explicit_hw"] = True
        node.depth = depth or 1
        if depth is not None:
            node.attrs["explicit_depth"] = True
    return node


from paddle_tpu.config.parse_state import (  # noqa: E402,F401
    HasInputsSet,
    Inputs,
    MultiData,
    Outputs,
    ProtoData,
    PyData,
    SimpleData,
    TestData,
    TrainData,
    define_py_data_sources2,
    inputs,
    outputs,
)
from paddle_tpu.trainer_config_helpers import layer_math  # noqa: E402,F401

_CONFIG_ARGS: dict = {}


def set_config_args(args: dict) -> None:
    """Inject CLI key=values for configs to read (≅ --config_args)."""
    _CONFIG_ARGS.clear()
    _CONFIG_ARGS.update(args)


def get_config_arg(name: str, type_=str, default=None):
    """≅ get_config_arg (config_parser): read a CLI-provided config knob
    (see v1_api_demo/mnist/light_mnist.py:17)."""
    if name not in _CONFIG_ARGS:
        return default
    v = _CONFIG_ARGS[name]
    if type_ is bool and isinstance(v, str):
        # bool("0") is True; CLI strings need real parsing
        return v.strip().lower() not in ("", "0", "false", "no", "off")
    return type_(v)
