"""``layer_math`` — arithmetic sugar over layers.

≅ ``trainer_config_helpers/layer_math.py``: unary math ops are mixed layers
with an identity projection and the matching activation; +/-/* overloads on
LayerOutput build slope_intercept / scaling / repeat combinations.
"""

from __future__ import annotations

from paddle_tpu.layers import activation as act
from paddle_tpu.layers import api as _api
from paddle_tpu.layers.base import LayerOutput, gen_name
from paddle_tpu.layers.extras import repeat as repeat_layer
from paddle_tpu.layers.mixed import identity_projection, mixed_layer

__all__ = []


def _register_unary(op_name: str, activation):
    def op(input, name=None):
        return mixed_layer(
            input=[identity_projection(input=input)],
            name=name or gen_name(op_name),
            act=activation,
        )

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


_register_unary("exp", act.ExpActivation())
_register_unary("log", act.LogActivation())
_register_unary("abs", act.AbsActivation())
_register_unary("sigmoid", act.SigmoidActivation())
_register_unary("tanh", act.TanhActivation())
_register_unary("square", act.SquareActivation())
_register_unary("relu", act.ReluActivation())
_register_unary("sqrt", act.SqrtActivation())
_register_unary("reciprocal", act.ReciprocalActivation())


def _is_number(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def add(layeroutput, other):
    if _is_number(other):
        return _api.slope_intercept(input=layeroutput, intercept=other)
    assert isinstance(other, LayerOutput), "can only add LayerOutput or number"
    if layeroutput.size == other.size:
        return mixed_layer(input=[
            identity_projection(input=layeroutput),
            identity_projection(input=other),
        ])
    assert other.size == 1 or layeroutput.size == 1, (
        "sizes must match or one side must be size 1")
    if layeroutput.size == 1:
        layeroutput, other = other, layeroutput
    other = repeat_layer(other, layeroutput.size)
    return mixed_layer(input=[
        identity_projection(input=layeroutput),
        identity_projection(input=other),
    ])


def sub(layeroutput, other):
    if _is_number(other):
        # bug-for-bug with the reference (layer_math.py sub: intercept=other,
        # NOT negated) — existing configs depend on this exact graph
        return _api.slope_intercept(input=layeroutput, intercept=other)
    assert isinstance(other, LayerOutput)
    neg = _api.slope_intercept(input=other, slope=-1.0)
    return add(layeroutput, neg)


def rsub(layeroutput, other):
    neg = _api.slope_intercept(input=layeroutput, slope=-1.0)
    return add(neg, other)


def mul(layeroutput, other):
    if _is_number(other):
        return _api.slope_intercept(input=layeroutput, slope=other)
    assert isinstance(other, LayerOutput)
    if layeroutput.size == 1:
        return _api.scaling(input=other, weight=layeroutput)
    if other.size == 1:
        return _api.scaling(input=layeroutput, weight=other)
    raise AssertionError(
        "one operand of '*' must be a number or a size-1 LayerOutput")


LayerOutput.__add__ = add
LayerOutput.__radd__ = add
LayerOutput.__sub__ = sub
LayerOutput.__rsub__ = rsub
LayerOutput.__mul__ = mul
LayerOutput.__rmul__ = mul
