"""Structured telemetry for paddle_tpu — the user-facing facade.

One metrics layer unifies the scattered primitives (``core/stat.py``
scope timers, ``profiler.py`` MFU accounting, ``trainer/event.py``
callbacks, the bench JSONL): a :class:`MetricsRegistry` of counters /
gauges / histograms with labeled series and pluggable sinks, plus a
structured record stream — one record per train step from ``SGD.train``
and ``trainer/cli.py`` with {step, loss, step_ms, examples_per_sec,
tokens_per_sec, mfu_pct, hbm_gbps, comm_bytes} — that ``bench.py``
shares, so trainer and bench records have one schema and one toolchain
(``tools/metrics_to_md.py``, ``tools/bench_to_md.py``).

Typical operator setup::

    from paddle_tpu import metrics
    metrics.configure(jsonl="/var/log/train.metrics.jsonl")   # or:
    #   PADDLE_TPU_METRICS_JSONL=... / --metrics_jsonl=... (trainer CLI)
    trainer.train(...)          # one JSONL record per step, tail -f-able

Tests and notebooks::

    sink = metrics.MemorySink()
    metrics.get_registry().add_sink(sink)
    ...
    sink.records                # list of step dicts

Related: the multihost flight recorder
(:mod:`paddle_tpu.distributed.multihost`) keeps the last N step records
+ heartbeats in a ring buffer and dumps them on exception/SIGTERM.
"""

from __future__ import annotations

from paddle_tpu.telemetry import (  # noqa: F401
    SCHEMA,
    Counter,
    Gauge,
    Histogram,
    JsonlSink,
    LoggingSink,
    MemorySink,
    MetricsRegistry,
    StepTelemetry,
    capture_comm,
    comm_snapshot,
    get_default_registry,
    host_index,
    json_default,
    record_comm,
    tokens_in_feed,
)


def get_registry() -> MetricsRegistry:
    """The process-global registry every built-in instrument uses."""
    return get_default_registry()


def configure(jsonl: str | None = None, memory: bool = False,
              log: bool = False, registry: MetricsRegistry | None = None):
    """Attach sinks to the (default) registry; returns the sinks added.

    ``jsonl``: path for a JSONL file sink; ``memory``: add a MemorySink
    (returned for inspection); ``log``: mirror records through the
    glog-style logger.

    Idempotent for ``jsonl`` (same path) and ``log``: re-running the
    setup (notebook cell, a library configuring after user code) must
    not attach duplicate sinks that double every record.  ``memory``
    always adds a fresh sink — the caller wants that exact object."""
    reg = registry or get_default_registry()
    added = []
    if jsonl and not any(getattr(s, "path", None) == jsonl
                         for s in reg.sinks):
        added.append(JsonlSink(jsonl))
    if memory:
        added.append(MemorySink())
    if log and not any(isinstance(s, LoggingSink) for s in reg.sinks):
        added.append(LoggingSink())
    for s in added:
        reg.add_sink(s)
    return added


def configure_from_flags(registry: MetricsRegistry | None = None):
    """Honor the central flag registry (``--metrics_jsonl=PATH`` /
    ``PADDLE_TPU_METRICS_JSONL``): idempotently attach a JSONL sink.
    Called by ``SGD.train`` and the trainer CLI on entry."""
    from paddle_tpu.core import flags

    path = flags.get("metrics_jsonl")
    if not path:
        return None
    reg = registry or get_default_registry()
    for s in reg.sinks:
        if getattr(s, "path", None) == path:
            return s
    sink = JsonlSink(path)
    reg.add_sink(sink)
    return sink
