"""Drop-in ``paddle`` module aliasing.

Reference config files and demos start with ``from
paddle.trainer_config_helpers import *`` or ``import paddle.v2 as paddle``.
``install_paddle_alias()`` registers this package under the ``paddle`` name
in ``sys.modules`` so those files run unmodified against the TPU runtime
(the compatibility claim of BASELINE.json's "keep the Python v2 API").

The alias is only installed when no real ``paddle`` is importable, and is
idempotent.
"""

from __future__ import annotations

import importlib
import sys

_ALIASES = {
    "paddle": "paddle_tpu",
    "paddle.trainer_config_helpers": "paddle_tpu.trainer_config_helpers",
    "paddle.trainer_config_helpers.optimizers": "paddle_tpu.trainer_config_helpers.optimizers",
    "paddle.trainer": "paddle_tpu.trainer",
    "paddle.trainer.config_parser": "paddle_tpu.trainer.config_parser",
    "paddle.trainer.PyDataProvider2": "paddle_tpu.reader.py_data_provider2",
    "paddle.proto": "paddle_tpu.proto",
    "paddle.v2": "paddle_tpu.v2",
}


def install_paddle_alias(force: bool = False) -> bool:
    if "paddle" in sys.modules and not force:
        already_ours = getattr(sys.modules["paddle"], "__name__", "").startswith(
            "paddle_tpu"
        )
        if already_ours:
            return True
        return False
    for alias, target in _ALIASES.items():
        try:
            sys.modules[alias] = importlib.import_module(target)
        except ImportError:
            pass
    return True
