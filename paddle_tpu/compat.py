"""Drop-in ``paddle`` module aliasing + cross-version jax shims.

Reference config files and demos start with ``from
paddle.trainer_config_helpers import *`` or ``import paddle.v2 as paddle``.
``install_paddle_alias()`` registers this package under the ``paddle`` name
in ``sys.modules`` so those files run unmodified against the TPU runtime
(the compatibility claim of BASELINE.json's "keep the Python v2 API").

The alias is only installed when no real ``paddle`` is importable, and is
idempotent.

``shard_map`` papers over the jax spelling change: new jax exports
``jax.shard_map`` (replication checking via ``check_vma``); 0.4.x has
``jax.experimental.shard_map.shard_map`` (``check_rep``).  Every
shard_map user in this package goes through this one symbol so the
parallel layers import (and run) on both.
"""

from __future__ import annotations

import importlib
import sys

try:  # new-jax spelling
    from jax import shard_map as _jax_shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _jax_shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """Version-portable ``jax.shard_map``; ``check_vma`` maps onto the
    installed jax's replication-check kwarg (``check_rep`` on 0.4.x)."""
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _jax_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


def tpu_compiler_params(**kwargs):
    """Version-portable pallas-TPU compiler params: new jax spells the
    class ``pltpu.CompilerParams``, older jax ``TPUCompilerParams`` (same
    fields).  Every pallas kernel in this package goes through this one
    constructor so the ops import (and run) on both."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis (``lax.axis_size`` where it
    exists; 0.4.x exposes it as ``core.axis_frame(name)`` — an int, so
    Python-level loop bounds like ppermute rings keep working)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax import core

    return core.axis_frame(axis_name)

_ALIASES = {
    "paddle": "paddle_tpu",
    "paddle.trainer_config_helpers": "paddle_tpu.trainer_config_helpers",
    "paddle.trainer_config_helpers.optimizers": "paddle_tpu.trainer_config_helpers.optimizers",
    "paddle.trainer": "paddle_tpu.trainer",
    "paddle.trainer.config_parser": "paddle_tpu.trainer.config_parser",
    "paddle.trainer.PyDataProvider2": "paddle_tpu.reader.py_data_provider2",
    "paddle.proto": "paddle_tpu.proto",
    "paddle.v2": "paddle_tpu.v2",
}


def install_paddle_alias(force: bool = False) -> bool:
    if "paddle" in sys.modules and not force:
        already_ours = getattr(sys.modules["paddle"], "__name__", "").startswith(
            "paddle_tpu"
        )
        if already_ours:
            return True
        return False
    for alias, target in _ALIASES.items():
        try:
            sys.modules[alias] = importlib.import_module(target)
        except ImportError:
            pass
    return True
