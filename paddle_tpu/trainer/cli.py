"""``python -m paddle_tpu.trainer`` — the classic trainer CLI.

≅ ``paddle/trainer/TrainerMain.cpp:24-61``: ``--config=<file>``,
``--job=train|test|time|checkgrad``, ``--config_args=k=v,...``,
``--num_passes``, ``--init_model_path``, ``--save_dir``.  The config file is
a v1 config (trainer_config_helpers) compiled by
:mod:`paddle_tpu.trainer.config_parser`; training runs the same jitted step
the v2 API uses.

Job modes:

- ``train``: pass loop over the config's PyDataProvider2 data source
  (``define_py_data_sources2``), saving pass checkpoints under --save_dir
  (≅ Trainer::train, ParamUtil).
- ``test``: forward over the test source, printing cost + evaluators
  (≅ Trainer::test / Tester.cpp).
- ``time``: ``--job=time`` benchmark of the train step
  (≅ TrainerBenchmark.cpp), ms/batch via the two-point method.
- ``checkgrad``: finite-difference vs ``jax.grad`` on every parameter
  (≅ Trainer::checkGradient, Trainer.cpp:332); exits nonzero on mismatch.
"""

from __future__ import annotations

import argparse
import importlib
import os
import sys

import numpy as np


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.trainer",
        description="paddle_tpu trainer (TrainerMain analog)",
    )
    p.add_argument("--config", required=True, help="v1 config file")
    p.add_argument("--job", default="train",
                   choices=["train", "test", "time", "checkgrad"])
    p.add_argument("--preflight", action="store_true",
                   help="build the configured train AND eval steps and "
                        "run the static program checks (paddle_tpu/"
                        "analysis: host-sync points, un-donated update "
                        "buffers, bf16 upcasts, per-device memory vs "
                        "--hbm_gb / --vmem_mb budgets, sharding-flow "
                        "audit, RNG fold-in discipline, ZeRO collective-"
                        "lowering mismatch, cross-rank program-"
                        "fingerprint divergence under --preflight_"
                        "rendezvous) instead of training; exit 1 on any "
                        "unsuppressed finding — the config_parser-style "
                        "reject-before-running gate.  --hbm_gb, "
                        "--vmem_mb and --preflight_rendezvous are "
                        "registry flags (PADDLE_TPU_* overridable)")
    p.add_argument("--config_args", default="",
                   help="var=val,... exposed via get_config_arg")
    p.add_argument("--num_passes", type=int, default=1)
    p.add_argument("--save_dir", default=None)
    p.add_argument("--init_model_path", default=None)
    p.add_argument("--log_period", type=int, default=100)
    p.add_argument("--test_period", type=int, default=0,
                   help="accepted for v1 compat")
    p.add_argument("--trainer_count", type=int, default=1,
                   help="data-parallel shards (mesh 'data' axis)")
    p.add_argument("--use_gpu", default=None, help="accepted for v1 compat")
    p.add_argument("--dot_period", type=int, default=1,
                   help="accepted for v1 compat")
    p.add_argument("--saving_period", type=int, default=1,
                   help="save a pass checkpoint every N passes")
    # input pipeline / overlap (see README "Input pipeline & overlap"):
    # unlike the v2 API (whose flag defaults prefetch_depth=0 /
    # sync_period=1 keep exact v2 semantics), the CLI defaults to the
    # overlapped configuration — operators get the win out of the box, at
    # the cost of burst-delivered EndIteration log lines.  Resolution
    # order (cmd_train): explicit CLI arg > PADDLE_TPU_* flag override >
    # CLI default (2 / 8).
    p.add_argument("--prefetch", type=int, default=None,
                   help="device feeds staged ahead of the step loop "
                        "(0 = synchronous input; default 2)")
    p.add_argument("--sync_period", type=int, default=None,
                   help="fence device costs every N steps (1 = per-batch "
                        "v2 event cadence; default 8)")
    p.add_argument("--seq_buckets", default=None,
                   help="comma-separated length-bucket table (e.g. "
                        "'8,16,32,64'): batch the training reader by "
                        "quantized sequence length and pad feeds only to "
                        "each bucket's ceiling — padded timesteps stop "
                        "burning recurrent flops (empty = off)")
    # weight-update sharding (README "Weight-update sharding (ZeRO-1/2)"):
    # the pserver's sharded aggregation re-expressed in-mesh
    p.add_argument("--zero", type=int, default=None, choices=[0, 1, 2],
                   help="ZeRO weight-update sharding over the mesh data "
                        "axis: 0 = replicated update (default) | 1 = "
                        "1/n-sharded optimizer state | 2 = reduce-scatter "
                        "grads + sharded update + all-gather params")
    # fault tolerance (README "Fault tolerance & recovery"): crash-safe
    # cursor checkpoints, the numeric guard, the restart-budget
    # supervisor and the deterministic chaos harness
    p.add_argument("--checkpoint_dir", default=None,
                   help="crash-safe checkpoint directory (params + "
                        "optimizer + states + (pass,batch) cursor); "
                        "resume is automatic")
    p.add_argument("--checkpoint_period", type=int, default=1,
                   help="full checkpoint every N passes")
    p.add_argument("--checkpoint_batch_period", type=int, default=None,
                   help="also checkpoint every N batches mid-pass "
                        "(0 = per-pass only)")
    p.add_argument("--checkpoint_keep", type=int, default=None,
                   help="retention GC: keep the newest N checkpoints "
                        "(0 = keep everything); the newest valid one and "
                        "any pinned mid-export are never deleted")
    p.add_argument("--nan_policy", default=None,
                   choices=["none", "skip", "rollback"],
                   help="non-finite-loss policy: none (die) | skip "
                        "(drop the poisoned update) | rollback (restore "
                        "the last checkpoint + reduced-LR rescue window)")
    p.add_argument("--max_restarts", type=int, default=None,
                   help="worker faults absorbed by restart-and-resume "
                        "(0 = die on the first fault); needs "
                        "--checkpoint_dir to resume rather than rewind")
    p.add_argument("--chaos", default=None,
                   help="deterministic fault-injection schedule, e.g. "
                        "'reader_error@3,nan@5,sigterm@7,host_loss@9:dp=4'"
                        "; serving-fleet kinds (replica_loss/replica_"
                        "hang@k:replica=i, servable_corrupt@k) arm via "
                        "FleetRouter(chaos=...) — TESTING ONLY (see "
                        "resilience/chaos.py)")
    p.add_argument("--elastic", action="store_true", default=None,
                   help="arm live resharding on host-loss/scale events: "
                        "membership changes rebuild the mesh at the new "
                        "data-parallel degree at a batch boundary "
                        "instead of killing the run (resilience/"
                        "elastic.py)")
    p.add_argument("--elastic_membership", default=None,
                   help="membership file to watch for elastic events "
                        "(default: the launcher's PADDLE_TPU_MEMBERSHIP "
                        "env when --elastic is set)")
    p.add_argument("--seq_dim", type=int, default=8,
                   help="timesteps per synthetic sequence for --job=time/"
                        "checkgrad feeds (the reference RNN benchmark pads "
                        "to 100, benchmark/paddle/rnn/rnn.py:8)")
    # checkgrad knobs (Trainer.cpp:332 checkgrad_eps analog)
    p.add_argument("--checkgrad_eps", type=float, default=1e-3,
                   help="tolerance scale for the gradient check")
    p.add_argument("--checkgrad_samples", type=int, default=6,
                   help="random entries probed per parameter")
    return p


def _provider_args(rec: dict) -> dict:
    """define_py_data_sources2 args=... -> init_hook kwargs (dict or
    'k=v,...' string form)."""
    args = rec.get("args") or {}
    if isinstance(args, str):
        args = dict(f.split("=", 1) for f in args.split(",") if "=" in f)
    return args


def _raw_reader_from_data_config(rec: dict, topo, input_order):
    """DataConfig -> (unbatched reader, provider-ish object).

    Dispatches on the config's data source type: PyDataProvider2 modules
    ("py"/define_py_data_sources2), binary DataFormat.proto files
    ("proto", ProtoDataProvider), or several sub-sources zipped into one
    sample stream ("multi", MultiDataProvider.h:24)."""
    from paddle_tpu.reader.py_data_provider2 import read_file_list

    kind = rec.get("type")
    if kind == "proto":
        from paddle_tpu.reader import proto_data

        files = read_file_list(rec["files"])
        types = proto_data.input_types_from_header(files[0])
        # row shape must match the header-derived types dataset-wide
        sequential = any(t.seq_type != 0 for t in types)
        reader = proto_data.proto_reader(
            files, sequential=sequential,
            usage_ratio=rec.get("usage_ratio"))

        class _ProtoObj:  # reader metadata the batching code consults
            should_shuffle = True
            calc_batch_size = None
            input_types = types

        if topo is not None:
            _apply_provider_types(topo, _ProtoObj, input_order)
        return reader, _ProtoObj
    if kind == "multi":
        from paddle_tpu.reader import proto_data

        subs = [_raw_reader_from_data_config(sub, None, None)
                for sub in rec["sub"]]
        reader = proto_data.multi_reader([r for r, _ in subs])
        # merge type declarations preserving names where present: a dict
        # binds by layer name, so mixing forms positionally would scramble
        # layers — flatten dicts ONLY when every sub uses the list form
        if any(isinstance(getattr(o, "input_types", None), dict)
               for _, o in subs):
            types = {}
            for _, o in subs:
                sub_types = getattr(o, "input_types", None) or {}
                enforce_dict = isinstance(sub_types, dict)
                if not enforce_dict:
                    raise ValueError(
                        "MultiData: mixing dict-typed and list-typed "
                        "sub-providers is ambiguous; declare all "
                        "input_types as {layer: type} dicts")
                types.update(sub_types)
        else:
            types = []
            for _, o in subs:
                types.extend(getattr(o, "input_types", None) or [])

        class _MultiObj:
            should_shuffle = True
            calc_batch_size = None
            input_types = types

        if topo is not None and types:
            _apply_provider_types(topo, _MultiObj, input_order)
        return reader, _MultiObj

    mod = importlib.import_module(rec["module"])
    obj = getattr(mod, rec["obj"])
    files = read_file_list(rec["files"])
    # config-supplied provider kwargs (define_py_data_sources2 args=...)
    # reach the init_hook; types may be declared there rather than in the
    # decorator, so bind them AFTER make_reader ran the hook
    reader = obj.make_reader(files, **_provider_args(rec))
    if topo is not None:
        _apply_provider_types(topo, obj, input_order)
    return reader, obj


def _reader_from_data_config(rec: dict, batch_size: int, shuffle: bool,
                             topo=None, input_order=None,
                             drop_last: bool | None = None,
                             seq_buckets=None):
    """DataConfig(py2) -> batched paddle reader via the provider module.
    The provider's declared ``input_types`` override the data layers' dense
    placeholders (reference: types live in the provider, not the config).
    ``seq_buckets`` (a table from ``--seq_buckets``) batches by quantized
    length instead of arrival order, so padded timesteps stop burning
    flops in the recurrent sweeps."""
    import paddle_tpu as paddle

    reader, obj = _raw_reader_from_data_config(rec, topo, input_order)
    if shuffle and getattr(obj, "should_shuffle", True) is not False:
        reader = paddle.reader.shuffle(reader, buf_size=4096)
    if seq_buckets:
        from paddle_tpu.parallel.mesh import get_mesh
        from paddle_tpu.reader.decorator import bucket_by_length

        # remainder="pad": leftover pools fill to the FULL batch size, so
        # every bucket stays ONE jit signature — the same recompile
        # discipline the drop_last rule below applies to plain batching
        # (a "drop"-trimmed tail would mint a fresh (batch, time) shape
        # every pass under shuffle)
        return bucket_by_length(
            reader, batch_size, buckets=seq_buckets, remainder="pad",
            size_multiple=get_mesh().num_replicas)
    if drop_last is None:
        # train (shuffle=True): tail flushes would emit non-pinned batch
        # sizes and recompile every pass (shuffle reorders the tail).
        # test: metrics must cover every sample, so flush tails — the tail
        # shapes are deterministic so at most one extra compile per shape.
        drop_last = shuffle
    calc = getattr(obj, "calc_batch_size", None)
    if calc is not None:
        # PyDataProvider2 dynamic-batch semantics: cost-balanced batches
        # per length bucket (one static shape each), trimmed to the mesh
        # replica count for sharding divisibility
        from paddle_tpu.parallel.mesh import get_mesh
        from paddle_tpu.reader.decorator import bucket_batch

        return bucket_batch(reader, batch_size, calc_batch_size=calc,
                            size_multiple=get_mesh().num_replicas,
                            drop_last=drop_last)
    batched = paddle.reader.batch(reader, batch_size=batch_size,
                                  drop_last=drop_last)
    if drop_last:
        return batched
    # tail batches must still divide the mesh data axis (shard_batch
    # enforces batch % replicas == 0); trim like bucket_batch does
    from paddle_tpu.parallel.mesh import get_mesh

    m = get_mesh().num_replicas

    def trimmed():
        dropped = 0
        for b in batched():
            if len(b) == batch_size:
                # full batches pass through: a batch_size that doesn't
                # divide the mesh is a config error shard_batch reports
                yield b
                continue
            n = (len(b) // m) * m
            dropped += len(b) - n
            if n:
                yield b[:n]
        if dropped:
            from paddle_tpu.core import logger as log

            log.info("test reader: dropped %d tail samples not divisible "
                     "by the %d-replica mesh", dropped, m)

    return trimmed if m > 1 else batched


def _add_config_dir_to_path(config_path: str) -> None:
    d = os.path.dirname(os.path.abspath(config_path))
    if d not in sys.path:
        sys.path.insert(0, d)


def _apply_provider_types(topo, obj, input_order):
    """Bind the provider's declared input_types onto the data layers (the
    reference keeps types in the provider, not the config).  Accepts both
    the dict form ({layer: type}) and the positional list form (matched to
    the config's input order)."""
    types = getattr(obj, "input_types", None)
    if types is None:
        return
    if isinstance(types, dict):
        items = types.items()
    else:
        order = input_order or list(topo.data_layers())
        items = zip(order, types)
    for lname, itype in items:
        node = topo.data_layers().get(lname)
        if node is not None:
            node.attrs.update(data_type=itype.kind,
                              seq_type=itype.seq_type, dim=itype.dim)


def _load_provider_types(args, parsed, topo):
    """For jobs that never build a reader (time/checkgrad): still bind the
    provider's input_types so synthetic feeds have the right kinds."""
    from paddle_tpu.config import parse_state

    rec = parse_state.STATE.data_config or parse_state.STATE.test_data_config
    if not rec:
        return
    if rec.get("type") in ("proto", "multi"):
        # header-derived types (no provider module to import)
        try:
            _raw_reader_from_data_config(rec, topo, parsed.input_layer_names)
        except Exception as e:
            from paddle_tpu.core import logger as log

            log.debug("proto/multi data files unavailable (%s); dense "
                      "placeholders stand", e)
        return
    if not rec.get("module"):
        return
    _add_config_dir_to_path(args.config)
    try:
        mod = importlib.import_module(rec["module"])
        obj = getattr(mod, rec["obj"])
    except Exception as e:
        from paddle_tpu.core import logger as log

        log.debug("data provider %s unavailable (%s); dense placeholders "
                  "stand", rec.get("module"), e)
        return
    if getattr(obj, "input_types", None) is None:
        # init_hook providers declare types on ``settings`` at reader
        # construction (benchmark/paddle/image/provider.py pattern); run
        # the hook over an empty file list just to harvest them
        try:
            obj.make_reader([], **_provider_args(rec))
        except Exception as e:
            from paddle_tpu.core import logger as log

            log.warning(
                "provider init_hook type harvest failed (%s); synthetic "
                "feeds fall back to dense placeholders — --job=time may "
                "benchmark a different input topology", e)
    _apply_provider_types(topo, obj, parsed.input_layer_names)


def _build(parsed):
    """ParsedConfig -> (topology, optimizer, data_types, feeding)."""
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer_config_helpers.optimizers import (
        get_settings_optimizer,
    )

    # evaluator inputs may name layers off the cost path (the reference's
    # GradientMachine computes every configured layer, so evaluators can
    # tap any of them) — keep those alive as extra topology roots
    from paddle_tpu.layers.base import layer_registry

    ev_names = {n for s in (getattr(parsed, "evaluators", None) or [])
                for n in s.input_layers}
    from paddle_tpu.layers.base import companion_name

    ev_names |= {companion_name(n) for n in set(ev_names)}
    extra = [lo for lo in layer_registry() if lo.name in ev_names]
    topo = Topology(parsed.output_layers(), extra_layers=extra)
    opt = get_settings_optimizer()
    from paddle_tpu.layers.data_type import InputType

    data_layers = topo.data_layers()
    order = [n for n in parsed.input_layer_names if n in data_layers]
    if not order:
        order = list(data_layers)
    # data layers reached only via evaluator extra roots still need a feed
    # slot (the provider yields fields for every configured data layer)
    order += [n for n in data_layers if n not in order]
    types = [
        (n, InputType(data_layers[n].attrs.get("dim", data_layers[n].size),
                      data_layers[n].attrs.get("seq_type", 0),
                      data_layers[n].attrs.get("data_type", "dense")))
        for n in order
    ]
    feeding = {n: i for i, (n, _) in enumerate(types)}
    return topo, opt, types, feeding


def cmd_preflight(args, parsed) -> int:
    """--preflight: static program checks over the step cmd_train would
    run — the config_parser-style validation gate, but over the
    compiled program instead of the config text."""
    import jax.numpy as jnp

    from paddle_tpu.analysis.preflight import run_preflight
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.parallel.mesh import get_mesh

    topo, opt, types, feeding = _build(parsed)
    _load_provider_types(args, parsed, topo)
    mesh = get_mesh()
    dp = mesh.mesh.shape.get("data", 1)
    batch_size = parsed.opt_config.batch_size or 32
    if batch_size % dp:  # the probe batch must shard like a real batch
        batch_size += dp - batch_size % dp
    feed = _synthetic_feed(topo, batch_size, seq_dim=args.seq_dim)
    zero = args.zero if args.zero is not None else _flags.get("zero")
    compute_dtype = jnp.bfloat16 if _flags.get("bf16") else None
    sync_period = args.sync_period if args.sync_period is not None \
        else _flags.get("sync_period")
    # fleet identity comes from the launcher's rendezvous env (the same
    # vars distributed.launch stamps per rank); with a rendezvous dir
    # and nproc > 1 the GL-P-DIVERGE fingerprint exchange is armed
    rank = int(os.environ.get("PADDLE_TPU_TRAINER_ID", "0"))
    nproc = int(os.environ.get("PADDLE_TPU_NPROC", "1"))
    epoch = int(os.environ.get("PADDLE_TPU_RENDEZVOUS_EPOCH", "0"))
    cost: dict = {}
    unsup, sup = run_preflight(
        topo, opt, feed, mesh, zero=zero, compute_dtype=compute_dtype,
        sync_period=sync_period, inject=_flags.get("preflight_inject"),
        config=os.path.basename(args.config),
        hbm_gb=_flags.get("hbm_gb"), vmem_mb=_flags.get("vmem_mb"),
        hw_profile=_flags.get("hw_profile"),
        mfu_floor=_flags.get("mfu_floor"),
        rendezvous_dir=_flags.get("preflight_rendezvous"),
        rank=rank, nproc=nproc, rendezvous_epoch=epoch, cost_out=cost)
    for f in unsup:
        print(f.render())
    if sup:
        print(f"({len(sup)} finding(s) suppressed by baseline)")
    if cost:
        print(f"preflight cost [{cost.get('profile')}]: predicted step "
              f"{cost.get('step_ms', 0.0):.2f} ms, MFU "
              f"{cost.get('mfu_pct', 0.0):.1f}%, bottleneck "
              f"{cost.get('bottleneck', '?')}")
    if unsup:
        print(f"preflight: {len(unsup)} unsuppressed finding(s) — "
              f"fix the program or baseline them with a reason")
        return 1
    budget = (f", {float(_flags.get('hbm_gb')):.1f} GB budget"
              if _flags.get("hbm_gb") else "")
    print(f"preflight: OK — {args.config} (zero={zero}, data={dp}"
          f"{budget})")
    return 0


def cmd_train(args, parsed) -> int:
    import paddle_tpu as paddle

    topo, opt, types, feeding = _build(parsed)
    batch_size = parsed.opt_config.batch_size or 32
    rec = __import__("paddle_tpu.config.parse_state", fromlist=["STATE"])
    data_rec = rec.STATE.data_config
    if data_rec is None:
        print("config defines no data source (define_py_data_sources2)",
              file=sys.stderr)
        return 2
    _add_config_dir_to_path(args.config)
    from paddle_tpu.core import flags as _bflags
    from paddle_tpu.reader.feeder import parse_seq_buckets

    seq_buckets = parse_seq_buckets(
        args.seq_buckets if args.seq_buckets is not None
        else _bflags.get("seq_buckets"))
    reader = _reader_from_data_config(data_rec, batch_size, shuffle=True,
                                      topo=topo,
                                      input_order=parsed.input_layer_names,
                                      seq_buckets=seq_buckets)

    params = paddle.parameters.create(topo)
    if args.init_model_path:
        with open(args.init_model_path, "rb") as f:
            params = paddle.parameters.Parameters.from_tar(f)

    from paddle_tpu.core import flags as _zflags

    trainer = paddle.trainer.SGD(
        cost=topo.outputs, parameters=params, update_equation=opt,
        extra_layers=topo.extra_layers,
        declared_evaluators=getattr(parsed, "evaluators", None),
        zero=(args.zero if args.zero is not None
              else _zflags.get("zero")))

    def on_event(event):
        if isinstance(event, paddle.event.EndIteration):
            if event.batch_id % args.log_period == 0:
                print(f"Pass {event.pass_id}, Batch {event.batch_id}, "
                      f"Cost {event.cost:.6f}, {event.metrics}")
        elif isinstance(event, paddle.event.EndPass):
            if event.metrics:
                # ≅ the reference's "Eval: name=value" pass summary line
                evals = " ".join(f"{k}={v:.6g}" if isinstance(v, float)
                                 else f"{k}={v}"
                                 for k, v in event.metrics.items())
                print(f"Pass {event.pass_id} Eval: {evals}")
            due = (event.pass_id % args.saving_period == args.saving_period - 1
                   or event.pass_id == args.num_passes - 1)
            if args.save_dir and due:
                os.makedirs(args.save_dir, exist_ok=True)
                path = os.path.join(
                    args.save_dir, f"pass-{event.pass_id:05d}.tar")
                with open(path, "wb") as f:
                    trainer.save_parameter_to_tar(f)
                print(f"saved {path}")

    from paddle_tpu.core import flags as _flags

    def _resolve(arg_val, flag_name, cli_default):
        if arg_val is not None:  # explicit CLI arg wins
            return arg_val
        if _flags.is_set(flag_name):  # then an operator's env/flag override
            return _flags.get(flag_name)
        return cli_default

    # deterministic chaos harness (TESTING ONLY): one schedule object for
    # the whole run, so once-faults stay fired across supervisor restarts
    chaos_spec = _resolve(args.chaos, "chaos", "")
    handler, train_reader, schedule = on_event, reader, None
    if chaos_spec:
        from paddle_tpu.resilience.chaos import ChaosSchedule

        schedule = ChaosSchedule(chaos_spec,
                                 seed=_flags.get("chaos_seed"))
        handler = schedule.wrap_event_handler(on_event)
        train_reader = schedule.wrap_reader(reader)

    # elastic fleet: membership events rebuild the mesh live at batch
    # boundaries (resilience/elastic.py); host-loss/scale-up chaos
    # faults and the launcher's membership file both feed the queue
    elastic = None
    if _resolve(args.elastic, "elastic", False):
        from paddle_tpu.resilience.elastic import ElasticCoordinator

        elastic = ElasticCoordinator(checkpoint_dir=args.checkpoint_dir)
        membership = _resolve(args.elastic_membership,
                              "elastic_membership",
                              os.environ.get("PADDLE_TPU_MEMBERSHIP", ""))
        if membership:
            # baseline = the fleet this rank JOINED: a peer that died
            # before our first file read must still read as a loss
            from paddle_tpu.distributed import multihost as _mh

            elastic.seed_membership(
                _mh.rendezvous_epoch(),
                int(os.environ.get("PADDLE_TPU_NPROC", "1")))
            elastic.watch_membership(membership)
            elastic.arm_signal(membership)
        if schedule is not None:
            schedule.bind_elastic(elastic)

    def run_train():
        if schedule is not None:
            # per-attempt index re-base: fault positions stay aligned
            # with the attempt's own batch/step stream across restarts
            # (fired-state persists, so once-faults still fire once;
            # ':always' faults re-fire at the same per-attempt spot)
            schedule.reset_counters()
        trainer.train(
            reader=train_reader, num_passes=args.num_passes,
            event_handler=handler, feeding=feeding,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_period=args.checkpoint_period,
            checkpoint_batch_period=_resolve(
                args.checkpoint_batch_period, "checkpoint_batch_period", 0),
            checkpoint_keep=_resolve(
                args.checkpoint_keep, "checkpoint_keep", 3),
            nan_policy=_resolve(args.nan_policy, "nan_policy", "none"),
            sync_period=_resolve(args.sync_period, "sync_period", 8),
            prefetch=_resolve(args.prefetch, "prefetch_depth", 2),
            elastic=elastic, seq_buckets=seq_buckets)

    max_restarts = _resolve(args.max_restarts, "max_restarts", 0)
    try:
        if max_restarts > 0:
            # the run supervisor: worker faults restart the loop; each
            # retry resumes from the newest valid checkpoint's
            # (pass, batch) cursor — and drops any queued elastic event
            # the restored state already reflects
            from paddle_tpu.resilience.supervisor import Supervisor

            Supervisor(max_restarts=max_restarts,
                       elastic=elastic).run(run_train)
        else:
            run_train()
    finally:
        if elastic is not None:
            elastic.stop()
    return 0


def cmd_test(args, parsed) -> int:
    import paddle_tpu as paddle

    topo, opt, types, feeding = _build(parsed)
    batch_size = parsed.opt_config.batch_size or 32
    from paddle_tpu.config import parse_state

    rec = parse_state.STATE.test_data_config or parse_state.STATE.data_config
    if rec is None:
        print("config defines no test data source", file=sys.stderr)
        return 2
    _add_config_dir_to_path(args.config)
    reader = _reader_from_data_config(rec, batch_size, shuffle=False,
                                      topo=topo,
                                      input_order=parsed.input_layer_names)

    params = paddle.parameters.create(topo)
    if args.init_model_path:
        with open(args.init_model_path, "rb") as f:
            params = paddle.parameters.Parameters.from_tar(f)
    trainer = paddle.trainer.SGD(
        cost=topo.outputs, parameters=params, update_equation=opt,
        extra_layers=topo.extra_layers,
        declared_evaluators=getattr(parsed, "evaluators", None))
    result = trainer.test(reader=reader, feeding=feeding)
    print(f"Test cost {result.cost:.6f}, {result.metrics}")
    return 0


def cmd_time(args, parsed) -> int:
    """--job=time: benchmark one jitted train step on synthetic data."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.trainer.step import build_train_step

    topo, opt, types, feeding = _build(parsed)
    _load_provider_types(args, parsed, topo)
    batch_size = parsed.opt_config.batch_size or 32
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt)
    feed = _synthetic_feed(topo, batch_size, seq_dim=args.seq_dim)
    key = jax.random.key(0)

    def one(params, opt_state, states):
        p, o, s, c, _ = step(params, opt_state, states, feed, key)
        return c

    # device-side timing where a profiler trace is available (BENCHMARKS
    # header: wall-clock two-point swings up to 3x below ~10 ms/step
    # through a tunneled TPU); fall back to the two-point benchmark
    carry = {"s": (params, opt_state, states)}

    def stateful():
        p, o, s, c, _ = step(*carry["s"], feed, key)
        carry["s"] = (p, o, s)
        return c

    def _deleted(x):
        try:
            return x.is_deleted()
        except (AttributeError, TypeError):  # plain numpy leaf
            return False

    def wall():
        # the donating step consumes its inputs, so if it raised MID-call
        # during the device-timing attempt, carry["s"] references deleted
        # buffers and the retry would die on an unrelated deleted-buffer
        # error (ADVICE round 5) — the state is synthetic, so rebuild it
        if any(_deleted(leaf) for leaf in jax.tree.leaves(carry["s"])):
            p2 = paddle.parameters.create(topo).as_dict()
            carry["s"] = (p2, opt.init(p2, specs), topo.init_states())
        res = profiler.benchmark(one, carry["s"],
                                 name=os.path.basename(args.config))
        return res.seconds_per_step * 1000.0

    ms, how, why = profiler.step_ms_with_fallback(stateful, wall)
    if why:
        from paddle_tpu.core import logger as log

        log.warning("--job=time device timing unavailable (%s); "
                    "wall-clock two-point used", why)
    # the benchmark result joins the structured metrics stream (same
    # schema as bench.py rows; JSONL sink via --metrics_jsonl)
    from paddle_tpu import metrics as metrics_mod

    reg = metrics_mod.get_registry()
    if reg.active:
        reg.emit({
            "metric": "trainer_time_ms_per_batch",
            "value": round(ms, 3), "unit": "ms", "run": "time",
            "config": os.path.basename(args.config),
            "batch_size": batch_size, "timing": how,
        }, kind="bench")
    print(f"TrainerBenchmark {args.config}: {ms:.3f} ms/batch "
          f"(batch_size={batch_size}, {how})")
    return 0


def _synthetic_feed(topo, batch_size: int, seq_dim: int = 8):
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.layers.data_type import DataKind, SeqType

    rng = np.random.default_rng(0)
    feed = {}
    for name, node in topo.data_layers().items():
        t = node.attrs
        kind, seq = t.get("data_type"), t.get("seq_type")
        dim = t.get("dim", node.size)
        if kind == DataKind.INTEGER:
            data = rng.integers(0, dim, size=(batch_size,))
        else:
            data = rng.normal(size=(batch_size, dim)).astype(np.float32)
        if seq and seq != SeqType.NO_SEQUENCE:
            tdim = seq_dim
            if kind == DataKind.INTEGER:
                data = rng.integers(0, dim, size=(batch_size, tdim))
            else:
                data = rng.normal(size=(batch_size, tdim, dim)).astype(
                    np.float32)
            feed[name] = SequenceBatch(
                data=data, length=np.full((batch_size,), tdim, np.int32))
        else:
            feed[name] = data
    return feed


def cmd_checkgrad(args, parsed) -> int:
    """Finite differences vs jax.grad on every parameter
    (≅ Trainer::checkGradient, Trainer.cpp:332)."""
    import jax

    # finite differences need more mantissa than the training dtype; the
    # globals are restored before returning (cli.main may be called
    # in-process).  A user-set --bf16 is also suspended: central
    # differences with eps=1e-3 on a bf16-rounded function would fail
    # every parameter spuriously.
    from paddle_tpu.core import flags as _flags

    prev_x64 = jax.config.jax_enable_x64
    prev_prec = jax.config.jax_default_matmul_precision
    prev_bf16 = _flags.get("bf16")
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_default_matmul_precision", "highest")
    _flags.set("bf16", False)
    import jax.numpy as jnp

    import paddle_tpu as paddle

    topo, opt, types, feeding = _build(parsed)
    _load_provider_types(args, parsed, topo)
    batch_size = min(parsed.opt_config.batch_size or 8, 8)
    params = {
        k: jnp.asarray(np.asarray(v), jnp.float64)
        for k, v in paddle.parameters.create(topo).as_dict().items()
    }
    states = {k: jnp.asarray(np.asarray(v), jnp.float64)
              for k, v in topo.init_states().items()}
    feed = _synthetic_feed(topo, batch_size, seq_dim=args.seq_dim)
    key = jax.random.key(0)

    @jax.jit
    def loss_fn(p):
        values, _ = topo.forward(p, states, feed, True, key)
        total = 0.0
        for out in topo.outputs:
            v = values[out.name]
            v = v.data if hasattr(v, "data") else v
            total = total + jnp.sum(v)
        return total

    from jax.test_util import check_grads

    failures = []
    for name, value in params.items():
        def one_param(v, name=name):
            return loss_fn({**params, name: v})

        try:
            # reverse-mode vs numerical jacobian along random directions
            # (jax's own methodology; ≅ Trainer::checkGradient's
            # whole-parameter perturbation, Trainer.cpp:332)
            check_grads(one_param, (value,), order=1, modes=("rev",),
                        atol=args.checkgrad_eps * 10,
                        rtol=args.checkgrad_eps * 10)
            print(f"checkgrad {name}: ok")
        except AssertionError as e:
            failures.append((name, str(e).splitlines()[0][:120]))
            print(f"checkgrad {name}: FAIL")
    jax.config.update("jax_enable_x64", prev_x64)
    jax.config.update("jax_default_matmul_precision", prev_prec)
    _flags.set("bf16", prev_bf16)
    if failures:
        for name, msg in failures[:10]:
            print(f"  MISMATCH {name}: {msg}", file=sys.stderr)
        return 1
    print(f"checkgrad PASSED over {len(params)} parameters")
    return 0


def main(argv=None) -> int:
    # args argparse doesn't know go to the gflags registry (TrainerMain
    # passes unparsed argv into gflags the same way) — e.g. --bf16,
    # --with_timer, --debug_nans
    args, extra = build_argparser().parse_known_args(argv)
    changed: dict = {}
    if extra:
        from paddle_tpu.core import flags as _flags

        before = _flags.snapshot_raw()
        leftover = _flags.parse_args(extra)
        # cli.main may be called in-process (demo runners, tests):
        # restore exactly the flags THIS call changed, on every exit
        # path — as RAW override values, so restoring a default doesn't
        # leave the flag marked explicitly-set (flags.is_set)
        after = _flags.snapshot_raw()
        changed = {k: before[k] for k in before if after[k] != before[k]}
        if leftover:
            _flags.restore_raw(changed)
            build_argparser().error(
                f"unrecognized arguments: {' '.join(leftover)}")
    from paddle_tpu.trainer.config_parser import parse_config

    # --metrics_jsonl=PATH (a registry flag, not argparse): attach the
    # JSONL sink so every job mode emits through the telemetry stream
    from paddle_tpu import metrics as _metrics

    _metrics.configure_from_flags()
    try:
        parsed = parse_config(args.config, args.config_args)
        if args.preflight:
            return cmd_preflight(args, parsed)
        jobs = {
            "train": cmd_train,
            "test": cmd_test,
            "time": cmd_time,
            "checkgrad": cmd_checkgrad,
        }
        return jobs[args.job](args, parsed)
    finally:
        if changed:
            from paddle_tpu.core import flags as _flags

            _flags.restore_raw(changed)


if __name__ == "__main__":
    sys.exit(main())
