"""Inference — successor of ``python/paddle/v2/inference.py:10-111``
(Inference.infer: test-mode forward returning numpy outputs) and the C
serving path (``paddle/capi/gradient_machine.h``; see ``native/`` for the
C-ABI equivalent)."""

from __future__ import annotations

import numpy as np

from paddle_tpu.config.topology import Topology
from paddle_tpu.core.lod import SequenceBatch, to_ragged
from paddle_tpu.core.parameters import Parameters
from paddle_tpu.layers.base import LayerOutput
from paddle_tpu.reader.feeder import DataFeeder
from paddle_tpu.trainer.step import build_forward


class Inference:
    def __init__(self, output_layer, parameters: Parameters,
                 strict: bool = False):
        """``strict=True`` (the serving default — ``serving/dense.py``)
        refuses to run when any topology parameter has no loaded value:
        the legacy behaviour silently ``init_missing``-ed fresh random
        weights, so serving from an incomplete checkpoint produced
        plausible-looking garbage.  Offline/experimental callers keep
        ``strict=False`` (a fresh ``parameters.create`` topology is fully
        initialized anyway)."""
        if isinstance(output_layer, LayerOutput):
            output_layer = [output_layer]
        self.topology = Topology(output_layer)
        self.parameters = parameters
        for spec in self.topology.param_specs():
            self.parameters.add(spec)
        if strict:
            missing = self.parameters.uninitialized_names()
            if missing:
                raise ValueError(
                    "Inference(strict=True): parameters have no value for "
                    f"{sorted(missing)} — the checkpoint/tar is incomplete "
                    "for this topology; refusing to serve random weights")
        self.parameters.init_missing()
        self.output_names = [o.name for o in output_layer]
        self._fwd = build_forward(self.topology, self.output_names)
        # states (e.g. BN moving stats) load from parameters when present
        self.states = {}
        for s in self.topology.state_specs():
            if s.name in self.parameters:
                self.states[s.name] = self.parameters[s.name]
            else:
                import jax.numpy as jnp

                self.states[s.name] = jnp.full(s.shape, s.init_value)

    def _feeder(self, feeding):
        from paddle_tpu.layers.data_type import InputType

        types = {
            name: InputType(
                dim=n.attrs["dim"],
                seq_type=n.attrs.get("seq_type", 0),
                kind=n.attrs.get("data_type", "dense"),
            )
            for name, n in self.topology.data_layers().items()
        }
        return DataFeeder(types, feeding)

    def infer(self, input, feeding=None, field="value", batch_size: int | None = None):
        feeder = self._feeder(feeding)
        params = {n: self.parameters[n] for n in self.parameters.names()}
        batches = [input] if batch_size is None else [
            input[i : i + batch_size] for i in range(0, len(input), batch_size)
        ]
        outs: list[list] = [[] for _ in self.output_names]
        ragged = [False] * len(self.output_names)
        for b in batches:
            feed = feeder(b)
            results = self._fwd(params, self.states, feed)
            for i, r in enumerate(results):
                if isinstance(r, SequenceBatch):
                    outs[i].extend(to_ragged(r))
                    ragged[i] = True
                elif hasattr(r, "inner"):  # NestedGeneratedSequence
                    inner = r.inner.to_list()
                    seq_len = np.asarray(r.seq_length)
                    for s_i in range(seq_len.shape[0]):
                        outs[i].append(
                            inner[s_i * r.n_sub:
                                  s_i * r.n_sub + int(seq_len[s_i])])
                    ragged[i] = True
                elif hasattr(r, "to_list"):  # GeneratedSequence (beam search)
                    outs[i].extend(r.to_list())
                    ragged[i] = True
                else:
                    outs[i].append(np.asarray(r))
        final = []
        for i, chunks in enumerate(outs):
            # ragged per-sequence rows stay a python list (one entry per
            # input row, v2 contract); only dense batch chunks concatenate
            if not ragged[i] and chunks and isinstance(chunks[0], np.ndarray):
                try:
                    final.append(np.concatenate(chunks, axis=0))
                    continue
                except ValueError:
                    pass
            final.append(chunks)
        return final[0] if len(final) == 1 else final


def infer(output_layer, parameters, input, feeding=None, field="value"):
    return Inference(output_layer, parameters).infer(
        input, feeding=feeding, field=field
    )
