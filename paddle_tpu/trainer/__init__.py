"""Training orchestration — successor of ``paddle/trainer`` (Trainer.cpp pass
loop, TrainerInternal.cpp batch loop, the ParameterUpdater family) and the v2
Python loop ``python/paddle/v2/trainer.py:24`` (SGD.train:124)."""

from paddle_tpu.trainer.trainer import SGD  # noqa: F401
