"""The v2 SGD trainer — keeps the contract of
``python/paddle/v2/trainer.py:24`` (``SGD.train:124``: reader → DataFeeder →
forwardBackward → update → events) while replacing the SWIG GradientMachine +
ParameterUpdater stack with one jitted, mesh-sharded train step.

The updater lifecycle the reference exposes (startPass/startBatch/update/
finishBatch/finishPass, ``ParameterUpdater.h:38``) collapses into the compiled
step; pass/batch iteration stays in Python exactly as in v2."""

from __future__ import annotations

import os
import time as _time
from typing import Callable

import jax
import numpy as np

from paddle_tpu.config.topology import Topology
from paddle_tpu.core import flags, rng
from paddle_tpu.core import logger as log
from paddle_tpu.core import stat
from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.core.parameters import Parameters
from paddle_tpu.layers.base import LayerOutput
from paddle_tpu.parallel.mesh import MeshContext, get_mesh
from paddle_tpu.reader import feeder as feeder_mod
from paddle_tpu.reader.feeder import DataFeeder, parse_seq_buckets
from paddle_tpu.trainer import event as v2_event
from paddle_tpu.trainer.step import build_eval_step, build_train_step


class _ElasticReplay(Exception):
    """Control flow, not an error: a checkpoint-fallback elastic rebuild
    restored state behind the current position, so the pass loop must
    re-enter at the restored cursor (reader fast-forward included) —
    the in-process analog of a supervisor restart.  Carries the
    re-placed state so ``_train_loop`` re-enters without another
    restore."""

    def __init__(self, pass_id: int, batch_id: int, params, opt_state,
                 states):
        super().__init__(f"elastic replay from pass {pass_id} "
                         f"batch {batch_id}")
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.params = params
        self.opt_state = opt_state
        self.states = states


def _feed_signature(feed: dict) -> tuple:
    sig = []
    for k in sorted(feed):
        v = feed[k]
        if isinstance(v, SequenceBatch):
            sig.append((k, tuple(v.data.shape), str(v.data.dtype), "seq"))
        else:
            sig.append((k, tuple(v.shape), str(v.dtype)))
    return tuple(sig)


class SGD:
    """v2 ``paddle.trainer.SGD``.

    :param cost: the cost LayerOutput to minimize.
    :param parameters: ``paddle.parameters.create(topology)`` result.
    :param update_equation: a ``paddle_tpu.optimizer.Optimizer``.
    :param extra_layers: additional layers to keep alive (e.g. for evaluators).
    :param is_local: kept for API compat; distribution now comes from the mesh.
    :param mesh: optional MeshContext; default = all devices on the data axis.
    """

    def __init__(self, cost, parameters: Parameters, update_equation,
                 extra_layers=None, is_local: bool = True, pserver_spec=None,
                 use_etcd: bool = False, mesh: MeshContext | None = None,
                 compute_dtype=None, declared_evaluators=None,
                 zero: int | None = None):
        self.compute_dtype = compute_dtype  # e.g. jnp.bfloat16 for the MXU
        # weight-update sharding over the mesh data axis (parallel/zero.py
        # — the pserver's sharded aggregation, in-mesh): 0 = replicated
        # update (the v2 behavior), 1 = 1/n-sharded optimizer state,
        # 2 = reduce-scatter grads + sharded update + all-gather params.
        # Default: the --zero flag (PADDLE_TPU_ZERO).
        self.zero = flags.get("zero") if zero is None else int(zero)
        # v1 *_evaluator declarations (EvaluatorSpecs or a prebuilt
        # DeclaredEvaluators) executed host-side per batch, like
        # GradientMachine::eval driving Evaluator.cpp
        from paddle_tpu.evaluator import runtime as _ev_runtime

        if declared_evaluators is None:
            self.declared_evaluators = _ev_runtime.build([])
        elif isinstance(declared_evaluators, _ev_runtime.DeclaredEvaluators):
            self.declared_evaluators = declared_evaluators
        else:
            self.declared_evaluators = _ev_runtime.build(declared_evaluators)
        self._tap_grads = None
        self._tap_grads_eval = None
        if isinstance(cost, LayerOutput):
            cost = [cost]
        # dual-output companions ("#ids") of declared evaluator inputs
        # join the topology automatically, so the v2 path works like the
        # CLI's without the caller passing extra_layers
        from paddle_tpu.layers import base as layer_base
        from paddle_tpu.layers.base import companion_name

        ev_inputs = {n for b in self.declared_evaluators.bound
                     for n in b.spec.input_layers}
        wanted_extra = ev_inputs | {companion_name(n) for n in ev_inputs}
        # data layers stay OUT: evaluator data inputs outside the topology
        # are resolved from the eval feed (runtime.eval_batch), and forcing
        # them in would make DataFeeder demand feed slots for them
        companions = [lo for lo in layer_base.layer_registry()
                      if lo.name in wanted_extra
                      and lo.layer_type != "data"]
        extra_layers = list(extra_layers or []) + [
            c for c in companions
            if not any(c is e for e in (extra_layers or []))]
        self.topology = Topology(cost, extra_layers=extra_layers)
        self.parameters = parameters
        for spec in self.topology.param_specs():
            self.parameters.add(spec)
        self.parameters.init_missing()
        self.optimizer = update_equation
        self.mesh = mesh if mesh is not None else get_mesh()
        self.states = self.topology.init_states()
        # warm-started Parameters may carry BN moving stats (saved as static
        # entries by save_parameter_to_tar) — load them back
        for sname in list(self.states):
            if sname in self.parameters:
                self.states[sname] = jax.numpy.asarray(self.parameters[sname])
        self._specs = {s.name: s for s in self.topology.param_specs()}
        self._trainable = {n for n, s in self._specs.items() if not s.is_static}
        self._opt_state = None
        self._train_step = None
        self._eval_step = None
        self._compiled_sigs: set = set()
        self._telemetry = None  # StepTelemetry, bound by train()
        self._telemetry_costs: dict = {}  # per-signature cost analysis
        self.__gradient_machine__ = self  # v2 attr some user code touches

    # -- internal -------------------------------------------------------------
    def _params_dict(self):
        return {n: jax.numpy.asarray(self.parameters[n]) for n in self.parameters.names()}

    def _zero_active(self) -> bool:
        return (self.zero >= 1
                and self.mesh.mesh.shape.get("data", 1) > 1)

    def _place_opt_state(self, opt_state):
        """Device placement for the optimizer state: ZeRO runs shard the
        slots 1/n over the data axis (parallel/zero.py), the replicated
        update keeps full copies everywhere — ONE placement point shared
        by train() init, checkpoint resume and the guard's rollback, so
        every path agrees on the layout the jitted step expects."""
        if not self._zero_active():
            return self.mesh.replicate(opt_state)
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.parallel.zero import shard_opt_state

        params = {n: jax.numpy.asarray(self.parameters[n])
                  for n in self._trainable}
        base = {
            n: (P(*s.sharding) if getattr(s, "sharding", None) else P())
            for n, s in self._specs.items() if n in self._trainable}
        return shard_opt_state(opt_state, params, self.mesh.mesh,
                               param_specs=base)

    def _ensure_built(self):
        if self._train_step is None:
            node_names = {n.name for n in self.topology.nodes}
            wanted = {
                name
                for b in (self.declared_evaluators.bound
                          if self.declared_evaluators else [])
                for name in b.spec.input_layers
            }
            # "#ids" companions (crf_decoding's decoded path) ride along so
            # evaluators can read the ids side of a dual-output layer
            from paddle_tpu.layers.base import companion_name
            wanted |= {companion_name(n) for n in set(wanted)}
            fetch = sorted(wanted & node_names)
            self._train_step = build_train_step(
                self.topology, self.optimizer, self.mesh,
                compute_dtype=self.compute_dtype, fetch_layers=fetch,
                zero=self.zero)
            self._eval_step = build_eval_step(self.topology, self.mesh)
            taps = (self.declared_evaluators.grad_tap_layers()
                    if self.declared_evaluators else [])
            if taps:
                from paddle_tpu.trainer.step import build_tap_grads

                self._tap_grads = build_tap_grads(self.topology, taps)

    def _default_feeder(self, feeding, seq_buckets=None):
        dl = self.topology.data_layers()
        types = {}
        for name, node in dl.items():
            from paddle_tpu.layers.data_type import DataKind, InputType

            types[name] = InputType(
                dim=node.attrs["dim"],
                seq_type=node.attrs.get("seq_type", 0),
                kind=node.attrs.get("data_type", DataKind.DENSE),
            )
        if seq_buckets is None:
            seq_buckets = parse_seq_buckets(flags.get("seq_buckets"))
        return DataFeeder(types, feeding, seq_buckets=seq_buckets)

    # -- the v2 train loop ----------------------------------------------------
    def train(self, reader, num_passes: int = 1,
              event_handler: Callable | None = None, feeding=None,
              checkpoint_dir: str | None = None, checkpoint_period: int = 1,
              resume: bool = True, checkpoint_async: bool = False,
              metrics_registry=None, sync_period: int | None = None,
              prefetch: int | None = None, nan_policy: str | None = None,
              checkpoint_batch_period: int | None = None,
              checkpoint_keep: int | None = None, elastic=None,
              seq_buckets=None):
        """reader yields BATCHES (lists of sample tuples), i.e. the output of
        ``paddle.batch(...)`` exactly as in v2.

        Input overlap (``reader/prefetch.py``): with ``prefetch`` > 0
        (default: the ``prefetch_depth`` flag, 0 — synchronous, matching
        v2; the CLI defaults to ``--prefetch=2``) a worker thread runs
        ``DataFeeder.feed`` + ``mesh.shard_batch`` ahead of the step loop,
        keeping up to ``prefetch`` device-resident feeds staged; 0 keeps
        everything on the consumer thread with no read-ahead (feed
        conversion then happens when the batch is pulled, just before
        that batch's ``BeginIteration``).  The training trajectory is
        bit-identical either way (same batches, same RNG key order) — but
        with ``prefetch`` > 0 the READER is consumed up to ``prefetch``
        batches ahead on a worker thread, so a reader that must run in
        lockstep with the event stream (e.g. curriculum state mutated by
        the event handler) or is not thread-safe should stay at 0.
        Host-fed workloads should opt in (``prefetch=2`` or
        ``PADDLE_TPU_PREFETCH_DEPTH=2``) — it is the structural fix for
        the device idling through every Python-side feed conversion.

        ``sync_period`` (default: the ``sync_period`` flag, 1) defers the
        per-step device fence: costs/metrics stay device arrays and are
        fetched with ONE ``jax.device_get`` every N steps, so the host
        keeps dispatching while the device computes.  ``EndIteration``
        events still carry real floats but arrive in bursts of N (and a
        batch's ``BeginIteration`` may precede the PREVIOUS batch's
        ``EndIteration``); 1 keeps exact v2 per-batch event cadence.
        Host-side evaluators / gradient taps force an effective period
        of 1 — they fence every batch anyway.

        ``checkpoint_dir`` enables full crash-safe checkpoints (parameters +
        optimizer slots + states + a ``(pass, batch)`` cursor + the RNG
        stream, uuid/sha manifest — see ``trainer/checkpoint.py``); with
        ``resume`` the newest VALID one is loaded (corrupt ones are
        skipped) and training continues from the cursor — for a mid-pass
        cursor the reader is fast-forwarded to the exact batch boundary
        and the restored RNG stream makes the replayed trajectory
        bit-identical to an uninterrupted run.
        ``checkpoint_batch_period`` (default: the flag, 0 = off)
        additionally checkpoints every N batches mid-pass, bounding lost
        work to N batches instead of a whole pass.
        ``checkpoint_async`` moves the disk write off the step loop
        (``AsyncCheckpointer``: host snapshot taken synchronously, npz +
        manifest written by a worker thread; the preemption save stays
        synchronous).

        ``nan_policy`` (default: the ``nan_policy`` flag, "none") arms
        the numeric guard (``resilience/guard.py``): "skip" discards a
        non-finite batch's update and keeps training; "rollback"
        restores the newest valid checkpoint and re-enters at a reduced
        step size for a rescue window.  Either policy fences every batch
        (effective ``sync_period=1``) and keeps a one-batch device-side
        state snapshot while armed.  With the ``heartbeat_stale_s`` flag
        set, a watchdog thread dumps the flight ring and fails fast when
        this host's train-loop heartbeat goes stale — a hung collective
        becomes a diagnosable crash instead of a silent barrier wait.

        Telemetry (see ``paddle_tpu/metrics.py``): one structured record
        per step — {step, loss, step_ms, examples_per_sec, tokens_per_sec,
        mfu_pct, hbm_gbps, comm_bytes, metrics} — flows through
        ``metrics_registry`` (default: the process-global registry, JSONL
        sink attachable via ``--metrics_jsonl``/``PADDLE_TPU_METRICS_JSONL``
        or ``metrics.configure``).  Every record also lands in the
        multihost flight recorder, whose ring buffer is dumped to disk on
        exception or SIGTERM (``distributed/multihost.py``).

        ``elastic`` (an :class:`~paddle_tpu.resilience.elastic.
        ElasticCoordinator`) arms live resharding: membership events
        (host loss, scale-up) queued on the coordinator are consumed at
        batch boundaries — the deferred-fence backlog is drained, a
        cursor checkpoint marks the boundary, the mesh is rebuilt at the
        new data-parallel degree, and params/optimizer state are
        re-placed from the live shards (or restored from the newest
        cursor checkpoint when a shard is unrecoverable, replaying from
        its cursor) — all without leaving this call."""
        from paddle_tpu import metrics as metrics_mod
        from paddle_tpu.distributed import multihost as mh
        from paddle_tpu.telemetry import StepTelemetry
        from paddle_tpu.telemetry import introspect as introspect_mod
        from paddle_tpu.telemetry import tracing as tracing_mod

        if sync_period is None:
            sync_period = flags.get("sync_period")
        if prefetch is None:
            prefetch = flags.get("prefetch_depth")
        if nan_policy is None:
            nan_policy = flags.get("nan_policy")
        if checkpoint_batch_period is None:
            checkpoint_batch_period = flags.get("checkpoint_batch_period")
        if checkpoint_keep is None:
            checkpoint_keep = flags.get("checkpoint_keep")
        if event_handler is None:
            event_handler = _default_event_handler
        metrics_mod.configure_from_flags(metrics_registry)
        # the cost cache lives on the SGD (same lifetime as _train_step):
        # a second train() on this trainer hits the jit trace cache, so
        # re-lowering would yield empty comm captures — reuse instead
        self._telemetry = StepTelemetry(
            registry=metrics_registry, run="train",
            flight=mh.flight_recorder(),
            cost_cache=self._telemetry_costs)
        # span tracing (--trace_spans): flag-on arms the global tracer;
        # a tracer a test already enabled stays enabled (never disarmed
        # here).  With tracing off, every span call site below resolves
        # to a shared no-op — the bit-identical-trajectory guarantee.
        if flags.get("trace_spans"):
            tracing_mod.configure_tracing(enabled=True)
        # goodput ledger (--goodput_ledger): a fold over the span ring,
        # so arming it arms tracing too.  Started before the build so
        # pre-step-0 wall (build, placement) lands in "idle" instead of
        # silently missing from the account.
        self._goodput_ledger = None
        if flags.get("goodput_ledger"):
            from paddle_tpu.telemetry import goodput as goodput_mod

            tracing_mod.configure_tracing(enabled=True)
            self._goodput_ledger = goodput_mod.GoodputLedger(
                registry=self._telemetry.registry).start()
        prev_debug_nans = jax.config.jax_debug_nans
        if flags.get("debug_nans"):
            # the documented jax nan-checking traps at the originating op;
            # the finite-cost check below remains as a cheap backstop
            jax.config.update("jax_debug_nans", True)
        self._ensure_built()
        # seq_buckets (None = the reader's own table, then the flag):
        # length-quantization table for the feeder's sequence slots —
        # it must be the SAME table the reader's bucket_by_length stage
        # used so every bucket is one jit signature.  bucket_by_length
        # readers (the dataset bucketed_batches helpers) carry theirs as
        # reader.seq_buckets, so bucketed input pads to bucket ceilings
        # by default, no repeated knob.
        if seq_buckets is None:
            seq_buckets = getattr(reader, "seq_buckets", None)
        feeder = self._default_feeder(feeding, seq_buckets)
        params = self.mesh.replicate(self._params_dict())
        states = self.mesh.replicate(self.states)
        if self._opt_state is None:
            opt_state = self.optimizer.init(
                {k: params[k] for k in self._trainable}, self._specs
            )
            opt_state = self._place_opt_state(opt_state)
        else:
            opt_state = self._opt_state

        # preemption handling (SURVEY §5/§7.8): on SIGTERM (the TPU-pod
        # eviction signal) the flight ring is dumped ALWAYS; with a
        # checkpoint_dir the run additionally finishes the current batch,
        # checkpoints, and exits — resume picks up from the saved pass.
        # Without one, the pre-train disposition is re-delivered after
        # the dump (the process still dies, but the post-mortem exists).
        preempted = {"flag": False}
        prev = {"handler": None, "installed": False}
        import signal

        def _on_sigterm(signum, frame):
            mh.flight_recorder().dump(reason="SIGTERM")
            if checkpoint_dir:
                preempted["flag"] = True
                log.info("SIGTERM received: checkpointing at the next "
                         "batch boundary")
                return
            mh.chain_signal(signum, frame, prev["handler"])

        try:
            prev["handler"] = signal.signal(signal.SIGTERM, _on_sigterm)
            prev["installed"] = True
        except ValueError:  # non-main thread: no handler, no preemption
            pass

        # heartbeat-staleness watchdog (multihost hang -> fail-fast dump):
        # the train loop heartbeats every batch; a stall past the flag's
        # threshold dumps the flight ring and interrupts the main thread
        watchdog = None
        stale_s = float(flags.get("heartbeat_stale_s") or 0.0)
        if stale_s > 0:
            watchdog = mh.HeartbeatWatchdog(recorder=mh.flight_recorder(),
                                            stale_after_s=stale_s)
            watchdog.start()

        if elastic is not None:
            elastic.bind(self, checkpoint_dir)
        # live introspection (--status_port / PADDLE_TPU_STATUS_PORT):
        # /metrics /healthz /snapshot /trace served for the duration of
        # this train() call — the flight ring becomes inspectable
        # BEFORE a crash, not only in its post-mortem dump.  Started
        # HERE, immediately before the try whose finally stops it: a
        # build failure above must not leak a bound port into a
        # supervisor-retried train() (EADDRINUSE on the retry).
        status_server = introspect_mod.server_from_flags(
            registry=self._telemetry.registry,
            flight=mh.flight_recorder())
        try:
            self._train_loop(reader, num_passes, event_handler, feeder,
                             params, states, opt_state, checkpoint_dir,
                             checkpoint_period, resume, preempted,
                             checkpoint_async=checkpoint_async,
                             sync_period=sync_period, prefetch=prefetch,
                             nan_policy=nan_policy,
                             checkpoint_batch_period=checkpoint_batch_period,
                             checkpoint_keep=checkpoint_keep,
                             elastic=elastic)
        finally:
            jax.config.update("jax_debug_nans", prev_debug_nans)
            if watchdog is not None:
                watchdog.stop()
            profile_window = getattr(self, "_profile_window", None)
            if profile_window is not None:
                # a run shorter than the window's B (or an abort inside
                # it) still stops the device trace and emits the record
                profile_window.close()
                self._profile_window = None
            ledger = getattr(self, "_goodput_ledger", None)
            if ledger is not None and ledger.started:
                # close the wall-clock account (idle absorbs whatever
                # no span covered) and emit the ledger record BEFORE
                # the status server stops, so a last /healthz scrape
                # sees the final goodput_fraction
                ledger_dir = flags.get("ledger_dir")
                ledger.finish(path=os.path.join(ledger_dir, "ledger.jsonl")
                              if ledger_dir else None)
                self._goodput_ledger = None
            if status_server is not None:
                status_server.stop()
            trace_dir = flags.get("trace_dir")
            if trace_dir and tracing_mod.get_tracer().enabled:
                # the per-rank Chrome trace tools/trace_merge.py folds
                # into one fleet timeline (same host-index stamp as the
                # flight dump, so lanes line up across artifacts)
                from paddle_tpu.telemetry import host_index

                tracing_mod.get_tracer().dump(os.path.join(
                    trace_dir, f"trace-host{host_index()}.json"))
            if prev["installed"] and prev["handler"] is not None:
                signal.signal(signal.SIGTERM, prev["handler"])

    def _restore_checkpoint_state(self, found, opt_state_template,
                                  states_fallback):
        """(path, manifest) -> (params, opt_state, states) replicated,
        with ``self.parameters`` updated and the RNG stream restored to
        the manifest's — shared by startup resume and the numeric
        guard's rollback path.  The restore wall time lands in the
        ``checkpoint_restore_ms`` gauge (the recovery-time observable)."""
        from paddle_tpu.distributed import multihost as mh
        from paddle_tpu.telemetry.tracing import get_tracer
        from paddle_tpu.trainer.checkpoint import load_checkpoint

        path, manifest = found
        t0 = _time.perf_counter()
        # tracer-clock twin of t0 for the retrospective "restore" span
        # below (the goodput ledger's checkpoint_restore bucket) — same
        # measurement window, the tracer's timeline
        tracer = get_tracer()
        tk0 = tracer.clock() if tracer.enabled else 0.0
        # heartbeat-free phases look like hangs to the staleness
        # watchdog; mark the restore so a slow load stays a sign of life
        mh.flight_recorder().heartbeat("restore", path=path)
        cp, copt, cstates, _ = load_checkpoint(
            path, opt_state_template=opt_state_template)
        for name, arr in cp.items():
            if name in self.parameters:
                self.parameters[name] = arr
        params = self.mesh.replicate(self._params_dict())
        opt_state = (self._place_opt_state(copt) if copt is not None
                     else opt_state_template)
        if cstates:
            # restore each state at its template dtype (bf16/f8
            # states were stored f32 by the npz layer)
            tmpl = self.states
            states = self.mesh.replicate({
                k: jax.numpy.asarray(
                    v, dtype=getattr(tmpl.get(k), "dtype", None))
                for k, v in cstates.items()})
        else:
            states = states_fallback
        if manifest.get("meta", {}).get("rng") is not None:
            rng.set_state(np.asarray(manifest["meta"]["rng"],
                                     dtype=np.uint32))
        mh.flight_recorder().heartbeat("restored", path=path)
        if tracer.enabled:
            tracer.add_span("restore", tk0, tracer.clock(), cat="trainer",
                            path=path)
        if self._telemetry is not None:
            self._telemetry.registry.gauge(
                "checkpoint_restore_ms",
                "wall ms to restore the newest checkpoint").set(
                (_time.perf_counter() - t0) * 1e3)
        return params, opt_state, states

    def _train_loop(self, reader, num_passes, event_handler, feeder,
                    params, states, opt_state, checkpoint_dir,
                    checkpoint_period, resume, preempted,
                    checkpoint_async=False, sync_period=1, prefetch=0,
                    nan_policy="none", checkpoint_batch_period=0,
                    checkpoint_keep=3, elastic=None):
        from paddle_tpu.trainer import checkpoint as ckpt

        writer = ckpt.AsyncCheckpointer() if (
            checkpoint_async and checkpoint_dir) else None

        start_pass = flags.get("start_pass")
        start_batch = 0
        if checkpoint_dir and resume:
            found = ckpt.latest_checkpoint(checkpoint_dir)
            if found is not None:
                path, manifest = found
                params, opt_state, states = self._restore_checkpoint_state(
                    found, opt_state, states)
                cursor = manifest.get("cursor")
                if cursor is not None:
                    # resume at the exact batch boundary the manifest
                    # recorded; an explicitly higher --start_pass wins
                    # (and starts that pass from its first batch)
                    if cursor["pass_id"] > start_pass:
                        start_pass = cursor["pass_id"]
                        start_batch = int(cursor.get("batch_id", 0))
                    elif cursor["pass_id"] == start_pass:
                        start_batch = int(cursor.get("batch_id", 0))
                else:  # pre-cursor manifests: continue with the next pass
                    start_pass = max(start_pass, manifest["pass_id"] + 1)
                log.info("resumed from %s (pass %d, next batch %d)", path,
                         start_pass, start_batch)
        try:
            while True:
                try:
                    self._run_passes(
                        start_pass, num_passes, reader, event_handler,
                        feeder, params, states, opt_state,
                        checkpoint_dir, checkpoint_period, preempted,
                        writer, sync_period=sync_period,
                        prefetch=prefetch, start_batch=start_batch,
                        nan_policy=nan_policy,
                        checkpoint_batch_period=checkpoint_batch_period,
                        checkpoint_keep=checkpoint_keep,
                        elastic=elastic)
                    break
                except _ElasticReplay as r:
                    # checkpoint-fallback elastic rebuild: re-enter the
                    # pass loop at the restored cursor with the re-placed
                    # state — the same replay a supervisor restart would
                    # do, minus the process restart
                    params, opt_state, states = (r.params, r.opt_state,
                                                 r.states)
                    start_pass, start_batch = r.pass_id, r.batch_id
                    log.info("elastic: replaying from pass %d batch %d "
                             "at the new mesh degree", start_pass,
                             start_batch)
        except BaseException as e:
            # post-mortem: the flight ring (last N step records +
            # heartbeats) goes to disk so pod hangs/desyncs are
            # diagnosable after the process is gone; dump() never raises
            from paddle_tpu.distributed import multihost as mh

            path = mh.flight_recorder().dump(
                reason=f"{type(e).__name__}: {e}"[:200])
            if path:
                log.info("flight recorder dumped to %s", path)
            raise
        finally:
            if writer is not None:
                import sys

                if sys.exc_info()[0] is None:
                    writer.wait()  # surface deferred write errors; flush
                else:
                    # a training exception is already propagating — don't
                    # let a checkpoint IO error supersede it
                    try:
                        writer.wait()
                    except Exception as e:
                        log.warning(
                            "async checkpoint write failed during "
                            "abort: %s", e)

    def _run_passes(self, start_pass, num_passes, reader, event_handler,
                    feeder, params, states, opt_state, checkpoint_dir,
                    checkpoint_period, preempted, writer,
                    sync_period=1, prefetch=0, start_batch=0,
                    nan_policy="none", checkpoint_batch_period=0,
                    checkpoint_keep=3, elastic=None):
        from paddle_tpu.reader.prefetch import (
            DevicePrefetcher,
            SynchronousFeeds,
            skip_feed_batches,
        )
        from paddle_tpu.telemetry import tokens_in_feed
        from paddle_tpu.trainer import checkpoint as ckpt

        sync_period = max(int(sync_period or 1), 1)
        prefetch = max(int(prefetch or 0), 0)
        checkpoint_batch_period = max(int(checkpoint_batch_period or 0), 0)
        remainder = flags.get("batch_remainder")
        # host-side evaluators / gradient taps read concrete layer values
        # every batch, i.e. they fence anyway — deferring the cost fence
        # around them would only reorder events for zero overlap
        if sync_period > 1 and (self.declared_evaluators
                                or self._tap_grads is not None):
            log.info("sync_period=%d requested, but host-side evaluators/"
                     "grad taps fence every batch; using sync_period=1",
                     sync_period)
            sync_period = 1
        telem = self._telemetry
        # phase spans (tracing.py; no-ops when --trace_spans is off) +
        # the --profile_steps windowed device capture, keyed by the
        # DISPATCH step counter (fence-time counters lag under deferred
        # fencing, so the window brackets what actually runs)
        from paddle_tpu.telemetry import tracing as tracing_mod

        tracer = tracing_mod.get_tracer()
        prev_window = getattr(self, "_profile_window", None)
        if prev_window is not None:
            # an elastic replay re-enters _run_passes: a window the
            # aborted entry left open must stop its device trace first
            prev_window.close()
        profile = self._profile_window = tracing_mod.ProfileWindow(
            flags.get("profile_steps"),
            trace_dir=flags.get("profile_dir") or None,
            registry=telem.registry if telem is not None else None,
            tracer=tracer)
        dispatched = {"n": 0}
        # the staleness watchdog reads the global flight ring, so the
        # loop must heartbeat even with telemetry inactive (a ring
        # append — cheap enough to pay unconditionally)
        from paddle_tpu.distributed import multihost as mh

        flight = telem.flight if (telem is not None and
                                  telem.flight is not None) \
            else mh.flight_recorder()

        guard = None
        if nan_policy and nan_policy != "none":
            from paddle_tpu.resilience.guard import NumericGuard

            guard = NumericGuard(
                policy=nan_policy,
                max_consecutive=flags.get("guard_max_consecutive"),
                rescue_batches=flags.get("guard_rescue_batches"),
                rescue_scale=flags.get("guard_rescue_scale"),
                registry=telem.registry if telem is not None else None,
                flight=telem.flight if telem is not None else None)
            if sync_period > 1:
                # the non-finite check must observe each cost before the
                # NEXT step is dispatched, or poisoned parameters spread
                # through the whole deferred window
                log.info("nan_policy=%r fences every batch; using "
                         "sync_period=1", nan_policy)
                sync_period = 1

        def restore_fn_for(opt_template, states_now):
            """Rollback loader for the guard: newest valid checkpoint ->
            replicated state tuple, or None when none exists yet."""
            def restore():
                found = ckpt.latest_checkpoint(checkpoint_dir)
                if found is None:
                    return None
                return self._restore_checkpoint_state(
                    found, opt_template, states_now)

            return restore if checkpoint_dir else (lambda: None)

        def cursor_meta(batches_done, extra=None):
            """Manifest meta for a mid-pass cursor checkpoint: the RNG
            stream (bit-identical replay) + the reader/prefetch cursor
            state resume needs to fast-forward to the same boundary."""
            meta = {
                "completed_pass": False,
                "rng": rng.get_state().tolist(),
                "reader_cursor": {
                    "batches_consumed": batches_done,
                    "shard_index": jax.process_index(),
                    "shard_count": jax.process_count(),
                },
                # staged prefetch feeds are read-ahead only — they are
                # discarded on death and re-derived from the reader on
                # resume, so "drained" is the only state to record
                "prefetch": {"depth": prefetch,
                             "staged_discarded_on_resume": True},
            }
            meta.update(extra or {})
            return meta

        for pass_id in range(start_pass, num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            batch_costs, batch_metrics = [], []
            if self.declared_evaluators:
                self.declared_evaluators.start()

            # steps dispatched but not yet fenced (device arrays for
            # cost/metrics); flushed every sync_period steps with ONE
            # jax.device_get of the whole backlog
            pending: list[dict] = []
            window = {"t0": _time.perf_counter()}

            def flush_pending():
                if not pending:
                    return
                # the deferred-fence drain: nested under the current
                # step span when a batch triggered it, top-level for
                # the end-of-pass / elastic-drain backlog flushes
                tk_fence = tracer.begin("fence", cat="trainer",
                                        steps=len(pending))
                t_f0 = _time.perf_counter()
                host_vals = jax.device_get(
                    [(p["cost"], p["metrics"]) for p in pending])
                t_f1 = _time.perf_counter()
                stall_ms = (t_f1 - t_f0) * 1e3 / len(pending)
                # per-step time: with per-step fencing, dispatch+fence —
                # the seed's device-bounded step_ms.  Under deferred
                # fencing the device time of ONE step is unobservable
                # (that is the point), so step_ms becomes the amortized
                # WALL time per step over the window (input wait
                # included) — the honest throughput number; derived
                # rates (ex/s, MFU%) then measure achieved throughput
                # rather than an inflated dispatch-only figure
                amort_ms = (t_f1 - window["t0"]) * 1e3 / len(pending)
                for p, (cost_h, metrics_h) in zip(pending, host_vals):
                    cost_f = float(cost_h)
                    if not np.isfinite(cost_f) and flags.get("debug_nans"):
                        # ≅ the reference's feenableexcept FP trapping
                        # (TrainerMain.cpp:49): stop at the poisoned batch
                        raise FloatingPointError(
                            f"non-finite cost {cost_f} at pass "
                            f"{p['pass_id']} batch {p['batch_id']} "
                            f"(flags.debug_nans)")
                    metrics_f = {k: float(v) for k, v in metrics_h.items()}
                    batch_costs.append(cost_f)
                    batch_metrics.append(metrics_f)
                    if telem is not None:
                        telem.record_step(
                            loss=cost_f,
                            step_ms=(p["dispatch_ms"] + stall_ms
                                     if sync_period == 1 else amort_ms),
                            examples=p["examples"], tokens=p["tokens"],
                            flops=p["flops"], bytes_accessed=p["bytes"],
                            pass_id=p["pass_id"], batch_id=p["batch_id"],
                            metrics=metrics_f, comm=p["comm"],
                            input_wait_ms=p["wait_ms"],
                            host_stall_ms=stall_ms,
                            padding_ratio=(p["padded_ts"] / p["total_ts"]
                                           if p["total_ts"] else None))
                    event_handler(v2_event.EndIteration(
                        p["pass_id"], p["batch_id"], cost_f, metrics_f,
                        self))
                pending.clear()
                tracer.end(tk_fence)
                ledger = getattr(self, "_goodput_ledger", None)
                if ledger is not None:
                    # the flush cadence is the ledger's fold cadence:
                    # frequent enough that the span ring can't wrap a
                    # whole fold interval on any realistic run
                    ledger.fold()
                window["t0"] = _time.perf_counter()

            # mid-pass resume: fast-forward the reader past the batches
            # the checkpoint already applied (no feed conversion, no
            # device placement, no RNG keys consumed — the manifest's
            # restored stream stays aligned with the replayed batches)
            skip = start_batch if pass_id == start_pass else 0
            if skip:
                log.info("pass %d: fast-forwarding the reader past %d "
                         "already-applied batches", pass_id, skip)
                pass_reader = skip_feed_batches(
                    reader, skip, replicas=self.mesh.num_replicas,
                    remainder=remainder,
                    heartbeat=lambda i: flight.heartbeat(
                        "fast_forward", pass_id=pass_id, batch_id=i))
            else:
                pass_reader = reader
            # the unmodified v2 configuration (no prefetch, strict
            # remainder) keeps the SEED's exact event order — batch pull,
            # BeginIteration, THEN feed conversion, so a handler may still
            # mutate feeder/curriculum state for the CURRENT batch; any
            # opt-in overlap/remainder feature converts before the event
            v2_order = prefetch == 0 and remainder == "error"
            if prefetch > 0:
                feeds = DevicePrefetcher(pass_reader, feeder, self.mesh,
                                         depth=prefetch,
                                         remainder=remainder)
            elif not v2_order:
                feeds = SynchronousFeeds(pass_reader, feeder, self.mesh,
                                         remainder=remainder)
            else:
                feeds = None
                raw_it = iter(pass_reader())
            pass_complete = False

            def maybe_cursor_checkpoint():
                # mid-pass cursor checkpoint: bounds lost work to
                # checkpoint_batch_period batches; resume replays from
                # this exact boundary.  The carried arrays already
                # include every dispatched step, so no fence beyond the
                # save's own host copy is needed.  Called on BOTH the
                # finite path and the guard's skip path — a NaN landing
                # on a period boundary must not stretch the bound to 2N
                if not (checkpoint_dir and checkpoint_batch_period
                        and batch_id > skip
                        and batch_id % checkpoint_batch_period == 0):
                    return
                flight.heartbeat("checkpoint", pass_id=pass_id,
                                 batch_id=batch_id)
                save = (ckpt.save_checkpoint if writer is None
                        else writer.save)
                with tracer.span("checkpoint", cat="trainer",
                                 pass_id=pass_id, batch_id=batch_id):
                    save(checkpoint_dir, pass_id,
                         {n: np.asarray(params[n]) for n in params},
                         opt_state=opt_state, states=dict(states),
                         keep_last=checkpoint_keep, batch_id=batch_id,
                         meta=cursor_meta(batch_id))

            def drain_checkpoint(host_params, host_opt, host_states):
                # elastic drain boundary: persist the exact state the
                # rebuild re-places, so (a) a crash mid-reshard resumes
                # here and (b) a fresh run at the new degree resuming
                # from this cursor replays the identical trajectory —
                # the bit-identity anchor the elastic tests assert
                if writer is not None:
                    try:  # a stale deferred write error must not mask
                        writer.wait()  # the drain save
                    except Exception as e:
                        log.warning("async checkpoint write had failed "
                                    "(%s); writing the elastic drain "
                                    "checkpoint synchronously", e)
                flight.heartbeat("checkpoint", pass_id=pass_id,
                                 batch_id=batch_id)
                with tracer.span("drain", cat="elastic",
                                 pass_id=pass_id, batch_id=batch_id):
                    ckpt.save_checkpoint(
                        checkpoint_dir, pass_id,
                        {n: np.asarray(v)
                         for n, v in host_params.items()},
                        opt_state=host_opt, states=dict(host_states),
                        keep_last=checkpoint_keep, batch_id=batch_id,
                        meta=cursor_meta(batch_id,
                                         {"elastic_drain": True}))

            def maybe_elastic():
                # elastic drain point (once per batch boundary): consume
                # pending membership events — flush the deferred-fence
                # backlog first so every dispatched step retires on the
                # old mesh, then rebuild and re-place.  The feed
                # pipeline is re-bound to the new mesh (staged prefetch
                # feeds are re-placed, not dropped: no reader batch is
                # lost or replayed on the live path).
                nonlocal params, opt_state, states
                if elastic is None or not elastic.pending():
                    return
                flush_pending()
                while elastic.pending():
                    out = elastic.apply(
                        self, params, opt_state, states, pass_id,
                        batch_id,
                        drain_checkpoint=(drain_checkpoint
                                          if checkpoint_dir else None))
                    if out is None:
                        break
                    params, opt_state, states = (out.params,
                                                 out.opt_state,
                                                 out.states)
                    if feeds is not None:
                        feeds.rebind_mesh(self.mesh)
                    if out.replay_cursor is not None:
                        raise _ElasticReplay(
                            int(out.replay_cursor["pass_id"]),
                            int(out.replay_cursor.get("batch_id", 0)),
                            params, opt_state, states)

            tk_step = None
            try:
                batch_id = skip
                feed_it = iter(feeds) if feeds is not None else None
                while True:
                    # one "step" span per batch, with feed / compute /
                    # fence / checkpoint / guard_rescue children — the
                    # timeline the /trace endpoint and trace_merge
                    # render.  Both tokens are canceled (not recorded)
                    # when the pull turns out to be the end-of-pass
                    # sentinel.
                    tk_step = tracer.begin("step", cat="trainer",
                                           pass_id=pass_id,
                                           batch_id=batch_id)
                    tk_feed = tracer.begin("feed", cat="trainer")
                    if v2_order:
                        # input_wait_ms covers the reader pull AND the
                        # conversion — the same accounting as the feed
                        # iterators, so the host-starvation signal doesn't
                        # change meaning with the knobs
                        t_feed0 = _time.perf_counter()
                        try:
                            data_batch = next(raw_it)
                        except StopIteration:
                            tracer.cancel(tk_feed)
                            tracer.cancel(tk_step)
                            pass_complete = True
                            break
                        event_handler(v2_event.BeginIteration(pass_id,
                                                              batch_id))
                        with stat.timer("feed"):
                            feed = feeder(data_batch)
                            padded_ts, total_ts = feeder_mod.padding_stats(
                                feed)
                            feed = self.mesh.shard_batch(feed)
                        wait_ms = (_time.perf_counter() - t_feed0) * 1e3
                        examples = len(data_batch)
                    else:
                        with stat.timer("feed"):
                            try:
                                fb = next(feed_it)
                            except StopIteration:
                                tracer.cancel(tk_feed)
                                tracer.cancel(tk_step)
                                pass_complete = True
                                break
                            examples, feed, wait_ms = (
                                fb.examples, fb.feed, fb.input_wait_ms)
                            padded_ts, total_ts = (fb.padded_timesteps,
                                                   fb.total_timesteps)
                        event_handler(v2_event.BeginIteration(pass_id,
                                                              batch_id))
                    tracer.end(tk_feed)
                    sig = _feed_signature(feed)
                    new_sig = sig not in self._compiled_sigs
                    if new_sig:
                        self._compiled_sigs.add(sig)
                        if len(self._compiled_sigs) > 1:
                            log.info("train step: compiling new feed "
                                     "signature %s", sig)
                    step_key = rng.next_key()
                    if telem is not None and telem.registry.active:
                        # FLOPs/bytes/comm of THIS signature's program
                        # (cached; lower() only traces — the live args are
                        # not read)
                        step_flops, step_bytes, step_comm = telem.cost_for(
                            sig, lambda: self._train_step.lower(
                                params, opt_state, states, feed, step_key))
                    else:
                        step_flops, step_bytes, step_comm = 0.0, 0.0, {}
                    if self._tap_grads is not None:
                        # same key as the step: the printed d(cost)/d(layer)
                        # corresponds to the exact update being taken
                        tap_grads = self._tap_grads(params, states, feed,
                                                    step_key)
                    else:
                        tap_grads = None
                    # pre-step heartbeat: a hang inside the step leaves
                    # "begin_batch" as this host's last sign of life.
                    # pass/batch ids are stamped explicitly — under
                    # deferred fencing global_step lags dispatch by up
                    # to sync_period-1 steps (it advances at fence
                    # time), so step alone would misattribute a hang
                    flight.heartbeat(
                        "begin_batch",
                        step=telem.global_step if telem is not None else -1,
                        pass_id=pass_id, batch_id=batch_id)
                    if guard is not None:
                        # the jitted step donates its inputs; these
                        # copies are the only way to undo the update
                        prev_snap = guard.snapshot(params, opt_state,
                                                   states)
                    if new_sig:
                        # must be the NEWEST beat when the step call
                        # below triggers XLA compilation: the staleness
                        # watchdog grants a "compiling" tag its own
                        # (long) grace window — compiles are minutes of
                        # legitimate heartbeat silence
                        flight.heartbeat("compiling", pass_id=pass_id,
                                         batch_id=batch_id)
                    n_disp = dispatched["n"]
                    profile.maybe_start(n_disp)
                    t_step0 = _time.perf_counter()
                    with stat.timer("forwardBackward+update"):
                        # compile=True marks the dispatch that built a
                        # new executable — the goodput ledger books the
                        # whole span as "recompile", not "compute"
                        tk_compute = tracer.begin("compute", cat="trainer",
                                                  compile=new_sig)
                        with profile.annotation(n_disp):
                            params, opt_state, states, cost, metrics = \
                                self._train_step(params, opt_state,
                                                 states, feed, step_key)
                        tracer.end(tk_compute)
                    dispatched["n"] = n_disp + 1
                    profile.maybe_stop(n_disp + 1, fence=cost)
                    if guard is not None:
                        cost_now = float(jax.device_get(cost))
                        if not np.isfinite(cost_now):
                            with tracer.span("guard_rescue", cat="trainer",
                                             policy=nan_policy):
                                params, opt_state, states = \
                                    guard.handle_nonfinite(
                                        cost_now, pass_id, batch_id,
                                        prev_snap,
                                        restore_fn_for(prev_snap[1],
                                                       prev_snap[2]))
                            # the poisoned update never happened: no
                            # events, no step record — but the batch and
                            # its RNG key stay consumed, so a later
                            # kill-and-resume replays this exact skip
                            batch_id += 1
                            if preempted["flag"]:
                                flush_pending()
                                tracer.end(tk_step)
                                break
                            maybe_cursor_checkpoint()
                            maybe_elastic()
                            tracer.end(tk_step)
                            continue
                        params = guard.after_finite_step(prev_snap[0],
                                                         params)
                    if self.declared_evaluators or tap_grads is not None:
                        # host-side evaluators read device values right
                        # below, which would absorb the device wait
                        # OUTSIDE both timers; fence here (a readback,
                        # the only fence the tunnel honors) so step_ms
                        # stays device-bounded exactly like the seed's
                        # float(cost)
                        jax.device_get(cost)
                    dispatch_ms = (_time.perf_counter() - t_step0) * 1e3
                    if self.declared_evaluators:
                        # layer values ride along in the metrics dict from
                        # the SAME forward the update used (fetch_layers) —
                        # no second pass
                        layer_vals = {
                            k[len("layer:"):]: v for k, v in metrics.items()
                            if k.startswith("layer:")}
                        self.declared_evaluators.eval_batch(
                            layer_vals, grads=tap_grads, feed=feed)
                    metrics = {k: v for k, v in metrics.items()
                               if not k.startswith("layer:")}
                    event_handler(v2_event.EndForwardBackward(
                        pass_id, batch_id, self))
                    pending.append({
                        "pass_id": pass_id, "batch_id": batch_id,
                        "cost": cost, "metrics": metrics,
                        "examples": examples,
                        "tokens": tokens_in_feed(feed),
                        "flops": step_flops, "bytes": step_bytes,
                        "comm": step_comm, "wait_ms": wait_ms,
                        "dispatch_ms": dispatch_ms,
                        "padded_ts": padded_ts, "total_ts": total_ts,
                    })
                    batch_id += 1
                    if len(pending) >= sync_period or preempted["flag"]:
                        flush_pending()
                    if preempted["flag"]:
                        tracer.end(tk_step)
                        break
                    maybe_cursor_checkpoint()
                    maybe_elastic()
                    tracer.end(tk_step)
                flush_pending()  # end-of-pass backlog
            finally:
                # an exception mid-batch (elastic replay, a supervisor-
                # retryable fault) must not leave the in-flight step
                # token on this thread's span stack, or every span of
                # the NEXT attempt would be mis-parented under it —
                # cancel truncates the stack from the token up
                # (idempotent for a cleanly ended one)
                tracer.cancel(tk_step)
                # preemption-drain / early exit: stop the prefetch worker
                # and drop staged feeds, so the checkpoint below sits on a
                # consistent batch boundary and no thread leaks
                if feeds is not None:
                    feeds.close()
            # write back for checkpoint/event access
            self.parameters.update_from(params)
            self.states = dict(states)
            self._opt_state = opt_state
            if preempted["flag"] and not pass_complete:
                # mid-pass eviction: checkpoint the partial pass with its
                # (pass, batch) cursor — no EndPass fires, the save
                # ignores checkpoint_period, and resume replays THIS pass
                # from the exact batch boundary (bit-identically: the
                # manifest carries the RNG stream and the reader is
                # fast-forwarded past the applied batches).
                if checkpoint_dir:
                    if writer is not None:
                        # eviction save must be durable AND must not be
                        # skipped by a stale deferred write error
                        try:
                            writer.wait()
                        except Exception as e:
                            log.warning("async checkpoint write had "
                                        "failed (%s); writing eviction "
                                        "checkpoint synchronously", e)
                    flight.heartbeat("checkpoint", pass_id=pass_id,
                                     batch_id=batch_id)
                    ckpt.save_checkpoint(
                        checkpoint_dir, pass_id,
                        {n: np.asarray(params[n]) for n in params},
                        opt_state=opt_state, states=dict(states),
                        keep_last=checkpoint_keep, batch_id=batch_id,
                        meta=cursor_meta(batch_id, {"preempted": True}),
                    )
                    log.info("preempted in pass %d: cursor checkpoint "
                             "written; resume replays pass %d from "
                             "batch %d", pass_id, pass_id, batch_id)
                break
            avg_metrics = _mean_dicts(batch_metrics)
            if self.declared_evaluators:
                avg_metrics.update(self.declared_evaluators.finish())
            event_handler(v2_event.EndPass(pass_id, avg_metrics))
            save_dir = flags.get("save_dir")
            if save_dir and (pass_id % max(flags.get("saving_period"), 1) == 0):
                self.save_parameter_to_tar_path(
                    os.path.join(save_dir, f"pass-{pass_id:05d}.tar")
                )
            if checkpoint_dir and (pass_id % max(checkpoint_period, 1) == 0
                                   or preempted["flag"]):
                flight.heartbeat("checkpoint", pass_id=pass_id)
                save = ckpt.save_checkpoint if writer is None else writer.save
                save(
                    checkpoint_dir, pass_id,
                    {n: np.asarray(params[n]) for n in params},
                    opt_state=opt_state, states=dict(states),
                    keep_last=checkpoint_keep,
                    meta={"avg_metrics": avg_metrics,
                          "rng": rng.get_state().tolist()},
                )
            stat.global_stat.print_all_status()
            if preempted["flag"]:
                # SIGTERM landed exactly as the pass finished: the normal
                # end-of-pass checkpoint above is the resume point
                break

    def test(self, reader, feeding=None) -> v2_event.TestResult:
        """≅ SGD.test: forward-only over a reader of batches.  When the
        optimizer keeps a model average (``settings(..., model_average=
        ModelAverage(average_window=...))``), the averaged parameters are
        swapped in for the duration of the test, exactly as the reference's
        ``AverageOptimizer::apply()``/``restore()`` bracket
        (``paddle/parameter/AverageOptimizer.h:63-64``) does around
        ``Trainer::test`` — being functional, nothing needs restoring."""
        self._ensure_built()
        feeder = self._default_feeder(feeding)
        params = self._params_dict()
        avg = self.optimizer.averaged(self._opt_state)
        if avg is not None:
            params.update(avg)
        states = self.states
        costs, metrics_list, n = [], [], 0
        if self.declared_evaluators:
            self.declared_evaluators.start()
        taps = (self.declared_evaluators.grad_tap_layers()
                if self.declared_evaluators else [])
        if taps and self._tap_grads_eval is None:
            from paddle_tpu.trainer.step import build_tap_grads

            # eval-mode forward (dropout off), matching _eval_step's pass;
            # cached: build_tap_grads jits, one compile per topology
            self._tap_grads_eval = build_tap_grads(self.topology, taps,
                                                   is_train=False)
        tap_grads_eval = self._tap_grads_eval
        from paddle_tpu.reader.prefetch import SynchronousFeeds

        # same partial-batch policy as training, so a non-divisible final
        # eval batch doesn't kill a multi-device run ("drop" keeps metrics
        # exact and skips fully-dropped batches; "pad" over-weights the
        # last sample)
        for fb in SynchronousFeeds(
                reader, feeder, self.mesh,
                remainder=flags.get("batch_remainder")):
            feed = fb.feed
            values, cost, metrics = self._eval_step(params, states, feed)
            if self.declared_evaluators:
                grads = None
                if tap_grads_eval is not None:
                    grads = tap_grads_eval(params, states, feed,
                                           jax.random.key(0))
                self.declared_evaluators.eval_batch(values, grads=grads,
                                                    feed=feed)
            costs.append(float(cost))
            metrics_list.append({k: float(v) for k, v in metrics.items()})
            n += 1
        enforce(n > 0, "test reader yielded no batches")
        metrics = _mean_dicts(metrics_list)
        if self.declared_evaluators:
            metrics.update(self.declared_evaluators.finish())
        return v2_event.TestResult(metrics, float(np.mean(costs)))

    def averaged_parameters(self) -> Parameters:
        """A ``Parameters`` copy with the model-averaged values swapped in
        (≅ reading PARAMETER_APPLY after ``AverageOptimizer::apply()``) —
        hand this to ``paddle.infer(parameters=...)`` to run inference on
        the averaged weights.  Falls back to the raw parameters when no
        average is kept."""
        import copy

        out = copy.copy(self.parameters)
        out._values = dict(self.parameters._values)
        avg = self.optimizer.averaged(self._opt_state)
        if avg is not None:
            for name, val in avg.items():
                out._values[name] = jax.numpy.asarray(val)
        return out

    # -- checkpointing (ParamUtil / Parameters.to_tar parity) -----------------
    def save_parameter_to_tar(self, f) -> None:
        self._merge_states_into_parameters()
        self.parameters.to_tar(f)

    def save_parameter_to_tar_path(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            self.save_parameter_to_tar(f)
        log.info("saved checkpoint %s", path)

    def _merge_states_into_parameters(self):
        from paddle_tpu.core import initializer as I
        from paddle_tpu.core.parameters import ParamSpec

        for name, v in self.states.items():
            arr = np.asarray(v)
            if name not in self.parameters:
                self.parameters.add(ParamSpec(
                    name=name, shape=tuple(arr.shape),
                    initializer=I.constant(0.0), is_static=True,
                ))
            self.parameters._values[name] = jax.numpy.asarray(arr)


def _mean_dicts(dicts: list[dict]) -> dict:
    if not dicts:
        return {}
    keys = dicts[0].keys()
    return {k: float(np.mean([d[k] for d in dicts if k in d])) for k in keys}


def _default_event_handler(e) -> None:
    if isinstance(e, v2_event.EndIteration):
        if e.batch_id % flags.get("log_period") == 0:
            log.info(
                "Pass %d, Batch %d, Cost %f, %s", e.pass_id, e.batch_id, e.cost,
                e.metrics,
            )
    elif isinstance(e, v2_event.EndPass):
        log.info("Pass %d done, %s", e.pass_id, e.metrics)
