"""Training events — successor of ``python/paddle/v2/event.py``: objects handed
to the user's event_handler during ``SGD.train`` (BeginPass/EndPass/
BeginIteration/EndIteration/EndForwardBackward, with TestResult)."""

from __future__ import annotations

import dataclasses
from typing import Any


class WithMetric:
    def __init__(self, evaluator):
        self.evaluator = evaluator  # dict metric_name -> value

    @property
    def metrics(self) -> dict:
        return dict(self.evaluator or {})


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass(WithMetric):
    pass_id: int
    evaluator: Any = None

    def __init__(self, pass_id, evaluator=None, gm=None):
        self.pass_id = pass_id
        WithMetric.__init__(self, evaluator)


@dataclasses.dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


class EndForwardBackward:
    def __init__(self, pass_id, batch_id, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.gm = gm


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator=None, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        WithMetric.__init__(self, evaluator)


class TestResult(WithMetric):
    def __init__(self, evaluator=None, cost: float = 0.0):
        self.cost = cost
        WithMetric.__init__(self, evaluator)
