"""Jitted train/eval step builder — the compiled replacement of
``GradientMachine::forwardBackward`` + ``ParameterUpdater::update``.

One XLA program per (topology, optimizer, feed-shape bucket) does: forward,
backward (``jax.grad``), gradient all-reduce over the mesh ``data`` axis
(XLA inserts ICI collectives from the shardings — replacing
``MultiGradientMachine``'s software ring and the pserver round-trip of
``RemoteParameterUpdater``), optimizer update, and metric computation.  The
reference pipelines per-parameter updates with backward via UpdateCallback
(``TrainerInternal.cpp:99-111``); XLA's scheduler provides that overlap.

``zero`` lowers the weight update to the pserver's sharded-aggregation
form in-mesh (``parallel/zero.py``): 1 shards the optimizer state 1/n
over data-parallel ranks; 2 additionally replaces the gradient
all-reduce with reduce-scatter + sharded update + parameter all-gather
(ZeRO-2 / Xu et al.'s automatic weight-update sharding)."""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu import compat
from paddle_tpu.config.topology import Topology
from paddle_tpu.layers.base import is_sequence, raw
from paddle_tpu.parallel.mesh import MeshContext


def _metric_parts(metric_specs, values) -> dict[str, tuple]:
    """Per-metric (numerator, denominator) pairs.  Splitting the ratio
    lets the ZeRO shard_map region psum both sides over the data axis —
    the sharded run's metrics are then EXACT, not a mean of per-shard
    means (which would mis-weight sequence masks)."""
    out = {}
    for kind, pred_name, label_name, tag in metric_specs:
        pred, label = values[pred_name], values[label_name]
        if kind == "classification_error":
            p, l = raw(pred), raw(label)
            if is_sequence(pred):
                mask = pred.mask()
                ids = jnp.argmax(p, axis=-1)
                err = (ids != raw(label)).astype(jnp.float32) * mask
                out["classification_error_evaluator"] = (
                    jnp.sum(err), jnp.sum(mask))
            else:
                ids = jnp.argmax(p, axis=-1)
                err = (ids != l.reshape(ids.shape)).astype(jnp.float32)
                out["classification_error_evaluator"] = (
                    jnp.sum(err), jnp.asarray(float(err.size), jnp.float32))
    return out


def _finalize_metrics(parts: dict[str, tuple]) -> dict[str, jax.Array]:
    return {k: num / jnp.maximum(den, 1.0)
            for k, (num, den) in parts.items()}


def _compute_metrics(metric_specs, values) -> dict[str, jax.Array]:
    return _finalize_metrics(_metric_parts(metric_specs, values))


def _cast_floats(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


def _cast_like(tree, ref):
    return jax.tree.map(
        lambda x, r: x.astype(r.dtype) if hasattr(r, "dtype") else x,
        tree, ref,
    )


def _batch_spec(x) -> P:
    """Batch-dim sharding spec of one feed leaf (mirrors
    ``MeshContext.data_sharding``)."""
    if hasattr(x, "ndim") and x.ndim >= 1:
        return P("data", *([None] * (x.ndim - 1)))
    return P()


def build_train_step(topology: Topology, optimizer,
                     mesh: MeshContext | None = None,
                     compute_dtype=None, fetch_layers=None,
                     zero: int | None = None, lowering: str = "auto"):
    """Returns jitted fn: (params, opt_state, states, feed, key)
    -> (params, opt_state, states, cost, metrics).

    ``compute_dtype=jnp.bfloat16`` enables mixed precision: forward/backward
    run in bf16 on the MXU while master parameters, optimizer state, and
    persistent states stay float32 (grads are upcast before the update).

    ``fetch_layers`` names layers whose batch values should ride along in
    the metrics dict (key ``"layer:<name>"``) — the declared-evaluator feed,
    computed by the SAME forward the update uses (same dropout draw, no
    extra pass).

    ``zero`` selects the weight-update sharding over the mesh ``data``
    axis (``parallel/zero.py``; None/0 = the replicated update):

    - ``1``: optimizer slots live 1/n-sharded (state memory /n); the
      gradient sync stays an all-reduce and updated parameters are
      all-gathered from the sharded deltas.
    - ``2``: the gradient all-reduce is REPLACED by reduce-scatter —
      each rank receives its 1/n gradient shard, applies the optimizer
      on its state shard, and updated parameters are all-gathered.

    On a pure-data mesh the zero>=2 gradient flow is lowered explicitly:
    forward/backward run per-shard inside ``shard_map`` and the sync goes
    through ``collective.reduce_scatter``/``all_gather``, so the
    telemetry census carries the real per-device payloads and the
    compiled program contains literal reduce-scatter ops on every
    backend.  On meshes with live TP/MoE axes the GSPMD lowering
    (sharding constraints, Xu et al.) is used instead — same math,
    partitioner-chosen collectives.  Dropout note: the explicit lowering
    folds the data-axis index into the step key (independent per-replica
    draws, like the reference's per-thread streams), so a stochastic
    model's trajectory differs from the replicated run's by the draw —
    deterministic models match to reduction-order tolerance.

    Mesh-mutability contract (elastic resharding): the returned step
    CAPTURES ``mesh`` and its data degree at build time — the shard_map
    region, the ZeRO specs, the 1/n gradient scale and the donated
    layouts are all frozen into the trace.  A runtime mesh change
    (``resilience/elastic.py``) must therefore discard the step and
    rebuild through this function (``SGD._ensure_built`` after nulling
    ``_train_step``), never re-invoke a stale one: jit would happily
    re-lower the old program onto arrays whose shardings name dead
    devices.  The per-signature cost analyses cached next to the step
    (``SGD._telemetry_costs``) freeze the same mesh and are invalidated
    together."""
    specs = {s.name: s for s in topology.param_specs()}
    trainable = {n for n, s in specs.items() if not s.is_static}
    metric_specs = topology.metrics()
    out_names = [o.name for o in topology.outputs]
    fetch_layers = list(fetch_layers or [])
    zero = int(zero or 0)
    # P() (not None) for unannotated params: a None entry is an empty
    # pytree to jax and would misalign spec lists in parallel/zero.py
    base_specs = {
        n: (P(*s.sharding) if getattr(s, "sharding", None) else P())
        for n, s in specs.items()}

    from paddle_tpu.parallel import zero as zero_mod

    dp = mesh.mesh.shape.get("data", 1) if mesh is not None else 1
    zero_on = zero >= 1 and mesh is not None and dp > 1
    # ``lowering`` pins the ZeRO>=2 gradient-flow lowering: "auto" (the
    # production rule — explicit shard_map on pure-data meshes, GSPMD
    # constraints when TP/MoE axes are live), "explicit", or "gspmd".
    # The preflight collective-sequence check (paddle_tpu/analysis)
    # builds BOTH and compares them — the multi-host deadlock class is
    # exactly a fleet whose hosts resolve "auto" differently.
    if lowering not in ("auto", "explicit", "gspmd"):
        raise ValueError(f"lowering must be auto|explicit|gspmd, "
                         f"got {lowering!r}")
    explicit = (zero_on and zero >= 2
                and zero_mod.explicit_lowering_ok(mesh.mesh)
                if lowering == "auto"
                else (zero_on and zero >= 2 and lowering == "explicit"))
    if lowering == "explicit" and zero_on and zero >= 2 \
            and not zero_mod.explicit_lowering_ok(mesh.mesh):
        raise ValueError("explicit ZeRO lowering requested but the mesh "
                         "has live non-data axes")
    # TPP fused shard update (ops/pallas/tpp/update): under the explicit
    # ZeRO-2 lowering with the fused_kernels flag on, the SGD/momentum
    # update runs as one read-modify-write pass inside a shard_map region
    # on exactly the 1/n gradient shard the reduce-scatter produced
    from paddle_tpu.ops.pallas import tpp as tpp_mod

    fused_update = explicit and tpp_mod.fused_enabled()

    def run_forward(tp, static_c, states, feed_c, key):
        """(cost, new_states, metric parts, fetch values, grads) on the
        batch visible to this trace (global under jit, the local shard
        under shard_map)."""
        def loss_fn(tp):
            if compute_dtype is not None:
                tp = _cast_floats(tp, compute_dtype)
            allp = {**static_c, **tp}
            values, new_states = topology.forward(
                allp, states, feed_c, True, key)
            cost = functools.reduce(
                lambda a, b: a + b,
                [jnp.sum(values[n], dtype=jnp.float32) for n in out_names]
            )
            parts = _metric_parts(metric_specs, values)
            fetch = {f"layer:{n}": jax.lax.stop_gradient(values[n])
                     for n in fetch_layers if n in values}
            return cost, (new_states, parts, fetch)

        # grads arrive f32 already (cotangent of the bf16 cast upcasts)
        (cost, (new_states, parts, fetch)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(tp)
        return cost, new_states, parts, fetch, grads

    def apply_update(grads, train_p, opt_state, gspecs):
        """Optimizer update (+ ZeRO constraints); returns
        (new_train, new_opt) with new_train back at its base layout."""
        fused = None
        if fused_update:
            fused = tpp_mod.fused_shard_apply(
                optimizer, grads, train_p, opt_state, specs, mesh.mesh,
                gspecs)
        if fused is not None:
            new_train, new_opt = fused
        else:
            new_train, new_opt = optimizer.apply(grads, train_p, opt_state,
                                                 specs)
        if zero_on:
            sspecs = zero_mod.state_specs(
                new_opt, {**train_p}, mesh.mesh,
                param_specs={n: base_specs[n] for n in train_p})
            new_opt = zero_mod.constrain_opt_state(new_opt, sspecs,
                                                   mesh.mesh)
            if explicit:
                new_train = zero_mod.gather_params(new_train, gspecs,
                                                   mesh.mesh)
            else:
                new_train = zero_mod.constrain_params(
                    new_train, mesh.mesh,
                    param_specs={n: base_specs[n] for n in train_p},
                    zero_specs=gspecs if zero >= 2 else None)
        return new_train, new_opt

    def step(params, opt_state, states, feed, key):
        train_p = {k: v for k, v in params.items() if k in trainable}
        static_p = {k: v for k, v in params.items() if k not in trainable}
        if compute_dtype is not None:
            feed_c = _cast_floats(feed, compute_dtype)
            static_c = _cast_floats(static_p, compute_dtype)
        else:
            feed_c, static_c = feed, static_p
        # persistent states (BN running stats) stay f32: batch_norm upcasts
        # internally, and a bf16 EMA accumulator would re-quantize each step

        gspecs = (zero_mod.grad_specs(
            train_p, mesh.mesh,
            param_specs={n: base_specs[n] for n in train_p})
            if zero_on else None)

        if explicit:
            def local_step(tp, static_c, states, feed_c, key):
                # independent per-replica RNG stream (the reference's
                # per-thread dropout draws, MultiGradientMachine)
                key = jax.random.fold_in(key, lax.axis_index("data"))
                cost, new_states, parts, fetch, grads = run_forward(
                    tp, static_c, states, feed_c, key)
                # cost layers reduce to batch-MEAN scalars (layers/api
                # _mean_over_batch), so the global cost is the pmean of
                # equal-shard local means and the global gradient the
                # 1/n-scaled sum — exact for dense costs; a masked
                # sequence cost weights each replica equally instead of
                # each timestep (the reference's multi-trainer
                # averaging did the same).  Metric num/den parts are
                # psummed separately, so METRICS stay exact either way.
                # Scalar reductions use raw lax — accounting noise kept
                # out of the census; the census IS the gradient flow.
                cost = lax.pmean(cost, "data")
                parts = jax.tree.map(lambda x: lax.psum(x, "data"), parts)
                new_states = jax.tree.map(lambda x: lax.pmean(x, "data"),
                                          new_states)
                grads = jax.tree.map(lambda g: g / dp, grads)
                grads = zero_mod.sync_grads(grads, gspecs)
                return cost, new_states, parts, fetch, grads

            # output STRUCTURE (metric keys, fetch leaves, state shapes)
            # comes from an abstract eval of the collective-free forward
            out_sh = jax.eval_shape(run_forward, train_p, static_c,
                                    states, feed_c, key)
            out_specs = (
                P(),                                        # cost
                jax.tree.map(lambda _: P(), out_sh[1]),     # new_states
                jax.tree.map(lambda _: P(), out_sh[2]),     # metric parts
                jax.tree.map(_batch_spec, out_sh[3]),       # fetch values
                gspecs,                                     # synced grads
            )
            region = compat.shard_map(
                local_step, mesh=mesh.mesh,
                in_specs=(
                    jax.tree.map(lambda _: P(), train_p),
                    jax.tree.map(lambda _: P(), static_c),
                    jax.tree.map(lambda _: P(), states),
                    jax.tree.map(_batch_spec, feed_c),
                    P(),
                ),
                out_specs=out_specs,
                check_vma=False)
            cost, new_states, parts, fetch, grads = region(
                train_p, static_c, states, feed_c, key)
            metrics = _finalize_metrics(parts)
            metrics.update(fetch)
        else:
            cost, new_states, parts, fetch, grads = run_forward(
                train_p, static_c, states, feed_c, key)
            metrics = _finalize_metrics(parts)
            metrics.update(fetch)
            if zero_on and zero >= 2:
                grads = zero_mod.constrain_grads(grads, gspecs, mesh.mesh)

        if compute_dtype is not None:
            new_states = _cast_like(new_states, states)
        new_train, new_opt = apply_update(grads, train_p, opt_state, gspecs)
        new_params = {**static_p, **new_train}
        return new_params, new_opt, new_states, cost, metrics

    donate = (0, 1, 2)
    if mesh is not None:
        with mesh.mesh:
            return jax.jit(step, donate_argnums=donate)
    return jax.jit(step, donate_argnums=donate)


def build_eval_step(topology: Topology, mesh: MeshContext | None = None):
    """Jitted test/inference forward: (params, states, feed) -> (values of
    outputs, cost scalar, metrics) with is_train=False."""
    metric_specs = topology.metrics()
    out_names = [o.name for o in topology.outputs]

    def step(params, states, feed):
        values, _ = topology.forward(params, states, feed, False, jax.random.key(0))
        cost = functools.reduce(
            lambda a, b: a + b, [jnp.sum(values[n]) for n in out_names]
        )
        metrics = _compute_metrics(metric_specs, values)
        return {n: values[n] for n in values}, cost, metrics

    return jax.jit(step)


def build_tap_grads(topology: Topology, tap_names: list[str],
                    is_train: bool = True):
    """Jitted (params, states, feed, key) -> {layer: d(cost)/d(layer)} —
    the gradient_printer_evaluator's data source (≅ the reference printing
    ``input.grad`` during backward, Evaluator.cpp:1091) via zero-valued
    output taps (Topology.forward ``taps``).  ``is_train`` selects the
    train or eval forward (dropout on/off) to match the pass being
    printed."""
    out_names = [o.name for o in topology.outputs]

    def grads(params, states, feed, key):
        values, _ = topology.forward(params, states, feed, is_train, key)
        taps0 = {n: jnp.zeros_like(raw(values[n])) for n in tap_names}

        def cost_of(taps):
            vals, _ = topology.forward(params, states, feed, is_train, key,
                                       taps=taps)
            return functools.reduce(
                lambda a, b: a + b,
                [jnp.sum(vals[n], dtype=jnp.float32) for n in out_names])

        return jax.grad(cost_of)(taps0)

    return jax.jit(grads)


def build_forward(topology: Topology, output_names: list[str]):
    """Inference forward returning selected layer values."""

    def fwd(params, states, feed):
        values, _ = topology.forward(params, states, feed, False, jax.random.key(0))
        return [values[n] for n in output_names]

    return jax.jit(fwd)
