"""Jitted train/eval step builder — the compiled replacement of
``GradientMachine::forwardBackward`` + ``ParameterUpdater::update``.

One XLA program per (topology, optimizer, feed-shape bucket) does: forward,
backward (``jax.grad``), gradient all-reduce over the mesh ``data`` axis
(XLA inserts ICI collectives from the shardings — replacing
``MultiGradientMachine``'s software ring and the pserver round-trip of
``RemoteParameterUpdater``), optimizer update, and metric computation.  The
reference pipelines per-parameter updates with backward via UpdateCallback
(``TrainerInternal.cpp:99-111``); XLA's scheduler provides that overlap."""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from paddle_tpu.config.topology import Topology
from paddle_tpu.layers.base import is_sequence, raw
from paddle_tpu.parallel.mesh import MeshContext


def _compute_metrics(metric_specs, values) -> dict[str, jax.Array]:
    out = {}
    for kind, pred_name, label_name, tag in metric_specs:
        pred, label = values[pred_name], values[label_name]
        if kind == "classification_error":
            p, l = raw(pred), raw(label)
            if is_sequence(pred):
                mask = pred.mask()
                ids = jnp.argmax(p, axis=-1)
                err = (ids != raw(label)).astype(jnp.float32) * mask
                out["classification_error_evaluator"] = jnp.sum(err) / jnp.maximum(
                    jnp.sum(mask), 1.0
                )
            else:
                ids = jnp.argmax(p, axis=-1)
                out["classification_error_evaluator"] = jnp.mean(
                    (ids != l.reshape(ids.shape)).astype(jnp.float32)
                )
    return out


def _cast_floats(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


def _cast_like(tree, ref):
    return jax.tree.map(
        lambda x, r: x.astype(r.dtype) if hasattr(r, "dtype") else x,
        tree, ref,
    )


def build_train_step(topology: Topology, optimizer,
                     mesh: MeshContext | None = None,
                     compute_dtype=None, fetch_layers=None):
    """Returns jitted fn: (params, opt_state, states, feed, key)
    -> (params, opt_state, states, cost, metrics).

    ``compute_dtype=jnp.bfloat16`` enables mixed precision: forward/backward
    run in bf16 on the MXU while master parameters, optimizer state, and
    persistent states stay float32 (grads are upcast before the update).

    ``fetch_layers`` names layers whose batch values should ride along in
    the metrics dict (key ``"layer:<name>"``) — the declared-evaluator feed,
    computed by the SAME forward the update uses (same dropout draw, no
    extra pass)."""
    specs = {s.name: s for s in topology.param_specs()}
    trainable = {n for n, s in specs.items() if not s.is_static}
    metric_specs = topology.metrics()
    out_names = [o.name for o in topology.outputs]
    fetch_layers = list(fetch_layers or [])

    def step(params, opt_state, states, feed, key):
        train_p = {k: v for k, v in params.items() if k in trainable}
        static_p = {k: v for k, v in params.items() if k not in trainable}
        if compute_dtype is not None:
            feed_c = _cast_floats(feed, compute_dtype)
            static_c = _cast_floats(static_p, compute_dtype)
        else:
            feed_c, static_c = feed, static_p
        # persistent states (BN running stats) stay f32: batch_norm upcasts
        # internally, and a bf16 EMA accumulator would re-quantize each step

        def loss_fn(tp):
            if compute_dtype is not None:
                tp = _cast_floats(tp, compute_dtype)
            allp = {**static_c, **tp}
            values, new_states = topology.forward(
                allp, states, feed_c, True, key)
            cost = functools.reduce(
                lambda a, b: a + b,
                [jnp.sum(values[n], dtype=jnp.float32) for n in out_names]
            )
            metrics = _compute_metrics(metric_specs, values)
            for n in fetch_layers:
                if n in values:
                    metrics[f"layer:{n}"] = jax.lax.stop_gradient(values[n])
            return cost, (new_states, metrics)

        # grads arrive f32 already (cotangent of the bf16 cast upcasts)
        (cost, (new_states, metrics)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(train_p)
        if compute_dtype is not None:
            new_states = _cast_like(new_states, states)
        new_train, new_opt = optimizer.apply(grads, train_p, opt_state, specs)
        new_params = {**static_p, **new_train}
        return new_params, new_opt, new_states, cost, metrics

    donate = (0, 1, 2)
    if mesh is not None:
        with mesh.mesh:
            return jax.jit(step, donate_argnums=donate)
    return jax.jit(step, donate_argnums=donate)


def build_eval_step(topology: Topology, mesh: MeshContext | None = None):
    """Jitted test/inference forward: (params, states, feed) -> (values of
    outputs, cost scalar, metrics) with is_train=False."""
    metric_specs = topology.metrics()
    out_names = [o.name for o in topology.outputs]

    def step(params, states, feed):
        values, _ = topology.forward(params, states, feed, False, jax.random.key(0))
        cost = functools.reduce(
            lambda a, b: a + b, [jnp.sum(values[n]) for n in out_names]
        )
        metrics = _compute_metrics(metric_specs, values)
        return {n: values[n] for n in values}, cost, metrics

    return jax.jit(step)


def build_tap_grads(topology: Topology, tap_names: list[str],
                    is_train: bool = True):
    """Jitted (params, states, feed, key) -> {layer: d(cost)/d(layer)} —
    the gradient_printer_evaluator's data source (≅ the reference printing
    ``input.grad`` during backward, Evaluator.cpp:1091) via zero-valued
    output taps (Topology.forward ``taps``).  ``is_train`` selects the
    train or eval forward (dropout on/off) to match the pass being
    printed."""
    out_names = [o.name for o in topology.outputs]

    def grads(params, states, feed, key):
        values, _ = topology.forward(params, states, feed, is_train, key)
        taps0 = {n: jnp.zeros_like(raw(values[n])) for n in tap_names}

        def cost_of(taps):
            vals, _ = topology.forward(params, states, feed, is_train, key,
                                       taps=taps)
            return functools.reduce(
                lambda a, b: a + b,
                [jnp.sum(vals[n], dtype=jnp.float32) for n in out_names])

        return jax.grad(cost_of)(taps0)

    return jax.jit(grads)


def build_forward(topology: Topology, output_names: list[str]):
    """Inference forward returning selected layer values."""

    def fwd(params, states, feed):
        values, _ = topology.forward(params, states, feed, False, jax.random.key(0))
        return [values[n] for n in output_names]

    return jax.jit(fwd)
