"""``parse_config`` — the v1 config-file compiler.

≅ ``python/paddle/trainer/config_parser.py:4238`` (``parse_config``), which
exec's a user config file in an environment of trainer_config_helpers
functions and returns a ``TrainerConfig`` proto.  The reference builds the
proto *during* the helper calls; here the helpers build the runtime layer
DAG (paddle_tpu.layers) and the proto is derived afterwards by
:mod:`paddle_tpu.config.proto_emit` — same wire surface, one source of
truth.

The returned object carries both the protos (``.model_config``,
``.opt_config`` …) and the live layer graph (``.output_layers``) so the
trainer CLI can jit-compile the same topology the proto describes.
"""

from __future__ import annotations

import os

from paddle_tpu.config import parse_state
from paddle_tpu.config.proto_emit import emit_model_config
from paddle_tpu.core.enforce import enforce
from paddle_tpu.layers import base as layer_base


class ParsedConfig:
    """TrainerConfig-shaped result; `.model_config` etc. are real protos."""

    def __init__(self, trainer_config, model_config, opt_config,
                 input_layer_names, output_layer_names, registry):
        self.trainer_config = trainer_config
        self.model_config = model_config
        self.opt_config = opt_config
        self.input_layer_names = list(input_layer_names)
        self.output_layer_names = list(output_layer_names)
        # live graph (creation order) for compiling a runtime Topology
        self.layers = list(registry)

    def output_layers(self):
        by_name = {n.name: n for n in self.layers}
        for n in self.layers:  # e.g. "__beam_search_predict__" (beam_search)
            for a in n.attrs.get("aliases", ()):
                by_name.setdefault(a, n)
        return [by_name[n] for n in self.output_layer_names]

    def protostr(self) -> str:
        from paddle_tpu.config.protostr import to_protostr

        return to_protostr(self.model_config, getattr(self, "int_style", None))


def make_config_environment(config_path: str, config_args: dict) -> dict:
    import paddle_tpu.trainer_config_helpers as tch

    tch.set_config_args(config_args)
    env: dict = {"__file__": config_path or "<config>"}
    for name in dir(tch):
        if not name.startswith("_"):
            env[name] = getattr(tch, name)
    env.update(
        get_config_arg=tch.get_config_arg,
        Inputs=parse_state.Inputs,
        Outputs=parse_state.Outputs,
        HasInputsSet=parse_state.HasInputsSet,
        outputs=parse_state.outputs,
        # the reference's 2017-era configs are python 2
        # (v1_api_demo/traffic_prediction/trainer_config.py uses xrange)
        xrange=range,
        long=int,
        unicode=str,
        # the reference config_parser's global-default setters
        default_initial_std=parse_state.default_initial_std,
        default_initial_mean=parse_state.default_initial_mean,
        default_decay_rate=parse_state.default_decay_rate,
        default_momentum=parse_state.default_momentum,
        default_initial_strategy=parse_state.default_initial_strategy,
        default_initial_smart=parse_state.default_initial_smart,
        default_num_batches_regularization=(
            parse_state.default_num_batches_regularization),
        default_device=parse_state.default_device,
    )
    return env


def parse_config(trainer_config, config_arg_str: str = ""):
    """Run a config file (path or callable) → :class:`ParsedConfig`.

    ``config_arg_str`` is ``var1=val1,var2=val2`` exposed via
    ``get_config_arg`` (≅ --config_args, config_parser.py:4238-4249).
    """
    from paddle_tpu import compat

    compat.install_paddle_alias()
    config_args = {}
    if config_arg_str:
        config_args = dict(f.split("=", 1) for f in config_arg_str.split(","))

    layer_base.reset_name_counters()
    parse_state.STATE.reset()
    parse_state.reset_defaults()
    from paddle_tpu.evaluator import declare as _declare

    _declare.reset()
    from paddle_tpu.trainer_config_helpers import optimizers as _opt

    _opt._SETTINGS.clear()

    if callable(trainer_config):
        env = make_config_environment("", config_args)
        trainer_config.__globals__.update(env)
        trainer_config()
    else:
        path = os.fspath(trainer_config)
        env = make_config_environment(path, config_args)
        with open(path) as f:
            code = compile(f.read(), path, "exec")
        exec(code, env)

    return finalize_config()


def finalize_config() -> ParsedConfig:
    settings = dict(_settings())
    registry = layer_base.layer_registry()
    input_names = parse_state.STATE.input_layer_names
    output_names = parse_state.STATE.output_layer_names
    enforce(registry, "config defined no layers")

    from paddle_tpu import proto

    tc = proto.TrainerConfig()
    # emit straight into tc.model_config so int_style message ids stay valid
    # for whole-TrainerConfig protostr rendering
    mc, emitter = emit_model_config(registry, input_names, output_names,
                                    settings, with_emitter=True,
                                    target=tc.model_config)
    if parse_state.STATE.data_config:
        _fill_data_config(tc.data_config, parse_state.STATE.data_config)
    _fill_opt_config(tc.opt_config, emitter)
    if parse_state.STATE.test_data_config:
        _fill_data_config(
            tc.test_data_config, parse_state.STATE.test_data_config,
            for_test=True)
    tc.save_dir = "./output/model"  # trainer_settings defaults
    tc.start_pass = 0
    pc = ParsedConfig(tc, mc, tc.opt_config, input_names, output_names,
                      registry)
    from paddle_tpu.evaluator import declare as _declare

    pc.evaluators = _declare.collect()
    pc.int_style = emitter.int_style
    pc._emitter = emitter  # keeps int_style's pinned upb wrappers alive
    return pc


def parse_config_and_serialize(trainer_config, config_arg_str: str = "") -> bytes:
    return parse_config(trainer_config, config_arg_str).trainer_config.SerializeToString()


def _settings() -> dict:
    from paddle_tpu.trainer_config_helpers.optimizers import get_settings

    return get_settings()


def _fill_data_config(dc, rec: dict, for_test: bool = False) -> None:
    """DataConfig emission: PyDataProvider2 ('py2', via
    define_py_data_sources2) or the classic typed providers
    (TrainData(SimpleData(...)) etc., config_parser.py:1049-1190)."""
    kind = rec.get("type", "py2")
    if kind == "simple":
        dc.type = "simple"
        if rec.get("files"):
            dc.files = rec["files"]
        if rec.get("feat_dim") is not None:
            dc.feat_dim = rec["feat_dim"]
        if rec.get("context_len") is not None:
            dc.context_len = rec["context_len"]
        if rec.get("buffer_capacity"):
            dc.buffer_capacity = rec["buffer_capacity"]
        dc.for_test = for_test
        return
    if kind == "proto":
        # ≅ config_parser.py:1036 ProtoData emission
        dc.type = "proto"
        if rec.get("files"):
            dc.files = rec["files"]
        if rec.get("usage_ratio") is not None:
            dc.usage_ratio = rec["usage_ratio"]
        dc.for_test = for_test
        return
    if kind == "multi":
        dc.type = "multi"
        dc.for_test = for_test
        for sub in rec.get("sub", ()):
            _fill_data_config(dc.sub_data_configs.add(), sub,
                              for_test=for_test)
        return
    dc.type = "py2" if kind == "py2" else "py"
    if rec.get("files"):
        dc.files = rec["files"]
    dc.async_load_data = False
    dc.for_test = for_test
    dc.load_data_module = rec.get("module") or ""
    dc.load_data_object = rec.get("obj") or ""
    args = rec.get("args")
    if args is not None and not isinstance(args, str):
        import pickle

        # reference data_sources.py:78 pickles non-string args (protocol 0)
        args = pickle.dumps(args, 0).decode("latin-1")
    dc.load_data_args = args or ""
    if kind == "py2":
        dc.data_ratio = 1
        dc.is_main_data = True
        dc.usage_ratio = 1.0


def _fill_opt_config(oc, emitter) -> None:
    """≅ update_g_config (config_parser.py:4196): every non-None entry of
    the settings dict (DEFAULT_SETTING overlaid with settings() kwargs)
    becomes an explicitly-set OptimizationConfig field."""
    from paddle_tpu.trainer_config_helpers.optimizers import proto_settings

    for key, v in proto_settings().items():
        if v is None or not hasattr(oc, key):
            continue
        try:
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                emitter.set_num(oc, key, v)
            else:
                setattr(oc, key, v)
        except (TypeError, ValueError):
            from paddle_tpu.core import logger

            logger.warning(
                "settings(%s=%r) has wrong type for OptimizationConfig.%s; "
                "field left at its default", key, v, key)
