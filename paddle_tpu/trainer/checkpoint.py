"""Checkpoint / resume — the reference's three mechanisms unified:

1. per-pass parameter snapshots under ``save_dir/pass-%05d`` with resume via
   ``--init_model_path``/``--start_pass`` (``paddle/trainer/ParamUtil.cpp``,
   flags ``utils/Flags.h:37``);
2. the Go pserver's crash-safe periodic checkpoint: payload to disk, manifest
   carrying uuid + content hash, auto-recovery picking the newest VALID
   checkpoint on restart (``go/pserver/service.go:119-156,342-391``,
   ``doc/design/cluster_train/checkpointing.md``);
3. Python ``Parameters.to_tar``/``from_tar`` (``v2/parameters.py:296-358``).

TPU-native: with no parameter server, the trainer is the state holder
(SURVEY §5 failure-detection note), so a checkpoint = parameters + optimizer
slots + layer states + RNG/pass cursor, all host-side numpy.  Manifest hashes
(sha256) stand in for the etcd md5 metadata; atomic tmp+rename replaces the
etcd transaction.  Optimizer pytrees are stored by key-path so restore works
onto a freshly built optimizer state without pickling treedefs."""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import re
import shutil
import time
import uuid as uuid_mod

import jax
import numpy as np

from paddle_tpu.core import logger as log
from paddle_tpu.core.enforce import enforce

MANIFEST = "checkpoint.json"
# retention-GC exclusion marker: a reader (servable export) drops this
# file in a checkpoint dir for the duration of its payload reads, and
# prune_old skips the dir — the marker is NOT in the manifest's file
# list, so validation ignores it
EXPORT_PIN = ".exporting"

# end-of-pass checkpoints are "pass-00003"; mid-pass cursor checkpoints
# (preemption / --checkpoint_batch_period) are "pass-00003-batch-000005",
# batch = batches COMPLETED in that pass (= the batch index resume
# replays from)
_DIR_RE = re.compile(r"^pass-(\d+)(?:-batch-(\d+))?$")


def _cursor_key(dirname: str) -> tuple[int, int] | None:
    """Chronological sort key = the manifest cursor encoded in the name:
    end-of-pass P resumes at (P+1, 0); mid-pass P after B batches resumes
    at (P, B) — so mid-pass snapshots of pass P order BEFORE pass P's
    end-of-pass snapshot and AFTER pass P-1's, regardless of the
    lexicographic accident that 'pass-00001' < 'pass-00001-batch-...'."""
    m = _DIR_RE.match(dirname)
    if not m:
        return None
    pass_id = int(m.group(1))
    if m.group(2) is None:
        return (pass_id + 1, 0)
    return (pass_id, int(m.group(2)))


def checkpoint_entries(ckpt_dir: str) -> list[str]:
    """All checkpoint dirs under ``ckpt_dir``, oldest..newest by cursor
    (not validated — callers needing integrity go through
    :func:`latest_checkpoint`, which also skips the debris a concurrent
    writer can expose: manifest missing/torn, payloads not yet written).
    Non-directories and the writer's ``.tmp-*`` staging dirs never
    qualify — a stray file named like a checkpoint must not reach the
    manifest probe."""
    if not os.path.isdir(ckpt_dir):
        return []
    named = [(k, d) for d in os.listdir(ckpt_dir)
             if (k := _cursor_key(d)) is not None
             and os.path.isdir(os.path.join(ckpt_dir, d))]
    return [os.path.join(ckpt_dir, d) for _, d in sorted(named)]


def _npz_safe(arr: np.ndarray) -> np.ndarray:
    """npz drops EXTENSION dtypes (ml_dtypes bfloat16 round-trips as raw
    ``|V2`` bytes) — store those upcast to f32 (lossless); the load side
    casts back to the template's dtype.  Native numpy dtypes (incl.
    float16) round-trip exactly and pass through untouched."""
    if arr.dtype.kind == "V" or str(arr.dtype) in (
            "bfloat16", "float8_e4m3fn", "float8_e5m2"):
        return arr.astype(np.float32)
    return arr


def _dtype_names(arrays: dict) -> dict[str, str]:
    """{name: original dtype} for entries _npz_safe will upcast — the
    manifest record that lets the load side restore bf16/fp8 exactly.
    Dtype-only inspection: no data is materialized (save already pays
    one full device->host copy; this must not add a second)."""
    out = {}
    for k, v in arrays.items():
        dt = getattr(v, "dtype", None)
        if dt is None:
            continue  # python scalars/lists: npz stores them natively
        name = str(dt)
        if np.dtype(dt).kind == "V" or name in (
                "bfloat16", "float8_e4m3fn", "float8_e5m2"):
            out[k] = name
    return out


def _dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 / float8_* live here

        return np.dtype(getattr(ml_dtypes, name))


def _restore_dtypes(arrays: dict[str, np.ndarray],
                    dtypes: dict[str, str]) -> dict[str, np.ndarray]:
    for k, name in (dtypes or {}).items():
        if k in arrays:
            try:
                arrays[k] = arrays[k].astype(_dtype_from_name(name))
            except (TypeError, AttributeError) as e:
                log.warning("checkpoint: cannot restore dtype %s for %r "
                            "(%s); leaving %s", name, k, e, arrays[k].dtype)
    return arrays


def _tree_to_flat(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[jax.tree_util.keystr(path)] = _npz_safe(np.asarray(leaf))
    return flat


# -- sharded (ZeRO) optimizer state -------------------------------------------
#
# A ZeRO run keeps the optimizer slots 1/n-sharded over the mesh ``data``
# axis (parallel/zero.py).  Checkpointing gathers NOTHING: each data
# shard is assembled host-side from the leaf's addressable device shards
# and written to its own ``opt_state.shard-<i>-of-<n>.npz``; the
# manifest's ``opt_shards`` map records which dim of which key-path was
# sharded.  Restore reassembles full host arrays and the trainer
# re-places them for ITS mesh/zero mode — so a zero=2 checkpoint
# restores into a replicated (zero=0) trainer, a different data-parallel
# degree, or vice versa (resharding on restore).


def _data_shard_info(leaf) -> tuple[int, int] | None:
    """(dim, shard count) when ``leaf`` is a jax array sharded over a
    ``data`` mesh axis with more than one shard, else None.

    Only fully-addressable leaves qualify: on a multi-process mesh this
    process can assemble just ITS shards, so the per-shard format would
    record count=n while writing a subset of the files — an unrestorable
    checkpoint.  Falling through to the plain path instead makes the
    np.asarray gather raise loudly (multi-host ZeRO checkpointing needs
    a cross-host gather/per-host manifest — not built yet)."""
    if not getattr(leaf, "is_fully_addressable", True):
        return None
    sh = getattr(leaf, "sharding", None)
    spec = getattr(sh, "spec", None)
    mesh = getattr(sh, "mesh", None)
    if spec is None or mesh is None:
        return None
    try:
        n = int(dict(mesh.shape).get("data", 1))
    except (TypeError, ValueError):  # exotic mesh stand-in (tests/mocks)
        return None
    if n <= 1:
        return None
    for d, entry in enumerate(spec):
        names = entry if isinstance(entry, tuple) else (entry,)
        if "data" in names:
            return d, n
    return None


def _data_shard_blocks(leaf, dim: int, count: int) -> dict[int, np.ndarray]:
    """{data-shard index: host block} assembled from this process's
    addressable device shards only — no full-array gather.  A leaf also
    sharded over other axes (TP) has its sub-blocks stitched; replicated
    duplicates of the same sub-block are written once."""
    per = leaf.shape[dim] // count
    blocks: dict[int, np.ndarray] = {}
    seen: dict[int, set] = {}
    for s in leaf.addressable_shards:
        idx = s.index
        start = idx[dim].start or 0
        i = start // per
        rebased = tuple(
            slice((sl.start or 0) - (start if k == dim else 0),
                  (sl.stop if sl.stop is not None else leaf.shape[k])
                  - (start if k == dim else 0))
            for k, sl in enumerate(idx))
        key = tuple((r.start, r.stop) for r in rebased)
        if key in seen.setdefault(i, set()):
            continue
        seen[i].add(key)
        data = np.asarray(s.data)
        if i not in blocks:
            shape = list(leaf.shape)
            shape[dim] = per
            blocks[i] = np.empty(shape, dtype=data.dtype)
        blocks[i][rebased] = data
    return blocks


def _flatten_opt_state(opt_state):
    """(plain flat dict, {shard idx: flat dict}, {key: dim}, count) —
    splits the state into replicated leaves (plain ``opt_state.npz``)
    and data-sharded leaves (per-shard files)."""
    flat: dict[str, np.ndarray] = {}
    shard_files: dict[int, dict[str, np.ndarray]] = {}
    dims: dict[str, int] = {}
    count = None
    for path, leaf in jax.tree_util.tree_flatten_with_path(opt_state)[0]:
        key = jax.tree_util.keystr(path)
        info = _data_shard_info(leaf)
        if info is None:
            flat[key] = _npz_safe(np.asarray(leaf))
            continue
        d, n = info
        if count is not None and n != count:
            # mixed shard counts would need per-key counts; gather the
            # odd one out rather than complicate the manifest
            flat[key] = _npz_safe(np.asarray(leaf))
            continue
        count = n
        dims[key] = d
        for i, block in _data_shard_blocks(leaf, d, n).items():
            shard_files.setdefault(i, {})[key] = _npz_safe(block)
    return flat, shard_files, dims, count


def _shard_file(i: int, count: int) -> str:
    return f"opt_state.shard-{i:05d}-of-{count:05d}.npz"


def _tree_from_flat(template, flat: dict[str, np.ndarray]):
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = jax.tree_util.keystr(path)
        enforce(key in flat,
                f"checkpoint missing optimizer slot {key!r} — optimizer "
                "config changed since the checkpoint was written")
        arr = flat[key]
        enforce(tuple(arr.shape) == tuple(np.shape(leaf)),
                f"checkpoint slot {key!r} shape {arr.shape} != "
                f"{np.shape(leaf)}")
        # restore the template's dtype (extension dtypes were stored f32)
        dt = getattr(leaf, "dtype", None)
        new_leaves.append(jax.numpy.asarray(arr)
                          if dt is None else jax.numpy.asarray(arr, dtype=dt))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_checkpoint(ckpt_dir: str, pass_id: int, params: dict,
                    opt_state=None, states: dict | None = None,
                    meta: dict | None = None, keep_last: int = 3,
                    batch_id: int | None = None) -> str:
    """Write ``{ckpt_dir}/pass-{pass_id:05d}/`` atomically; returns the path.

    ``batch_id`` (mid-pass cursor checkpoints: preemption saves and
    ``checkpoint_batch_period``) is the number of batches COMPLETED in
    ``pass_id``; the directory becomes ``pass-P-batch-B`` and the
    manifest ``cursor`` tells resume to replay pass P from batch B.
    Without it the cursor is the following pass's first batch.

    Files: ``params.npz`` (name -> array), ``opt_state.npz`` (key-path ->
    array), ``states.npz``, ``checkpoint.json`` manifest with uuid + sha256
    per payload file (written LAST, so a manifest implies complete payload).
    """
    if batch_id is None:
        final = os.path.join(ckpt_dir, f"pass-{pass_id:05d}")
        cursor = {"pass_id": pass_id + 1, "batch_id": 0}
    else:
        final = os.path.join(
            ckpt_dir, f"pass-{pass_id:05d}-batch-{batch_id:06d}")
        cursor = {"pass_id": pass_id, "batch_id": batch_id}
    tmp = final + ".tmp-" + uuid_mod.uuid4().hex[:8]
    os.makedirs(tmp, exist_ok=True)
    try:
        np.savez(os.path.join(tmp, "params.npz"),
                 **{k: _npz_safe(np.asarray(v)) for k, v in params.items()})
        opt_shards = None
        if opt_state is not None:
            flat, shard_files, dims, count = _flatten_opt_state(opt_state)
            np.savez(os.path.join(tmp, "opt_state.npz"), **flat)
            for i, blocks in sorted(shard_files.items()):
                np.savez(os.path.join(tmp, _shard_file(i, count)), **blocks)
            if shard_files:
                opt_shards = {"axis": "data", "count": count, "dims": dims}
        if states:
            np.savez(os.path.join(tmp, "states.npz"),
                     **{k: _npz_safe(np.asarray(v))
                        for k, v in states.items()})
        manifest = {
            "uuid": uuid_mod.uuid4().hex,
            "pass_id": pass_id,
            # where resume continues: the first (pass, batch) NOT yet
            # applied.  Mid-pass cursors let a preempted/killed run
            # replay from the exact batch boundary (trainer resume
            # fast-forwards the reader and restores the manifest's RNG
            # stream, so the trajectory is bit-identical).
            "cursor": cursor,
            "created": time.time(),
            "files": {
                f: _sha256(os.path.join(tmp, f))
                for f in sorted(os.listdir(tmp))
            },
            # npz stores extension dtypes (bf16/fp8) upcast to f32; the
            # originals are recorded here so load_checkpoint hands back
            # the exact dtypes — otherwise a bf16 model resumes f32 and
            # silently recompiles under a different signature
            "dtypes": {
                "params": _dtype_names(params),
                "states": _dtype_names(states or {}),
            },
            # ZeRO sharded-state map: which key-paths were split on which
            # dim into the opt_state.shard-*.npz payloads (absent for a
            # replicated/host-numpy opt_state).  The shard files sit in
            # "files" like every payload, so sha256 validation covers
            # them and a missing/corrupt shard invalidates the whole
            # checkpoint (latest_checkpoint falls back to the previous).
            **({"opt_shards": opt_shards} if opt_shards else {}),
            "meta": meta or {},
        }
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    log.info("checkpoint saved: %s (uuid %s)", final, manifest["uuid"])
    _gc_old(ckpt_dir, keep_last)
    return final


@contextlib.contextmanager
def export_pin(path: str):
    """Pin a checkpoint dir against retention GC for the duration of a
    read (the deployment controller holds this around servable export,
    so a concurrent trainer save's :func:`prune_old` cannot rmtree the
    payload mid-read)."""
    marker = os.path.join(path, EXPORT_PIN)
    with open(marker, "w") as f:
        f.write(str(os.getpid()))
    try:
        yield path
    finally:
        try:
            os.remove(marker)
        except OSError:
            pass


def prune_old(ckpt_dir: str, keep_last: int = 3) -> list[str]:
    """Retention GC: delete checkpoints beyond the newest ``keep_last``
    (by cursor order); returns the removed paths.  ``keep_last <= 0``
    disables pruning.  Two dirs are NEVER deleted regardless of age:

    - the newest VALID checkpoint (the recovery target — if every
      younger entry is torn or corrupt, deleting it would leave nothing
      to resume or deploy from);
    - any dir pinned mid-export (:func:`export_pin`'s marker) — the
      deployment controller may be streaming its payload right now.
    """
    if keep_last <= 0:
        return []
    entries = checkpoint_entries(ckpt_dir)
    if len(entries) <= keep_last:
        return []  # nothing would be deleted — skip the validity probe
    keep = set(entries[-keep_last:])
    newest_valid = latest_checkpoint(ckpt_dir)
    if newest_valid is not None:
        keep.add(newest_valid[0])
    removed = []
    for path in entries:
        if path in keep:
            continue
        if os.path.exists(os.path.join(path, EXPORT_PIN)):
            log.info("checkpoint GC: %s pinned mid-export, kept", path)
            continue
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    if removed:
        log.info("checkpoint GC: pruned %d old checkpoint(s), kept %d",
                 len(removed), len(entries) - len(removed))
    return removed


def _gc_old(ckpt_dir: str, keep_last: int) -> None:
    prune_old(ckpt_dir, keep_last)


def _validate(path: str) -> dict | None:
    """Return the manifest if the checkpoint is complete and uncorrupted."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        for fname, digest in manifest["files"].items():
            if _sha256(os.path.join(path, fname)) != digest:
                log.warning("checkpoint %s: %s hash mismatch", path, fname)
                return None
        return manifest
    except (OSError, ValueError, KeyError, TypeError, AttributeError) as e:
        log.warning("checkpoint %s unreadable: %s", path, e)
        return None


def latest_checkpoint(ckpt_dir: str) -> tuple[str, dict] | None:
    """Newest VALID checkpoint by cursor order (corrupt/partial ones —
    manifest missing, unreadable, or any payload sha256 mismatch — are
    skipped, falling back to the previous one: the Go pserver recovery
    rule)."""
    for path in reversed(checkpoint_entries(ckpt_dir)):
        manifest = _validate(path)
        if manifest is not None:
            return path, manifest
    return None


def load_checkpoint(path: str, opt_state_template=None):
    """Returns (params dict, opt_state-or-None, states dict, manifest)."""
    manifest = _validate(path)
    enforce(manifest is not None, f"invalid checkpoint at {path}")

    def load_npz(name):
        p = os.path.join(path, name)
        if not os.path.exists(p):
            return {}
        with np.load(p) as z:
            return {k: z[k] for k in z.files}

    dtypes = manifest.get("dtypes", {})
    params = _restore_dtypes(load_npz("params.npz"), dtypes.get("params"))
    states = _restore_dtypes(load_npz("states.npz"), dtypes.get("states"))
    opt_state = None
    opt_flat = load_npz("opt_state.npz")
    shards = manifest.get("opt_shards")
    if shards:
        # reassemble each sharded key-path by concatenating its per-shard
        # blocks along the recorded dim — full host arrays the caller
        # re-places for ITS mesh/zero mode (resharding on restore)
        count = int(shards["count"])
        parts = [load_npz(_shard_file(i, count)) for i in range(count)]
        for key, dim in shards["dims"].items():
            enforce(all(key in p for p in parts),
                    f"checkpoint shard files missing key {key!r}")
            opt_flat[key] = np.concatenate([p[key] for p in parts],
                                           axis=int(dim))
    if opt_flat and opt_state_template is not None:
        opt_state = _tree_from_flat(opt_state_template, opt_flat)
    return params, opt_state, states, manifest


class AsyncCheckpointer:
    """Non-blocking checkpoint writes.

    The go pserver keeps its periodic checkpoint off the optimization
    path (``go/pserver/service.go:119-156`` — a ticker goroutine, not the
    SendGrad handler); the in-trainer analog keeps disk serialization off
    the step loop.  ``save()`` materializes a consistent host snapshot
    synchronously (device->host copies), then hands the npz/manifest
    write to a single daemon worker; at most one write is in flight — a
    new ``save()`` first joins the previous one, and a failed write
    re-raises from the next ``save()``/``wait()`` so errors are never
    silently dropped (the failure is also counted in telemetry —
    ``checkpoint_write_failures`` — the moment it happens, so a run
    whose next save is far away still shows it).  Transient I/O errors
    are retried on the worker per ``retry`` (default: a short
    deterministic :class:`~paddle_tpu.resilience.policy.RetryPolicy`
    over OSError — a flaky NFS write should not cost the snapshot).
    Writes stay atomic (tmp dir + rename in ``save_checkpoint``), so a
    crash mid-write never corrupts the newest valid checkpoint.

    ZeRO note: the host snapshot below materializes FULL arrays
    (np.asarray gathers a sharded optimizer state), so an async save of
    a ZeRO run writes the plain full-state format rather than per-shard
    files — restorable either way (load reassembles/re-places), at the
    cost of one host-side gather the synchronous path avoids.
    """

    def __init__(self, retry=None):
        import threading

        from paddle_tpu.resilience.policy import RetryPolicy

        self._thread = None
        # _err is written by the writer thread and read/cleared by the
        # step loop in wait(); every access holds _lock (the GL-THREAD
        # audited contract)
        self._lock = threading.Lock()
        self._err = None
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
            retry_on=(OSError,), scope="checkpoint")

    def save(self, ckpt_dir: str, pass_id: int, params: dict,
             opt_state=None, states: dict | None = None,
             meta: dict | None = None, keep_last: int = 3,
             batch_id: int | None = None) -> None:
        import threading

        self.wait()
        params_h = {k: np.asarray(v) for k, v in params.items()}
        opt_h = None if opt_state is None else jax.tree.map(
            np.asarray, opt_state)
        states_h = None if not states else {
            k: np.asarray(v) for k, v in states.items()}

        def run():
            try:
                self._retry.call(
                    save_checkpoint, ckpt_dir, pass_id, params_h,
                    opt_state=opt_h, states=states_h, meta=meta,
                    keep_last=keep_last, batch_id=batch_id)
            except BaseException as e:  # surfaced on next save()/wait()
                with self._lock:
                    self._err = e
                from paddle_tpu.telemetry import safe_inc

                safe_inc("checkpoint_write_failures",
                         "async checkpoint writes that failed")
                log.warning("async checkpoint write failed (%s: %s); the "
                            "error re-raises at the next save()/wait()",
                            type(e).__name__, e)

        self._thread = threading.Thread(
            target=run, name=f"ckpt-pass-{pass_id}", daemon=True)
        self._thread.start()

    def wait(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        with self._lock:
            err, self._err = self._err, None
        if err is not None:
            raise err
