"""``python -m paddle_tpu.trainer`` (≅ the paddle_trainer binary)."""

import sys

from paddle_tpu.trainer.cli import main

sys.exit(main())
