"""``paddle.v2`` alias — reference user code does ``import paddle.v2 as
paddle``; here ``import paddle_tpu.v2 as paddle`` (or just ``import
paddle_tpu as paddle``) exposes the identical surface."""

from paddle_tpu import *  # noqa: F401,F403
from paddle_tpu import (  # noqa: F401
    attr,
    dataset,
    event,
    infer,
    layer,
    optimizer,
    parameters,
    reader,
    topology,
    trainer,
)

try:  # keep the v2 sub-namespaces addressable
    from paddle_tpu.layers import activation, data_type, pooling  # noqa: F401
except ImportError:  # pragma: no cover
    pass


from paddle_tpu import init  # noqa: F401  (the flag-setup function)
