"""Reference-flavored proto text rendering ("protostr").

The reference's golden files (``trainer_config_helpers/tests/configs/
protostr/*.protostr``) were produced by Python-2-era protobuf text_format,
whose float rendering is py2 ``str(float)`` — 12 significant digits
(``'%.12g'``) with a trailing ``.0`` for integral values.  Modern protobuf
prints shortest-repr floats, so its output would differ byte-wise on any
computed float (e.g. ``initial_std: 0.0441941738242``).  This tiny printer
walks descriptors directly (fields in number order, 2-space indents, C-style
string escaping) and reproduces the py2 spelling, giving byte-exact golden
comparisons.
"""

from __future__ import annotations



def py2_float_repr(v: float) -> str:
    """Python-2 ``str(float)``: %.12g plus '.0' for integral magnitudes."""
    s = "%.12g" % float(v)
    if "." not in s and "e" not in s and "n" not in s and "i" not in s:
        s += ".0"
    return s


def _escape(s) -> str:
    if isinstance(s, bytes):
        data = s
    else:
        data = s.encode("utf-8")
    out = []
    for b in data:
        if b == 0x22:
            out.append('\\"')
        elif b == 0x5C:
            out.append("\\\\")
        elif b == 0x0A:
            out.append("\\n")
        elif b == 0x0D:
            out.append("\\r")
        elif b == 0x09:
            out.append("\\t")
        elif 0x20 <= b < 0x7F:
            out.append(chr(b))
        else:
            out.append("\\%03o" % b)
    return "".join(out)


def _float32_shortest(v: float) -> float:
    """Shortest decimal that round-trips to the same float32 — py2 stored the original python double in FLOAT fields, so goldens show '0.45', not the float32-rounded 0.449999988079."""
    import struct

    packed = struct.pack("f", v)
    for digits in range(1, 17):
        cand = float(f"%.{digits}g" % v)
        if struct.pack("f", cand) == packed:
            return cand
    return v


def _scalar(fd, v, int_style=None, msg_id=None) -> str:
    t = fd.type
    if t == fd.TYPE_FLOAT:
        v = _float32_shortest(v)
    if t in (fd.TYPE_FLOAT, fd.TYPE_DOUBLE):
        # config_parser assigns some fields straight from user values (no
        # float() coercion); py2's pure-python protobuf stored the int
        # as-is, so goldens print those without ".0".  Emitters record the
        # int-typed assignments per message instance (Emitter.set_num).
        if int_style and (msg_id, fd.name) in int_style and float(v).is_integer():
            return str(int(v))
        return py2_float_repr(v)
    if t == fd.TYPE_BOOL:
        return "true" if v else "false"
    if t == fd.TYPE_STRING or t == fd.TYPE_BYTES:
        return f'"{_escape(v)}"'
    if t == fd.TYPE_ENUM:
        return fd.enum_type.values_by_number[v].name
    return str(v)


def _print_msg(msg, indent: int, out: list, int_style=None) -> None:
    pad = "  " * indent
    mid = id(msg)
    for fd in msg.DESCRIPTOR.fields:  # descriptor order == declaration order
        if fd.is_repeated:  # label() is deprecated in protobuf>=5
            values = getattr(msg, fd.name)
            for v in values:
                if fd.type == fd.TYPE_MESSAGE:
                    out.append(f"{pad}{fd.name} {{")
                    _print_msg(v, indent + 1, out, int_style)
                    out.append(f"{pad}}}")
                else:
                    out.append(f"{pad}{fd.name}: {_scalar(fd, v, int_style, mid)}")
        else:
            if not msg.HasField(fd.name):
                continue
            if fd.type == fd.TYPE_MESSAGE:
                out.append(f"{pad}{fd.name} {{")
                _print_msg(getattr(msg, fd.name), indent + 1, out, int_style)
                out.append(f"{pad}}}")
            else:
                out.append(
                    f"{pad}{fd.name}: "
                    f"{_scalar(fd, getattr(msg, fd.name), int_style, mid)}"
                )


def to_protostr(msg, int_style=None) -> str:
    out: list[str] = []
    _print_msg(msg, 0, out, int_style)
    return "\n".join(out) + "\n"
