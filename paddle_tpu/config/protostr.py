"""Reference-flavored proto text rendering ("protostr").

The reference's golden files (``trainer_config_helpers/tests/configs/
protostr/*.protostr``) were produced by Python-2-era protobuf text_format,
whose float rendering is py2 ``str(float)`` — 12 significant digits
(``'%.12g'``) with a trailing ``.0`` for integral values.  Modern protobuf
prints shortest-repr floats, so its output would differ byte-wise on any
computed float (e.g. ``initial_std: 0.0441941738242``).  This tiny printer
walks descriptors directly (fields in number order, 2-space indents, C-style
string escaping) and reproduces the py2 spelling, giving byte-exact golden
comparisons.
"""

from __future__ import annotations

from google.protobuf import descriptor as _desc


def py2_float_repr(v: float) -> str:
    """Python-2 ``str(float)``: %.12g plus '.0' for integral magnitudes."""
    s = "%.12g" % float(v)
    if "." not in s and "e" not in s and "n" not in s and "i" not in s:
        s += ".0"
    return s


def _escape(s) -> str:
    if isinstance(s, bytes):
        data = s
    else:
        data = s.encode("utf-8")
    out = []
    for b in data:
        if b == 0x22:
            out.append('\\"')
        elif b == 0x5C:
            out.append("\\\\")
        elif b == 0x0A:
            out.append("\\n")
        elif b == 0x0D:
            out.append("\\r")
        elif b == 0x09:
            out.append("\\t")
        elif 0x20 <= b < 0x7F:
            out.append(chr(b))
        else:
            out.append("\\%03o" % b)
    return "".join(out)


# double/float fields that config_parser assigns straight from user values
# (no float() coercion): py2's pure-python protobuf stored the int as-is, so
# goldens print them without ".0".  Fields the reference float()s always
# print py2-float style.
INT_STYLE_FIELDS = {
    ("ClipConfig", "min"),
    ("ClipConfig", "max"),
    ("LayerConfig", "slope"),
    ("LayerConfig", "intercept"),
    ("LayerConfig", "cos_scale"),
    ("OperatorConfig", "dotmul_scale"),
    ("NormConfig", "pow"),
}


def _scalar(fd, v, msg_name: str = "") -> str:
    t = fd.type
    if t in (fd.TYPE_FLOAT, fd.TYPE_DOUBLE):
        if (msg_name, fd.name) in INT_STYLE_FIELDS and float(v).is_integer():
            return str(int(v))
        return py2_float_repr(v)
    if t == fd.TYPE_BOOL:
        return "true" if v else "false"
    if t == fd.TYPE_STRING or t == fd.TYPE_BYTES:
        return f'"{_escape(v)}"'
    if t == fd.TYPE_ENUM:
        return fd.enum_type.values_by_number[v].name
    return str(v)


def _print_msg(msg, indent: int, out: list) -> None:
    pad = "  " * indent
    mname = msg.DESCRIPTOR.name
    for fd in msg.DESCRIPTOR.fields:  # descriptor order == declaration order
        if fd.label == _desc.FieldDescriptor.LABEL_REPEATED:
            values = getattr(msg, fd.name)
            for v in values:
                if fd.type == fd.TYPE_MESSAGE:
                    out.append(f"{pad}{fd.name} {{")
                    _print_msg(v, indent + 1, out)
                    out.append(f"{pad}}}")
                else:
                    out.append(f"{pad}{fd.name}: {_scalar(fd, v, mname)}")
        else:
            if not msg.HasField(fd.name):
                continue
            if fd.type == fd.TYPE_MESSAGE:
                out.append(f"{pad}{fd.name} {{")
                _print_msg(getattr(msg, fd.name), indent + 1, out)
                out.append(f"{pad}}}")
            else:
                out.append(
                    f"{pad}{fd.name}: {_scalar(fd, getattr(msg, fd.name), mname)}"
                )


def to_protostr(msg) -> str:
    out: list[str] = []
    _print_msg(msg, 0, out)
    return "\n".join(out) + "\n"
