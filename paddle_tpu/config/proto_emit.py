"""ModelConfig proto emission from the layer DAG.

The reference builds ``ModelConfig`` *during* helper calls
(``config_parser.py``: each ``LayerBase.__init__`` appends a ``LayerConfig``,
``Parameter()`` appends a ``ParameterConfig``).  Here the runtime graph is
the single source of truth — helper calls build :class:`LayerOutput` nodes
(compiled to a jitted step by ``Topology``) — and this module *derives* the
byte-compatible proto from those nodes afterwards.  Per-layer-type emit
functions reproduce the reference's accreted field semantics (defaults,
computed conv geometry, parameter dims/init) so protostr output matches the
reference's goldens (``trainer_config_helpers/tests/configs/protostr``).

Layer ordering follows the creation-order registry
(:func:`paddle_tpu.layers.base.layer_registry`), matching the reference's
append-at-call-time order, not the topo-sort used for execution.
"""

from __future__ import annotations

import math

from paddle_tpu import proto
from paddle_tpu.config.protostr import to_protostr
from paddle_tpu.core.enforce import EnforceError, enforce
from paddle_tpu.layers.attr import ParamAttr
from paddle_tpu.layers.base import LayerOutput

EMITTERS: dict = {}


def emits(*types):
    def deco(fn):
        for t in types:
            EMITTERS[t] = fn
        return fn

    return deco


def emission_parents(node):
    """The parents the WIRE CONFIG shows: runtime rewires (the fused-CE
    logits companion) stash the original wiring in __emit_parent_nodes__,
    and runtime-only extra parents are trimmed via __emit_parents__."""
    parents = node.attrs.get("__emit_parent_nodes__") or node.parents
    n_emit = node.attrs.get("__emit_parents__")
    if n_emit is not None:
        parents = parents[:n_emit]
    return parents


class Emitter:
    """One ModelConfig under construction (≅ config_parser globals)."""

    def __init__(self, settings: dict | None = None, target=None):
        s = settings or {}
        self.mc = target if target is not None else proto.ModelConfig()
        self.mc.type = "nn"
        self.root = self.mc.sub_models.add()
        self.root.name = "root"
        self.root.is_recurrent_layer_group = False
        self.cur_submodel = self.root
        self._param_names: set[str] = set()
        self._layer_names: set[str] = set()
        # (id(message), field) pairs assigned from int-typed user values —
        # printed without ".0" like py2 protobuf did (see protostr._scalar).
        # _pins keeps those upb wrappers alive: their identity (and hence the
        # id-keyed lookup) is only stable while a Python reference exists.
        self.int_style: set = set()
        self._pins: list = []
        # g_default_* (config_parser.py:118-121 + settings())
        self.defaults = {
            "initial_mean": 0.0,
            "initial_std": 0.01,
            "initial_strategy": 0,
            "initial_smart": False,
            "momentum": s.get("default_momentum"),
            "decay_rate": s.get("default_decay_rate"),
            "num_batches_regularization": s.get("num_batches_regularization"),
            "gradient_clipping_threshold": None,
        }

    # -- core helpers (≅ LayerBase / Parameter) ---------------------------

    def layer(self, node: LayerOutput, ltype: str | None = None,
              active_type: str | None = None, size: int | None = None,
              inputs: bool = True):
        """≅ LayerBase.__init__ (config_parser.py:1541): append LayerConfig,
        one LayerInputConfig per parent, register in current submodel."""
        lc = self.mc.layers.add()
        lc.name = node.name
        lc.type = ltype or node.layer_type
        if active_type is None:
            active_type = node.attrs.get("active_type", "")
        lc.active_type = active_type
        if size is None:
            size = node.size
        if size:
            lc.size = int(size)
        if node.attrs.get("drop_rate"):
            lc.drop_rate = float(node.attrs["drop_rate"])
        if node.attrs.get("error_clipping_threshold") is not None:
            lc.error_clipping_threshold = node.attrs["error_clipping_threshold"]
        if node.attrs.get("coeff_field") is not None:
            lc.coeff = float(node.attrs["coeff_field"])
        if inputs:
            for p in emission_parents(node):
                lc.inputs.add().input_layer_name = p.name
        self.cur_submodel.layer_names.append(node.name)
        self._layer_names.add(node.name)
        return lc

    def parameter(self, name: str, size: int, dims, attr: ParamAttr | None,
                  extra: dict | None = None, sparse=None, fmt=None):
        """≅ Parameter() (config_parser.py:3852): shared params emitted once;
        smart init recomputes mean/std from dims."""
        if name in self._param_names:
            return
        self._param_names.add(name)
        pf = dict(attr.proto_fields()) if attr is not None else {}
        if extra:
            pf.update(extra)
        d = self.defaults
        p = self.mc.parameters.add()
        p.name = name
        p.size = int(size)
        p.dims.extend(int(x) for x in dims)
        if "learning_rate" in pf:
            p.learning_rate = float(pf["learning_rate"])
        mom = pf.get("momentum", d["momentum"])
        if mom is not None:
            p.momentum = float(mom)
        dr = pf.get("decay_rate", d["decay_rate"])
        if dr is not None:
            p.decay_rate = float(dr)
        if "decay_rate_l1" in pf:
            p.decay_rate_l1 = float(pf["decay_rate_l1"])
        self.set_num(p, "initial_std", pf.get("initial_std", d["initial_std"]))
        self.set_num(p, "initial_mean", pf.get("initial_mean", d["initial_mean"]))
        nbr = pf.get("num_batches_regularization", d["num_batches_regularization"])
        if nbr is not None:
            p.num_batches_regularization = int(nbr)
        if "sparse_remote_update" in pf:
            p.sparse_remote_update = bool(pf["sparse_remote_update"])
        if "sparse_update" in pf:
            p.sparse_update = bool(pf["sparse_update"])
        gct = pf.get(
            "gradient_clipping_threshold", d["gradient_clipping_threshold"]
        )
        if gct is not None:
            p.gradient_clipping_threshold = float(gct)
        p.initial_strategy = int(pf.get("initial_strategy", d["initial_strategy"]))
        p.initial_smart = bool(pf.get("initial_smart", d["initial_smart"]))
        if p.initial_smart:
            p.initial_mean = 0.0
            p.initial_std = 1.0 / math.sqrt(p.dims[0] if p.dims else p.size)
        if sparse is not None:
            p.is_sparse = bool(sparse)
        if fmt is not None:
            p.format = fmt
        if "is_static" in pf:
            p.is_static = bool(pf["is_static"])
        if "is_shared" in pf:
            p.is_shared = bool(pf["is_shared"])
        for hook in pf.get("update_hooks", ()):
            h = p.update_hooks.add()
            h.type = hook[0]
            if hook[1] is not None:
                h.sparsity_ratio = hook[1]
        return p

    def set_num(self, msg, field: str, v) -> None:
        """Assign a float/double field, remembering int-typed sources."""
        setattr(msg, field, v)
        if isinstance(v, int) and not isinstance(v, bool):
            self.int_style.add((id(msg), field))
            self._pins.append(msg)

    # -- spec plumbing ----------------------------------------------------

    @staticmethod
    def split_specs(node: LayerOutput):
        """(weight_specs, bias_spec) — bias by the explicit ``bias_spec``
        attr when a shared/renamed bias was used, else the ``.wbias``
        naming convention."""
        explicit = node.attrs.get("bias_spec")
        ws, b = [], None
        for s in node.param_specs:
            if (explicit and s.name == explicit) or (
                not explicit and s.name.endswith(".wbias")
            ):
                b = s
            else:
                ws.append(s)
        return ws, b

    def input_param(self, lc, idx: int, spec, size: int, dims,
                    default_attr: ParamAttr | None = None, extra=None,
                    sparse=None, fmt=None):
        """≅ create_input_parameter (config_parser.py:1687)."""
        lc.inputs[idx].input_parameter_name = spec.name
        attr = spec.attr
        if default_attr is not None and (attr is None or _is_default_attr(attr)):
            attr = default_attr  # layer-specific default init (e.g. conv MSRA)
        self.parameter(spec.name, size, dims, attr, extra=extra,
                       sparse=sparse, fmt=fmt)

    def bias_param(self, lc, node: LayerOutput, size: int, dims=None,
                   bias_spec=None):
        """≅ create_bias_parameter (config_parser.py:1634): default bias
        attr is zero-init gauss (wrap_bias_attr_default,
        default_decorators.py:144)."""
        if bias_spec is None:
            explicit = node.attrs.get("bias_spec")
            if explicit:
                bias_spec = next(
                    (s for s in node.param_specs if s.name == explicit), None)
            else:
                _, bias_spec = self.split_specs(node)
        if bias_spec is None:
            return
        if dims is None:
            dims = [1, size]
        attr = bias_spec.attr
        if attr is None or _is_default_attr(attr):
            attr = ParamAttr(initial_std=0.0, initial_mean=0.0)
        lc.bias_parameter_name = bias_spec.name
        self.parameter(bias_spec.name, size, dims, attr)

    # -- finalization ------------------------------------------------------

    def finalize(self, input_names, output_names):
        self.mc.input_layer_names.extend(input_names)
        self.mc.output_layer_names.extend(output_names)
        self.root.input_layer_names.extend(input_names)
        self.root.output_layer_names.extend(output_names)

    def evaluator(self, etype: str, name: str, inputs: list[str], **kw):
        """≅ Evaluator() (config_parser.py:1470)."""
        ev = self.mc.evaluators.add()
        ev.type = etype
        ev.name = name
        ev.input_layers.extend(inputs)
        for k, v in kw.items():
            if v is None:
                continue
            if isinstance(v, (list, tuple)):
                getattr(ev, k).extend(v)
            else:
                setattr(ev, k, v)
        self.cur_submodel.evaluator_names.append(name)
        return ev


def _is_default_attr(a: ParamAttr) -> bool:
    """True when the user supplied no init/decay info (plain ParamAttr())."""
    return (
        a.initial_std is None and a.initial_mean is None
        and a.initial_max is None and a.initial_min is None
        and a.learning_rate is None and a.l1_rate is None
        and a.l2_rate is None and a.momentum is None
        and not a.is_static and not a.sparse_update
        and a.gradient_clipping_threshold is None
        and a.sparsity_ratio is None and a.initializer is None
        and a.name is None
    )


# ---------------------------------------------------------------------------
# geometry helpers (≅ config_parser cnn_output_size / get_img_size)
# ---------------------------------------------------------------------------


def cnn_output_size(img_size, filter_size, padding, stride, caffe_mode=True):
    out = (2 * padding + img_size - filter_size) / float(stride)
    return 1 + int(math.floor(out) if caffe_mode else math.ceil(out))


def cnn_image_size(output_size, filter_size, padding, stride, caffe_mode=True):
    img = (output_size - 1) * stride + filter_size - 2 * padding
    return img if caffe_mode else img + 1


def get_img_size(parent: LayerOutput, channels: int):
    pixels = parent.size // channels
    img_size = parent.width if parent.width > 0 else int(pixels ** 0.5)
    img_size_y = parent.height if parent.height > 0 else int(pixels // img_size)
    enforce(
        img_size * img_size_y == pixels,
        f"layer {parent.name}: image size {img_size}x{img_size_y} != {pixels} px",
    )
    return img_size, img_size_y


# ---------------------------------------------------------------------------
# per-type emitters
# ---------------------------------------------------------------------------


@emits("data")
def _data(E: Emitter, node: LayerOutput):
    lc = E.layer(node, active_type="")
    if node.attrs.get("explicit_hw"):
        lc.height = node.height
        lc.width = node.width
        if node.attrs.get("explicit_depth"):
            lc.depth = node.depth


@emits("fc")
def _fc(E: Emitter, node: LayerOutput):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    for i, (p, spec) in enumerate(zip(emission_parents(node), ws)):
        E.input_param(lc, i, spec, p.size * node.size, [p.size, node.size])
    E.bias_param(lc, node, node.size)


@emits("trans")
def _trans(E, node):
    E.layer(node, active_type="")


@emits("selective_fc")
def _selective_fc(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    # inputs: [data..., select]; parameters only for the data inputs
    for i, spec in enumerate(ws):
        p = node.parents[i]
        E.input_param(lc, i, spec, p.size * node.size, [p.size, node.size],
                      sparse=False)
    E.bias_param(lc, node, node.size)
    lc.selective_fc_pass_generation = node.attrs.get("pass_generation", False)
    lc.has_selected_colums = node.attrs.get("has_selected_colums", True)
    lc.selective_fc_full_mul_ratio = node.attrs.get("full_mul_ratio", 0.02)


@emits("exconv", "exconvt")
def _conv(E: Emitter, node: LayerOutput):
    a = node.attrs
    trans = node.layer_type == "exconvt"
    lc = E.layer(node)
    lc.ClearField("size")
    num_filters = a["num_filters"]
    lc.num_filters = num_filters
    lc.shared_biases = a.get("shared_biases", True)
    parent = node.parents[0]
    groups = a.get("groups", 1)
    kh, kw = a["filter_size"]
    sh, sw = a["stride"]
    ph, pw = a["padding"]
    channels = a.get("channels") or parent.depth
    cc = lc.inputs[0].conv_conf
    cc.filter_size = kw
    cc.filter_size_y = kh
    cc.channels = channels
    cc.padding = pw
    cc.padding_y = ph
    cc.stride = sw
    cc.stride_y = sh
    cc.groups = groups
    cc.caffe_mode = a.get("caffe_mode", True)
    if not trans:
        cc.filter_channels = channels // groups
        cc.img_size, cc.img_size_y = get_img_size(parent, channels)
        cc.output_x = cnn_output_size(cc.img_size, cc.filter_size, cc.padding,
                                      cc.stride, cc.caffe_mode)
        cc.output_y = cnn_output_size(cc.img_size_y, cc.filter_size_y,
                                      cc.padding_y, cc.stride_y, cc.caffe_mode)
        out_x, out_y = cc.output_x, cc.output_y
    else:
        cc.filter_channels = num_filters // groups
        cc.output_x, cc.output_y = get_img_size(parent, channels)
        cc.img_size = cnn_image_size(cc.output_x, cc.filter_size, cc.padding,
                                     cc.stride, cc.caffe_mode)
        cc.img_size_y = cnn_image_size(cc.output_y, cc.filter_size_y,
                                       cc.padding_y, cc.stride_y, cc.caffe_mode)
        out_x, out_y = cc.img_size, cc.img_size_y
    dil = a.get("dilation", (1, 1))
    if isinstance(dil, int):
        dil = (dil, dil)
    if dil[0] > 1 or dil[1] > 1:
        cc.dilation = dil[1]
        cc.dilation_y = dil[0]
    ws, _ = E.split_specs(node)
    # ConvLayerBase vs ConvTransLayerBase calc_parameter_size
    psize = (channels if trans else num_filters) * cc.filter_channels * kh * kw
    default_attr = ParamAttr(
        initial_mean=0.0,
        initial_std=(2.0 / (cc.filter_size ** 2 * channels)) ** 0.5,
    )
    E.input_param(lc, 0, ws[0], psize, [], default_attr=default_attr)
    lc.size = num_filters * out_y * out_x
    lc.height, lc.width = out_y, out_x
    if lc.shared_biases:
        E.bias_param(lc, node, num_filters, dims=[num_filters, 1])
    else:
        E.bias_param(lc, node, lc.size, dims=[lc.size, 1])


@emits("pool")
def _pool(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    lc.ClearField("size")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    kh, kw = a["pool_size"]
    sh, sw = a["stride"]
    ph, pw = a.get("padding", (0, 0))
    pc = lc.inputs[0].pool_conf
    pc.pool_type = {"max": "max-projection", "average": "avg-projection"}.get(
        a["pool_type"], a["pool_type"])
    pc.channels = channels
    pc.size_x = kw
    pc.stride = sw
    pc.size_y = kh
    pc.stride_y = sh
    pc.img_size, pc.img_size_y = get_img_size(parent, channels)
    pc.padding = pw
    pc.padding_y = ph
    ceil_mode = a.get("ceil_mode", True)
    pc.output_x = cnn_output_size(pc.img_size, pc.size_x, pc.padding,
                                  pc.stride, not ceil_mode)
    pc.output_y = cnn_output_size(pc.img_size_y, pc.size_y, pc.padding_y,
                                  pc.stride_y, not ceil_mode)
    lc.size = pc.output_x * pc.output_y * channels
    lc.height, lc.width = pc.output_y, pc.output_x


@emits("norm")
def _norm(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    lc.ClearField("size")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    nc = lc.inputs[0].norm_conf
    nc.norm_type = a.get("norm_type", "cmrnorm-projection")
    nc.channels = channels
    nc.size = a["size"]
    nc.scale = a.get("scale", 0.0128)  # img_cmrnorm_layer default alpha
    E.set_num(nc, "pow", a.get("power", 0.75))
    nc.blocked = a.get("blocked", False)
    nc.img_size, nc.img_size_y = get_img_size(parent, channels)
    nc.output_x = nc.img_size
    nc.output_y = nc.img_size_y
    if nc.norm_type == "cmrnorm-projection":
        nc.scale /= nc.size
    else:
        nc.scale /= nc.size ** 2
    lc.size = nc.output_x * nc.output_y * channels
    lc.height, lc.width = nc.output_y, nc.output_x


@emits("batch_norm")
def _batch_norm(E, node):
    a = node.attrs
    lc = E.layer(node)
    lc.ClearField("size")
    parent = node.parents[0]
    # reference adds two extra self-inputs for the moving stats
    # (config_parser.py:2425-2434)
    for _ in range(2):
        lc.inputs.add().input_layer_name = parent.name
    channels = a.get("channels") or parent.depth
    ic = lc.inputs[0].image_conf
    ic.channels = channels
    img_size_set = parent.width > 0 or parent.height > 0
    if a.get("img3D"):
        # parse_image3d: x/y from width/height, z from the layer depth
        ic.img_size = parent.width
        ic.img_size_y = parent.height
        ic.img_size_z = parent.depth
        lc.size = ic.img_size * ic.img_size_y * ic.img_size_z * channels
        lc.height, lc.width = ic.img_size_y, ic.img_size
        lc.depth = ic.img_size_z
    else:
        if parent.size % channels == 0 and (parent.size // channels) >= 1:
            try:
                ic.img_size, ic.img_size_y = get_img_size(parent, channels)
            except EnforceError:  # non-square pixels: 1-D geometry stands
                ic.img_size = parent.size // channels
                ic.img_size_y = 1
        if img_size_set:
            lc.size = ic.img_size * ic.img_size_y * channels
            lc.height, lc.width = ic.img_size_y, ic.img_size
            lc.depth = 1
        else:
            lc.size = parent.size
    if a.get("use_global_stats") is not None:
        lc.use_global_stats = a["use_global_stats"]
    lc.moving_average_fraction = a.get("moving_average_fraction", 0.9)
    psize = channels
    ws, bias = E.split_specs(node)
    default_w = ParamAttr(initial_mean=1.0, initial_std=0.0)
    E.input_param(lc, 0, ws[0], psize, [], default_attr=default_w)
    stat_attr = ParamAttr(initial_std=0.0, initial_mean=0.0, is_static=True)
    extra = {"is_shared": True}
    for i, sname in enumerate(a["stat_param_names"]):
        lc.inputs[1 + i].input_parameter_name = sname
        E.parameter(sname, psize, [1, psize], stat_attr, extra=extra)
    E.bias_param(lc, node, psize, dims=[1, psize], bias_spec=bias)


@emits("addto")
def _addto(E, node):
    lc = E.layer(node)
    E.bias_param(lc, node, node.size)
    lc.height, lc.width = node.height, node.width
    lc.depth = node.depth


@emits("concat")
def _concat(E, node):
    lc = E.layer(node)
    lc.height, lc.width = node.height, node.width
    lc.depth = node.depth


@emits("seqlastins")
def _seqlastins(E, node):
    a = node.attrs
    lc = E.layer(node)
    if a.get("select_first"):
        lc.select_first = True
    lc.trans_type = a.get("trans_type", "non-seq")
    lc.seq_pool_stride = a.get("stride", -1)
    E.bias_param(lc, node, node.size)


@emits("expand")
def _expand(E, node):
    lc = E.layer(node)
    lc.trans_type = node.attrs.get("trans_type", "non-seq")
    E.bias_param(lc, node, node.size)


@emits("average", "max")
def _seq_pool(E, node):
    a = node.attrs
    lc = E.layer(node)
    if node.layer_type == "average":
        lc.average_strategy = a.get("average_strategy", "average")
    if a.get("output_max_index") is not None:
        lc.output_max_index = a["output_max_index"]
    lc.trans_type = a.get("trans_type", "non-seq")
    lc.seq_pool_stride = a.get("stride", -1)
    E.bias_param(lc, node, node.size)


# -- cost layers -----------------------------------------------------------

_COST_TYPES = (
    "multi-class-cross-entropy",
    "mse",
    "square_error",
    "rank-cost",
    "lambda_cost",
    "multi_class_cross_entropy_with_selfnorm",
    "sum_cost",
    "huber_regression",
    "huber_classification",
    "multi_binary_label_cross_entropy",
    "smooth_l1",
    "soft_binary_class_cross_entropy",
)


@emits(*_COST_TYPES)
def _cost(E, node):
    a = node.attrs
    size = node.size or 1
    if node.layer_type == "multi_class_cross_entropy_with_selfnorm":
        size = 0  # reference creates it with size 0 (not printed)
    lc = E.layer(node, active_type="", size=size)
    if node.layer_type == "lambda_cost":
        lc.NDCG_num = a.get("NDCG_num", 5)
        lc.max_sort_size = a.get("max_sort_size", -1)
        return  # lambda_cost prints no coeff
    if node.layer_type == "multi_class_cross_entropy_with_selfnorm":
        lc.softmax_selfnorm_alpha = a.get("softmax_selfnorm_alpha", 0.1)
    lc.coeff = float(a.get("coeff", 1.0))
    if node.layer_type == "huber_regression":
        lc.delta = a.get("delta", 1.0)
    if a.get("metric"):
        ev_type, ev_inputs = a["metric"]
        if ev_type == "classification_error":
            E.evaluator(
                "classification_error",
                "classification_error_evaluator",
                list(ev_inputs),
            )


@emits("ctc")
def _ctc2(E, node):
    lc = E.layer(node, active_type="")
    lc.norm_by_times = node.attrs.get("norm_by_times", False)


@emits("warp_ctc")
def _warp_ctc(E, node):
    lc = E.layer(node, active_type="")
    lc.norm_by_times = node.attrs.get("norm_by_times", False)
    lc.blank = node.attrs.get("blank", 0)


@emits("recurrent")
def _recurrent(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    d = node.size
    E.input_param(lc, 0, ws[0], d * d, [d, d])
    E.bias_param(lc, node, d)
    lc.reversed = node.attrs.get("reverse", False)


@emits("lstmemory")
def _lstmemory(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    d = node.size
    # reference LstmLayer (config_parser.py): w0 size 4*d*d dims [d, d, 4];
    # bias 7*d (gates + peepholes) dims [1, 7d]
    E.input_param(lc, 0, ws[0], d * d * 4, [d, d, 4])
    E.bias_param(lc, node, 7 * d, dims=[1, 7 * d])
    lc.reversed = node.attrs.get("reverse", False)
    lc.active_gate_type = node.attrs.get("active_gate_type", "sigmoid")
    lc.active_state_type = node.attrs.get("active_state_type", "tanh")


@emits("gated_recurrent")
def _gated_recurrent(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    d = node.size
    E.input_param(lc, 0, ws[0], d * d * 3, [d, 3 * d])
    E.bias_param(lc, node, 3 * d, dims=[1, 3 * d])
    lc.reversed = node.attrs.get("reverse", False)
    lc.active_gate_type = node.attrs.get("active_gate_type", "sigmoid")


@emits("hsigmoid")
def _hsigmoid(E, node):
    lc = E.layer(node, active_type="", size=1)
    ws, _ = E.split_specs(node)
    n = node.attrs["num_classes"]
    for i, spec in enumerate(ws):
        p = node.parents[i]
        E.input_param(lc, i, spec, (n - 1) * p.size, [n - 1, p.size])
    E.bias_param(lc, node, n - 1, dims=[1, n - 1])
    lc.num_classes = n


@emits("subseq")
def _subseq(E, node):
    E.layer(node)


@emits("switch_order")
def _switch_order(E, node):
    lc = E.layer(node)
    rc = lc.reshape_conf
    rc.height_axis.extend(node.attrs.get("height_axis", []))
    rc.width_axis.extend(node.attrs.get("width_axis", []))


@emits("mdlstmemory")
def _mdlstm(E, node):
    lc = E.layer(node)
    lc.ClearField("size")
    lc.size = node.size
    ws, _ = E.split_specs(node)
    d = node.depth
    ndims = len(node.attrs["directions"])
    E.input_param(lc, 0, ws[0], d * d * (3 + ndims), [d, d * (3 + ndims)])
    E.bias_param(lc, node, (5 + 2 * ndims) * d, dims=[1, (5 + 2 * ndims) * d])
    lc.active_gate_type = node.attrs.get("active_gate_type", "sigmoid")
    lc.active_state_type = node.attrs.get("active_state_type", "tanh")
    for b in node.attrs["directions"]:
        lc.directions.append(bool(b))


@emits("cross_entropy_over_beam")
def _ce_over_beam(E, node):
    E.layer(node, active_type="", size=0)


@emits("print")
def _print(E, node):
    lc = E.layer(node, active_type="", size=0)
    lc.user_arg = node.attrs["user_arg"]


@emits("sampling_id", "resize", "row_l2_norm", "multiplex", "seqconcat",
       "seqreshape", "conv_shift", "out_prod", "sub_nested_seq", "eos",
       "trans", "convex_comb", "rotate", "crop")
def _plain(E, node):
    E.layer(node, active_type=node.attrs.get("active_type", ""))


@emits("clip")
def _clip(E, node):
    lc = E.layer(node, active_type="")
    cc = lc.inputs[0].clip_conf
    E.set_num(cc, "min", node.attrs["clip_min"])
    E.set_num(cc, "max", node.attrs["clip_max"])


@emits("featmap_expand")
def _featmap_expand(E, node):
    lc = E.layer(node)
    lc.num_filters = node.attrs["num_filters"]
    if node.attrs.get("user_arg"):
        lc.user_arg = node.attrs["user_arg"]


@emits("seq_slice")
def _seq_slice(E, node):
    lc = E.layer(node, active_type="")
    if "select_first" in node.attrs:
        lc.select_first = bool(node.attrs["select_first"])


@emits("kmax_seq_score")
def _kmax(E, node):
    lc = E.layer(node, active_type="", size=0)
    lc.beam_size = node.attrs["beam_size"]


@emits("prelu")
def _prelu(E, node):
    lc = E.layer(node, active_type="")
    ws, _ = E.split_specs(node)
    partial = node.attrs.get("partial_sum", 1)
    E.input_param(lc, 0, ws[0], node.size // partial, [])
    lc.partial_sum = partial


@emits("row_conv")
def _row_conv(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    ctx_len = node.attrs["context_len"]
    lc.inputs[0].row_conv_conf.context_length = ctx_len
    E.input_param(lc, 0, ws[0], ctx_len * node.size, [ctx_len, node.size])


@emits("scale_shift")
def _scale_shift(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    E.input_param(lc, 0, ws[0], 1, [1, 1])
    E.bias_param(lc, node, 1, dims=[1, 1])


@emits("maxout")
def _maxout(E, node):
    lc = E.layer(node, active_type="")
    parent = node.parents[0]
    channels = node.attrs.get("channels") or parent.depth
    mo = lc.inputs[0].maxout_conf
    mo.image_conf.channels = channels
    mo.image_conf.img_size, mo.image_conf.img_size_y = get_img_size(
        parent, channels
    )
    mo.groups = node.attrs["groups"]
    lc.size = parent.size // mo.groups
    lc.height, lc.width = mo.image_conf.img_size_y, mo.image_conf.img_size


@emits("pad")
def _pad(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    pc = lc.inputs[0].pad_conf
    pc.image_conf.channels = channels
    pc.image_conf.img_size, pc.image_conf.img_size_y = get_img_size(
        parent, channels
    )
    pc.pad_c.extend(a["pad_c"])
    pc.pad_h.extend(a["pad_h"])
    pc.pad_w.extend(a["pad_w"])
    out_ch = channels + sum(a["pad_c"])
    out_h = pc.image_conf.img_size_y + sum(a["pad_h"])
    out_w = pc.image_conf.img_size + sum(a["pad_w"])
    lc.size = out_ch * out_h * out_w
    lc.height, lc.width = out_h, out_w


@emits("spp")
def _spp(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    sc = lc.inputs[0].spp_conf
    sc.image_conf.channels = channels
    sc.image_conf.img_size, sc.image_conf.img_size_y = get_img_size(
        parent, channels
    )
    sc.pool_type = a["pool_type"]
    sc.pyramid_height = a["pyramid_height"]
    out_x = (4 ** sc.pyramid_height - 1) // 3
    lc.size = channels * out_x
    lc.height, lc.width = 1, out_x


@emits("bilinear_interp")
def _bilinear(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    bc = lc.inputs[0].bilinear_interp_conf
    bc.image_conf.channels = channels
    bc.image_conf.img_size, bc.image_conf.img_size_y = get_img_size(
        parent, channels
    )
    bc.out_size_x = a["out_size_x"]
    bc.out_size_y = a["out_size_y"]
    lc.size = channels * bc.out_size_x * bc.out_size_y
    lc.height, lc.width = bc.out_size_y, bc.out_size_x


@emits("blockexpand")
def _blockexpand(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    bc = lc.inputs[0].block_expand_conf
    bc.channels = channels
    bc.stride_x = a["stride_x"]
    bc.stride_y = a["stride_y"]
    bc.padding_x = a.get("padding_x", 0)
    bc.padding_y = a.get("padding_y", 0)
    bc.block_x = a["block_x"]
    bc.block_y = a["block_y"]
    # reference parse_block_expand takes img sizes from the helper args
    # (default 0), not from the input layer
    bc.img_size_x = a.get("img_size_x", 0)
    bc.img_size_y = a.get("img_size_y", 0)
    if bc.img_size_x > 0:
        bc.output_x = cnn_output_size(
            bc.img_size_x, bc.block_x, bc.padding_x, bc.stride_x, False
        )
        bc.output_y = cnn_output_size(
            bc.img_size_y, bc.block_y, bc.padding_y, bc.stride_y, False
        )
    else:
        bc.output_x = bc.output_y = 0
    lc.size = bc.block_x * bc.block_y * bc.channels


@emits("tensor")
def _tensor(E, node):
    lc = E.layer(node)
    ws, _ = E.split_specs(node)
    a, b = node.parents
    E.input_param(lc, 0, ws[0], node.size * a.size * b.size,
                  [a.size, b.size, node.size])
    E.bias_param(lc, node, node.size)


@emits("linear_comb")
def _linear_comb(E, node):
    E.layer(node)


@emits("slope_intercept")
def _slope_intercept(E, node):
    lc = E.layer(node, active_type="")
    E.set_num(lc, "slope", node.attrs.get("slope", 1.0))
    E.set_num(lc, "intercept", node.attrs.get("intercept", 0.0))


@emits("interpolation", "power", "scaling", "sum_to_one_norm")
def _weighted_pair(E, node):
    E.layer(node, active_type="")


@emits("cos", "cos_vm")
def _cos(E, node):
    lc = E.layer(node, active_type="")
    E.set_num(lc, "cos_scale", node.attrs.get("scale", 1.0))


@emits("crf")
def _crf(E, node):
    n = node.attrs.get("num_classes", node.size)
    lc = E.layer(node, active_type="", size=n)
    ws, _ = E.split_specs(node)
    E.input_param(lc, 0, ws[0], (n + 2) * n, [n + 2, n])
    lc.coeff = float(node.attrs.get("coeff", 1.0))


@emits("crf_decoding")
def _crf_decoding(E, node):
    n = node.attrs.get("num_classes")
    lc = E.layer(node, active_type="", size=n)
    ws, _ = E.split_specs(node)
    E.input_param(lc, 0, ws[0], (n + 2) * n, [n + 2, n])


@emits("nce")
def _nce(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="sigmoid", size=1)
    ws, _ = E.split_specs(node)
    n = a["num_classes"]
    for i, spec in enumerate(ws):
        p = node.parents[i]
        E.input_param(lc, i, spec, n * p.size, [n, p.size])
    E.bias_param(lc, node, n, dims=[1, n])
    lc.num_classes = n
    lc.num_neg_samples = a.get("num_neg_samples", 10)
    if a.get("neg_sampling_dist"):
        lc.neg_sampling_dist.extend(a["neg_sampling_dist"])


def _fill_conv_conf(cc, g: dict):
    for k, v in g.items():
        setattr(cc, k, v)


def _emit_mixed_items(E: Emitter, node, lc):
    """Shared by mixed/concat2: LayerInputConfig proj_confs, operator_confs,
    and projection parameters (≅ MixedLayer, config_parser.py:3387)."""
    for item in node.attrs["mixed_items"]:
        if item["kind"] == "proj":
            ic = lc.inputs[item["slot"]]
            pc = ic.proj_conf
            pc.type = item["type"]
            pc.name = item["pname"]
            pc.input_size = item["input_size"]
            pc.output_size = item["output_size"]
            proto = item["proto"]
            if item["type"] == "context":
                pc.context_start = proto["context_start"]
                pc.context_length = proto["context_length"]
                pc.trainable_padding = proto["trainable_padding"]
            if item["type"] == "identity_offset":
                pc.offset = proto["offset"]
            if item["type"] == "slice":
                for s, e in proto["slices"]:
                    sl = pc.slices.add()
                    sl.start, sl.end = s, e
            if "conv" in proto:
                _fill_conv_conf(pc.conv_conf, proto["conv"])
                pc.num_filters = proto["num_filters"]
            spec = item.get("spec")
            if spec is not None:
                ic.input_parameter_name = spec.name
                attr = spec.attr
                if attr is None or _is_default_attr(attr):
                    attr = item.get("default_emit_attr") or attr
                psize = 1
                for d in spec.shape:
                    psize *= d
                E.parameter(spec.name, psize, item["param_dims"] or [], attr)
        else:
            oc = lc.operator_confs.add()
            oc.type = item["type"]
            oc.input_indices.extend(item["indices"])
            oc.input_sizes.extend(item["input_sizes"])
            oc.output_size = item["output_size"]
            proto = item["proto"]
            if "dotmul_scale" in proto:
                E.set_num(oc, "dotmul_scale", proto["dotmul_scale"])
            if "conv" in proto:
                _fill_conv_conf(oc.conv_conf, proto["conv"])
                oc.num_filters = proto["num_filters"]


@emits("mixed")
def _mixed(E, node):
    lc = E.layer(node)
    _emit_mixed_items(E, node, lc)
    E.bias_param(lc, node, node.size)


@emits("concat2")
def _concat2(E, node):
    lc = E.layer(node)
    _emit_mixed_items(E, node, lc)
    bias_size = node.attrs.get("bias_size", 0)
    if bias_size:
        # config_parser.py:3544-3553: conv projections share a per-channel
        # bias (psize = sum num_filters); others bias the full output
        if node.attrs.get("shared_biases"):
            lc.shared_biases = True
        lc.bias_size = bias_size
        E.bias_param(lc, node, bias_size)


@emits("detection_output")
def _detection_output(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    dc = lc.inputs[0].detection_output_conf
    dc.num_classes = a["num_classes"]
    dc.nms_threshold = a["nms_threshold"]
    dc.nms_top_k = a["nms_top_k"]
    dc.background_id = a.get("background_id", 0)
    dc.input_num = a["input_num"]
    dc.keep_top_k = a["keep_top_k"]
    dc.confidence_threshold = a["confidence_threshold"]


@emits("multibox_loss")
def _multibox_loss(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    mc = lc.inputs[0].multibox_loss_conf
    mc.num_classes = a["num_classes"]
    mc.overlap_threshold = a["overlap_threshold"]
    mc.neg_pos_ratio = a["neg_pos_ratio"]
    mc.neg_overlap = a["neg_overlap"]
    mc.background_id = a.get("background_id", 0)
    mc.input_num = a["input_num"]


@emits("scale_sub_region")
def _scale_sub_region(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    parent = node.parents[0]
    channels = a.get("channels") or parent.depth
    sc = lc.inputs[0].scale_sub_region_conf
    sc.image_conf.channels = channels
    sc.image_conf.img_size, sc.image_conf.img_size_y = get_img_size(
        parent, channels
    )
    E.set_num(sc, "value", a["value"])
    lc.height = sc.image_conf.img_size_y
    lc.width = sc.image_conf.img_size


@emits("conv3d", "deconv3d")
def _conv3d(E, node):
    a = node.attrs
    trans = a["trans"]
    lc = E.layer(node)
    lc.ClearField("size")
    num_filters = a["num_filters"]
    lc.num_filters = num_filters
    lc.shared_biases = a.get("shared_biases", True)
    channels = a["channels"]
    kx, ky, kz = a["filter_size"]
    sx, sy, sz = a["stride"]
    px, py, pz = a["padding"]
    d_in, h_in, w_in = a["img_vol"]
    cc = lc.inputs[0].conv_conf
    cc.filter_size, cc.filter_size_y, cc.filter_size_z = kx, ky, kz
    cc.channels = channels
    cc.stride, cc.stride_y, cc.stride_z = sx, sy, sz
    cc.padding, cc.padding_y, cc.padding_z = px, py, pz
    cc.groups = a.get("groups", 1)
    cc.caffe_mode = True
    if not trans:
        cc.filter_channels = channels // cc.groups
        cc.img_size, cc.img_size_y, cc.img_size_z = w_in, h_in, d_in
        cc.output_x = cnn_output_size(w_in, kx, px, sx, True)
        cc.output_y = cnn_output_size(h_in, ky, py, sy, True)
        cc.output_z = cnn_output_size(d_in, kz, pz, sz, True)
        out = (cc.output_z, cc.output_y, cc.output_x)
    else:
        cc.filter_channels = num_filters // cc.groups
        cc.output_x, cc.output_y, cc.output_z = w_in, h_in, d_in
        cc.img_size = cnn_image_size(w_in, kx, px, sx, True)
        cc.img_size_y = cnn_image_size(h_in, ky, py, sy, True)
        cc.img_size_z = cnn_image_size(d_in, kz, pz, sz, True)
        out = (cc.img_size_z, cc.img_size_y, cc.img_size)
    ws, _ = E.split_specs(node)
    psize = num_filters * cc.filter_channels * kx * ky * kz
    default_attr = ParamAttr(
        initial_mean=0.0,
        initial_std=(2.0 / (cc.filter_size ** 2 * channels)) ** 0.5,
    )
    E.input_param(lc, 0, ws[0], psize, [], default_attr=default_attr)
    lc.size = num_filters * out[0] * out[1] * out[2]
    lc.height, lc.width = out[1], out[2]
    lc.depth = out[0]
    if lc.shared_biases:
        E.bias_param(lc, node, num_filters, dims=[num_filters, 1])
    else:
        E.bias_param(lc, node, lc.size, dims=[lc.size, 1])


@emits("pool3d")
def _pool3d(E, node):
    a = node.attrs
    lc = E.layer(node, active_type="")
    lc.ClearField("size")
    channels = a["channels"]
    kx, ky, kz = a["pool_size"]
    sx, sy, sz = a["stride"]
    px, py, pz = a["padding"]
    d_in, h_in, w_in = a["img_vol"]
    pc = lc.inputs[0].pool_conf
    pc.pool_type = ("max-projection" if a["pool_type"] == "max"
                    else "avg-projection")
    pc.channels = channels
    pc.size_x, pc.stride, pc.padding = kx, sx, px
    pc.img_size = w_in
    pc.output_x = cnn_output_size(w_in, kx, px, sx, False)
    pc.size_y, pc.stride_y, pc.padding_y = ky, sy, py
    pc.img_size_y = h_in
    pc.output_y = cnn_output_size(h_in, ky, py, sy, False)
    pc.size_z, pc.stride_z, pc.padding_z = kz, sz, pz
    pc.img_size_z = d_in
    pc.output_z = cnn_output_size(d_in, kz, pz, sz, False)
    lc.size = channels * pc.output_x * pc.output_y * pc.output_z
    lc.height, lc.width, lc.depth = pc.output_y, pc.output_x, pc.output_z


@emits("recurrent_layer_group")
def _recurrent_group_emit(E, node):
    """≅ RecurrentLayerGroupBegin/End (config_parser.py): a marker layer +
    a sub_model with scatter/gather agents, memory agents, and the step
    layers (all "@group"-suffixed), then gather agents at root."""
    g = node.attrs["group"]
    E.mc.type = "recurrent_nn"
    gname = g["marker"]

    marker = E.mc.layers.add()
    marker.name = gname
    marker.type = "recurrent_layer_group"
    marker.active_type = ""
    E.root.layer_names.append(gname)

    sub = E.mc.sub_models.add()
    sub.name = gname
    sub.is_recurrent_layer_group = True
    sub.reversed = node.attrs.get("reverse", False)

    prev = E.cur_submodel
    E.cur_submodel = sub
    for ph, outer in g["scatter"]:
        lc = E.mc.layers.add()
        lc.name = ph.name
        lc.type = "scatter_agent"
        lc.size = ph.size
        lc.active_type = ""
        sub.layer_names.append(ph.name)
    for member in g["members"]:
        if member.layer_type == "__memory__":
            lc = E.mc.layers.add()
            lc.name = member.name
            lc.type = "agent"
            lc.size = member.size
            lc.active_type = ""
            sub.layer_names.append(member.name)
            continue
        fn = EMITTERS.get(member.layer_type)
        enforce(fn is not None,
                f"no proto emitter for in-group layer type "
                f"{member.layer_type!r} ({member.name!r})")
        fn(E, member)
    E.cur_submodel = prev

    # gather agents at root (one per output)
    outs = g["outs"]
    bases = g["out_bases"]
    gather_names = []
    for o, base in zip(outs, bases):
        lc = E.mc.layers.add()
        lc.name = base
        lc.type = "gather_agent"
        lc.size = o.size
        lc.active_type = ""
        E.root.layer_names.append(base)
        gather_names.append(base)

    for mem, tgt in g["memories"]:
        m = sub.memories.add()
        m.layer_name = tgt.name
        m.link_name = mem.name
    for ph, outer in g["scatter"]:
        il = sub.in_links.add()
        il.layer_name = outer.name
        il.link_name = ph.name
    for o, base in zip(outs, bases):
        ol = sub.out_links.add()
        ol.layer_name = o.name
        ol.link_name = base


@emits("beam_search")
def _beam_search_emit(E, node):
    """Generation-time recurrent group (beam_search, layers.py:4145).

    The reference emits a full recurrent_layer_group whose step layers run
    host-side beam search (RecurrentGradientMachine::generateSequence);
    here generation compiles to one lax.scan, so the emission is a marker
    layer + a sub_model carrying the GeneratorConfig (max_num_frames /
    beam_size / num_results_per_sample) and the "__beam_search_predict__"
    out-link.  No reference protostr golden exists for generation configs;
    runtime behavior is locked by tests/test_generation_golden.py against
    the reference's r1.test.* files instead."""
    a = node.attrs
    E.mc.type = "recurrent_nn"
    marker = E.mc.layers.add()
    marker.name = node.name
    marker.type = "recurrent_layer_group"
    marker.active_type = ""
    E.root.layer_names.append(node.name)

    sub = E.mc.sub_models.add()
    sub.name = node.name
    sub.is_recurrent_layer_group = True
    gen = sub.generator
    gen.max_num_frames = a["max_length"]
    gen.eos_layer_name = ""
    gen.beam_size = a["beam_size"]
    gen.num_results_per_sample = a.get("num_results_per_sample",
                                       a["beam_size"])

    out = E.mc.layers.add()
    out.name = "__beam_search_predict__"
    out.type = "gather_agent"
    out.size = node.size
    out.active_type = ""
    E.root.layer_names.append("__beam_search_predict__")
    ol = sub.out_links.add()
    ol.layer_name = node.name
    ol.link_name = "__beam_search_predict__"


@emits("gather_selector")
def _gather_selector(E, node):
    # the gather agent was already emitted by the group node
    pass


@emits("get_output")
def _get_output(E, node):
    lc = E.layer(node, active_type="", inputs=False)
    src = node.attrs["arg_of_node"]
    ic = lc.inputs.add()
    ic.input_layer_name = src.name
    ic.input_layer_argument = node.attrs.get("arg_name", "state")


@emits("lstm_step")
def _lstm_step(E, node):
    lc = E.layer(node)
    d = node.size
    E.bias_param(lc, node, 3 * d, dims=[1, 3 * d])
    lc.active_gate_type = node.attrs.get("active_gate_type", "sigmoid")
    lc.active_state_type = node.attrs.get("active_state_type", "tanh")


@emits("gru_step")
def _gru_step(E, node):
    lc = E.layer(node)
    d = node.size
    ws, _ = E.split_specs(node)
    E.input_param(lc, 0, ws[0], d * 3 * d, [d, 3 * d])
    E.bias_param(lc, node, 3 * d, dims=[1, 3 * d])
    lc.active_gate_type = node.attrs.get("active_gate_type", "sigmoid")


@emits("maxid")
def _maxid(E, node):
    lc = E.layer(node, active_type="")
    if node.attrs.get("beam_size") is not None:
        lc.beam_size = node.attrs["beam_size"]


@emits("dropout")
def _dropout(E, node):
    E.layer(node)


# ---------------------------------------------------------------------------
# top level
# ---------------------------------------------------------------------------


_SKIP_TYPES = {"__memory__", "__step_input__", "__static_input__"}


def emit_model_config(registry, input_names, output_names,
                      settings: dict | None = None, with_emitter: bool = False,
                      target=None):
    E = Emitter(settings, target=target)
    for node in registry:
        if node.attrs.get("__in_group__") or node.layer_type in _SKIP_TYPES:
            continue  # emitted by their recurrent_layer_group node
        if node.attrs.get("__hidden__"):
            continue  # runtime-only companions (e.g. crf_decoding "#ids")
        fn = EMITTERS.get(node.layer_type)
        enforce(
            fn is not None,
            f"no proto emitter for layer type {node.layer_type!r} "
            f"(layer {node.name!r})",
        )
        fn(E, node)
    from paddle_tpu.evaluator import declare as _declare

    for spec in _declare.collect():
        E.evaluator(spec.type, spec.name, list(spec.input_layers),
                    **spec.fields)
    E.finalize(input_names, output_names)
    return (E.mc, E) if with_emitter else E.mc


def model_config_protostr(registry, input_names, output_names,
                          settings=None) -> str:
    mc, E = emit_model_config(registry, input_names, output_names, settings,
                              with_emitter=True)
    return to_protostr(mc, E.int_style)
