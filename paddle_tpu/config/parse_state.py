"""Config-parse session state: Inputs()/Outputs()/outputs().

≅ the reference's config_parser globals (``g_config.model_config.
input_layer_names`` etc., config_parser.py:209-240) plus the
``outputs()`` DFS input/output inference from ``networks.py:1503``.
"""

from __future__ import annotations

from paddle_tpu.layers.base import LayerOutput


class ParseState:
    def __init__(self):
        self.input_layer_names: list[str] = []
        self.output_layer_names: list[str] = []
        # (files, module, obj, args) for train and test providers
        self.data_config: dict | None = None
        self.test_data_config: dict | None = None

    def reset(self):
        self.__init__()


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    """≅ data_sources.define_py_data_sources2: record PyDataProvider2
    DataConfigs for the trainer (DataConfig.proto fields load_data_*)."""

    def pick(x, idx):
        return x[idx] if isinstance(x, (list, tuple)) else x

    if train_list is not None:
        STATE.data_config = dict(
            files=train_list, module=pick(module, 0), obj=pick(obj, 0),
            args=pick(args, 0) if args is not None else "")
    if test_list is not None:
        STATE.test_data_config = dict(
            files=test_list, module=pick(module, 1), obj=pick(obj, 1),
            args=pick(args, 1) if args is not None else "")


STATE = ParseState()


# ≅ config_parser.py:116-123 g_default_* globals, set by the default_*()
# config functions below and consumed by ParamAttr.make_initializer /
# proto emission.  Reset per parse.
G_DEFAULTS: dict = {"initial_std": None, "initial_mean": None,
                    "decay_rate": None, "momentum": None, "device": None,
                    "initial_strategy": None, "initial_smart": None,
                    "num_batches_regularization": None}


def reset_defaults() -> None:
    for k in G_DEFAULTS:
        G_DEFAULTS[k] = None


def default_initial_std(val) -> None:
    """≅ default_initial_std (config_parser.py:54)."""
    G_DEFAULTS["initial_std"] = float(val)


def default_initial_mean(val) -> None:
    G_DEFAULTS["initial_mean"] = float(val)


def default_decay_rate(val) -> None:
    """≅ default_decay_rate (config_parser.py:57)."""
    G_DEFAULTS["decay_rate"] = float(val)


def default_momentum(val) -> None:
    """≅ default_momentum (config_parser.py:60): per-parameter momentum
    default.  Flows into ParamSpec.momentum (layers/api.py _wspec) and is
    applied by the SGD/Momentum/SparseMomentum update rules, exactly as
    ``paraConfig.momentum()`` drives ``sgdUpdate`` in the reference."""
    G_DEFAULTS["momentum"] = float(val)


def _warn_unapplied(name):
    from paddle_tpu.core import logger as log

    log.warning("%s: accepted for config parity; not applied by this "
                "runtime", name)


def default_initial_strategy(val) -> None:
    G_DEFAULTS["initial_strategy"] = int(val)


def default_initial_smart(val) -> None:
    G_DEFAULTS["initial_smart"] = bool(val)


def default_num_batches_regularization(val) -> None:
    G_DEFAULTS["num_batches_regularization"] = int(val)
    _warn_unapplied("default_num_batches_regularization")


def default_device(val) -> None:
    """≅ default_device (config_parser.py:123): accepted for config
    parity; placement on TPU is the mesh's job, not per-layer device ids."""
    G_DEFAULTS["device"] = val


def Inputs(*names: str) -> None:
    """≅ config_parser Inputs() (config_parser.py:209)."""
    STATE.input_layer_names.extend(names)


def Outputs(*names: str) -> None:
    """≅ config_parser Outputs() (config_parser.py:231)."""
    STATE.output_layer_names.extend(names)


def HasInputsSet() -> bool:
    return len(STATE.input_layer_names) != 0


def SimpleData(files=None, feat_dim=None, context_len=None,
               buffer_capacity=None, **kw) -> dict:
    """≅ SimpleData (config_parser.py:1049): dense rows from text files."""
    return {"type": "simple", "files": files, "feat_dim": feat_dim,
            "context_len": context_len, "buffer_capacity": buffer_capacity}


def PyData(files=None, type=None, load_data_module=None,
           load_data_object=None, load_data_args="", **kw) -> dict:
    """≅ PyData (config_parser.py:1066)."""
    return {"type": "py", "files": files, "module": load_data_module,
            "obj": load_data_object, "args": load_data_args}


def ProtoData(files=None, usage_ratio=None, **kw) -> dict:
    """≅ ProtoData (config_parser.py:1036): binary DataFormat.proto files
    (the ProtoDataProvider source; reader in
    :mod:`paddle_tpu.reader.proto_data`)."""
    return {"type": "proto", "files": files, "usage_ratio": usage_ratio}


def MultiData(data_configs=(), **kw) -> dict:
    """≅ MultiData: several sub-providers feeding one network
    (MultiDataProvider.h:24)."""
    return {"type": "multi", "sub": list(data_configs)}


def TrainData(data_config: dict, async_load_data=None) -> None:
    """≅ TrainData (config_parser.py:1178)."""
    STATE.data_config = dict(data_config)


def TestData(data_config: dict, async_load_data=None) -> None:
    """≅ TestData (config_parser.py:1190)."""
    STATE.test_data_config = dict(data_config)


def inputs(layers, *args) -> None:
    """≅ networks.inputs (networks.py:1485): declare input order."""
    if isinstance(layers, LayerOutput):
        layers = [layers]
    layers = list(layers) + list(args)
    Inputs(*[l.name for l in layers])


def outputs(layers, *args) -> None:
    """≅ networks.outputs (networks.py:1503): declare outputs; if inputs are
    unset, infer both by DFS — data layers become inputs, v1-cost-typed
    ancestors become outputs (falling back to the given layers)."""
    if isinstance(layers, LayerOutput):
        layers = [layers]
    layers = list(layers) + list(args)
    assert layers

    if HasInputsSet():
        Outputs(*[l.name for l in layers])
        return

    traveled = set()

    def dfs(layer: LayerOutput, predicate):
        if id(layer) in traveled:
            return []
        traveled.add(id(layer))
        retv = []
        for p in layer.attrs.get("dfs_parents", layer.parents):
            retv.extend(dfs(p, predicate))
        if predicate(layer):
            retv.append(layer)
        return retv

    inputs: list[LayerOutput] = []
    outs: list[LayerOutput] = []
    for each in layers:
        inputs.extend(dfs(each, lambda x: x.layer_type == "data"))
    traveled.clear()
    for each in layers:
        outs.extend(dfs(each, lambda x: x.attrs.get("v1_cost", False)))

    final_inputs, final_outputs = [], []
    for x in inputs:
        if x.name not in final_inputs:
            final_inputs.append(x.name)
    for x in outs:
        if x.name not in final_outputs:
            final_outputs.append(x.name)
    if not final_outputs:
        final_outputs = [l.name for l in layers]
    else:
        # explicitly-passed non-cost layers stay outputs (matches reference
        # goldens, e.g. test_cost_layers_with_weight's nce output)
        for l in layers:
            if l.name not in final_outputs:
                final_outputs.append(l.name)
    Inputs(*final_inputs)
    Outputs(*final_outputs)
