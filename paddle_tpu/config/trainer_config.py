"""Trainer/optimization config — successor of ``proto/TrainerConfig.proto:21-140``
(OptimizationConfig: batch_size, learning_rate + decay schedule, momentum,
regularization, gradient clipping, model averaging) and the
``trainer_config_helpers/optimizers.py settings()`` entry point."""

from __future__ import annotations

import dataclasses
import json
from typing import Any


@dataclasses.dataclass
class OptimizationConfig:
    """≅ TrainerConfig.proto OptimizationConfig."""

    batch_size: int = 1
    learning_rate: float = 0.01
    learning_method: str = "sgd"  # sgd|momentum|adam|adagrad|adadelta|rmsprop|...
    momentum: float = 0.0
    # lr schedule (≅ LearningRateScheduler.cpp: constant/exp/poly/linear)
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    learning_rate_warmup_steps: int = 0
    # regularization (≅ Regularizer.h)
    l1_rate: float = 0.0
    l2_rate: float = 0.0
    # clipping
    gradient_clipping_threshold: float = 0.0
    # model averaging (≅ AverageOptimizer)
    average_window: float = 0.0
    max_average_window: int = 0
    # adam etc. hyperparams
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    ada_epsilon: float = 1e-6
    ada_rou: float = 0.95
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    def serialize(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)


@dataclasses.dataclass
class TrainerConfig:
    """≅ TrainerConfig.proto: model + optimization + data configs."""

    opt_config: OptimizationConfig = dataclasses.field(default_factory=OptimizationConfig)
    save_dir: str = ""
    test_period: int = 0
    num_passes: int = 1


def settings(batch_size: int = 1, learning_rate: float = 0.01,
             learning_method=None, regularization=None,
             gradient_clipping_threshold: float = 0.0, model_average=None,
             learning_rate_decay_a: float = 0.0, learning_rate_decay_b: float = 0.0,
             learning_rate_schedule: str = "constant", **kw) -> OptimizationConfig:
    """≅ trainer_config_helpers.optimizers.settings:28-358 — v1 config entry."""
    cfg = OptimizationConfig(
        batch_size=batch_size,
        learning_rate=learning_rate,
        learning_rate_decay_a=learning_rate_decay_a,
        learning_rate_decay_b=learning_rate_decay_b,
        learning_rate_schedule=learning_rate_schedule,
        gradient_clipping_threshold=gradient_clipping_threshold,
    )
    if learning_method is not None:
        # accepts optimizer objects from paddle_tpu.optimizer or strings
        cfg.learning_method = getattr(learning_method, "name", str(learning_method))
        for field in ("momentum", "adam_beta1", "adam_beta2", "adam_epsilon"):
            if hasattr(learning_method, field):
                setattr(cfg, field, getattr(learning_method, field))
    if regularization is not None:
        cfg.l1_rate = getattr(regularization, "l1_rate", 0.0)
        cfg.l2_rate = getattr(regularization, "l2_rate", 0.0)
    if model_average is not None:
        cfg.average_window = getattr(model_average, "average_window", 0.0)
        cfg.max_average_window = getattr(model_average, "max_average_window", 0)
    cfg.extra.update(kw)
    return cfg
