"""Topology — the compiled form of a layer DAG.

Reference: ``python/paddle/v2/topology.py`` wraps the ModelConfig proto built
by ``config_parser.py``; C++ ``NeuralNetwork`` then interprets it layer by
layer (``NeuralNetwork.cpp:245-327``).  Here Topology owns the DAG directly
and exposes:

- ``param_specs()`` / ``state_specs()`` — what ``parameters.create`` materializes
  (≅ ParameterConfig extraction);
- ``forward(...)`` — one pure evaluation of the whole graph, the function that
  ``jax.jit``/``jax.grad`` consume (≅ GradientMachine::forward, with backward
  provided by autodiff instead of ``Layer::backward``);
- ``serialize()`` — a stable JSON description standing in for the protostr
  golden-file tests (``trainer_config_helpers/tests/configs``)."""

from __future__ import annotations

import hashlib
import json
from typing import Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.parameters import ParamSpec
from paddle_tpu.layers.base import Context, LayerOutput, StateSpec, evaluate, topo_sort


class Topology:
    def __init__(self, outputs: LayerOutput | Sequence[LayerOutput], extra_layers=None):
        if isinstance(outputs, LayerOutput):
            outputs = [outputs]
        self.outputs: list[LayerOutput] = list(outputs)
        extra = list(extra_layers) if extra_layers else []
        self.extra_layers: list[LayerOutput] = extra
        self.nodes: list[LayerOutput] = topo_sort(self.outputs + extra)
        names = [n.name for n in self.nodes]
        enforce(len(names) == len(set(names)), "duplicate layer names in topology")

    # -- specs ---------------------------------------------------------------
    def data_layers(self) -> dict[str, LayerOutput]:
        """Input layers in graph order (≅ Topology.data_layers())."""
        return {n.name: n for n in self.nodes if n.layer_type == "data"}

    def param_specs(self) -> list[ParamSpec]:
        seen: dict[str, ParamSpec] = {}
        for n in self.nodes:
            for s in n.param_specs:
                if s.name not in seen:
                    seen[s.name] = s
        return list(seen.values())

    def state_specs(self) -> list[StateSpec]:
        out: list[StateSpec] = []
        seen = set()
        for n in self.nodes:
            for s in n.state_specs:
                if s.name not in seen:
                    seen.add(s.name)
                    out.append(s)
        return out

    def init_states(self) -> dict[str, jax.Array]:
        return {
            s.name: jnp.full(s.shape, s.init_value, s.dtype or jnp.float32)
            for s in self.state_specs()
        }

    def metrics(self) -> list[tuple[str, str, str, str]]:
        """(metric_kind, pred_layer, label_layer, tag) tuples auto-attached by
        cost layers (≅ classification_cost's auto classification_error
        evaluator)."""
        out = []
        for n in self.nodes:
            # metric_runtime overrides where the RUNTIME reads values
            # (e.g. the fused-CE cost points classification_error at its
            # logits companion — argmax-equal to the probs) while the
            # emitted evaluator block keeps the reference layer names
            m = n.attrs.get("metric_runtime") or n.attrs.get("metric")
            if m:
                names = m[1] if isinstance(m[1], (list, tuple)) else [m[1], m[2]]
                out.append((m[0], names[0], names[1], n.name))
        return out

    # -- execution -------------------------------------------------------------
    def forward(
        self,
        params: dict[str, jax.Array],
        states: dict[str, jax.Array],
        feed: dict,
        is_train: bool,
        key: jax.Array | None = None,
        taps: dict | None = None,
    ):
        """Evaluate every node; returns ({layer_name: value}, new_states)."""
        ctx = Context(is_train=is_train, key=key)
        return evaluate(self.nodes, ctx, params, states, feed, taps=taps)

    # -- serialization (golden-config tests) ----------------------------------
    def serialize(self) -> str:
        doc = {
            "layers": [n.config_record() for n in self.nodes],
            "input_layer_names": list(self.data_layers()),
            "output_layer_names": [o.name for o in self.outputs],
        }
        return json.dumps(doc, indent=2, sort_keys=True)

    def digest(self) -> str:
        return hashlib.sha256(self.serialize().encode()).hexdigest()[:16]

    def proto(self) -> str:
        """Kept under the v2 name; returns the JSON config text."""
        return self.serialize()
