"""Model configuration & compilation — successor of the reference's
config stack: ``python/paddle/trainer/config_parser.py`` (layer calls →
ModelConfig proto), ``python/paddle/v2/topology.py`` (proto from outputs), and
``TrainerConfig.proto``.  The proto interpreter (GradientMachine/Executor) is
replaced by trace-to-XLA compilation of the layer DAG."""

from paddle_tpu.config.topology import Topology  # noqa: F401
from paddle_tpu.config.trainer_config import OptimizationConfig, TrainerConfig  # noqa: F401
