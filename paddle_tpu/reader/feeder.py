"""DataFeeder — successor of ``python/paddle/v2/data_feeder.py:28``
(DataProviderConverter → SWIG Arguments).  Converts a Python batch (list of
sample tuples) into the jit feed dict: dense arrays, int ids, or
SequenceBatch/NestedSequenceBatch for *_sequence types.  Sparse inputs are
densified host-side (the TPU path treats them as dense one/multi-hot rows —
embedding lookups take the integer-sequence path instead)."""

from __future__ import annotations

from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.lod import (
    SequenceBatch,
    bucket_length,
    from_nested_ragged,
    from_ragged,
)
from paddle_tpu.layers.data_type import DataKind, SeqType


def _densify_ids(rows, dim: int) -> np.ndarray:
    """id lists (one per row) -> dense 0/1 [len(rows), dim].

    One flat fancy-indexed scatter instead of a per-row Python loop: the
    row index of every id comes from ``np.repeat`` over the per-row
    counts, so the whole batch densifies in a single C-level assignment
    (duplicate ids within a row collapse to 1, as before)."""
    rows = [r if hasattr(r, "__len__") else list(r) for r in rows]
    n = len(rows)
    dense = np.zeros((n, dim), np.float32)
    counts = np.fromiter((len(r) for r in rows), np.int64, count=n)
    total = int(counts.sum())
    if total:
        cols = np.fromiter((int(j) for r in rows for j in r), np.int64,
                           count=total)
        dense[np.repeat(np.arange(n), counts), cols] = 1.0
    return dense


def _densify_pairs(rows, dim: int) -> np.ndarray:
    """(index, value) pair lists -> dense [len(rows), dim].

    One flat fancy-indexed assignment for the whole batch.  Duplicate
    indices within a row keep the seed's last-write-wins semantic
    (numpy applies repeated-index assignments in order), so existing
    sparse_float datasets produce bit-identical feeds.  The per-row
    ``reshape(len(r), 2)`` keeps the seed's fail-fast on malformed
    pairs (arity != 2) — a flat scan would silently misalign every
    later pair instead."""
    rows = [r if hasattr(r, "__len__") else list(r) for r in rows]
    n = len(rows)
    dense = np.zeros((n, dim), np.float32)
    counts = np.fromiter((len(r) for r in rows), np.int64, count=n)
    if int(counts.sum()):
        flat = np.concatenate(
            [np.asarray(r, dtype=np.float64).reshape(len(r), 2)
             for r in rows if len(r)], axis=0)
        cols = flat[:, 0].astype(np.int64)
        if not np.array_equal(cols, flat[:, 0]):
            # the seed's per-element indexing raised on j=1.5; a silent
            # truncation here would train on corrupted features
            raise IndexError(
                "sparse_float pair indices must be integers; got a "
                "fractional index")
        dense[np.repeat(np.arange(n), counts),
              cols] = flat[:, 1].astype(np.float32)
    return dense


def _stack_uniform(col, dtype) -> np.ndarray | None:
    """[B] list of equal-length samples -> one stacked [B, T, ...] array
    via a single conversion, or None when the column is ragged/opaque —
    the vectorized fast path for sequence columns."""
    try:
        first_len = len(col[0])
        if all(len(s) == first_len for s in col):
            arr = np.asarray(col, dtype=dtype)
            return arr if arr.ndim >= 2 else None
    except (TypeError, ValueError):
        pass
    return None


def parse_seq_buckets(spec) -> tuple[int, ...] | None:
    """Bucket-table spec -> sorted tuple or None (use the default table).
    Accepts a comma-separated string (the ``--seq_buckets`` CLI /
    ``PADDLE_TPU_SEQ_BUCKETS`` env form, e.g. ``"8,16,32,64"``), any
    int sequence, or empty/None."""
    if spec is None:
        return None
    if isinstance(spec, str):
        spec = [s for s in spec.replace(" ", "").split(",") if s]
    table = tuple(sorted(int(b) for b in spec))
    return table or None


def padding_stats(feed: Mapping) -> tuple[int, int]:
    """(padded, total) timesteps across the SequenceBatch slots of a feed
    — the numerator/denominator of the per-step ``padding_ratio``
    telemetry field.  Host-side and cheap: only the tiny [B] length
    vectors are read."""
    padded = total = 0
    for v in feed.values():
        length = getattr(v, "length", None)
        data = getattr(v, "data", None)
        if length is None or data is None:
            continue
        try:
            lens = np.asarray(length)
            t = int(data.shape[1])
            total += int(lens.size) * t
            padded += int(np.sum(np.maximum(t - lens, 0)))
        except (TypeError, ValueError, IndexError):
            continue  # exotic slot shapes carry no padding signal
    return padded, total


class DataFeeder:
    def __init__(self, data_types: Mapping[str, object] | Sequence[tuple],
                 feeding: Mapping[str, int] | Sequence[str] | None = None,
                 seq_buckets: Sequence[int] | None = None):
        """data_types: {layer_name: InputType} or [(name, InputType), ...];
        feeding: {layer_name: index in sample tuple} (defaults to order);
        seq_buckets: override the default length-quantization table for
        sequence slots — MUST match the reader's ``bucket_by_length``
        table so every batch of a bucket compiles to one static shape."""
        if not isinstance(data_types, Mapping):
            data_types = dict(data_types)
        self.types = dict(data_types)
        self.seq_buckets = (tuple(sorted(int(b) for b in seq_buckets))
                            if seq_buckets else None)
        if feeding is None:
            self.feeding = {n: i for i, n in enumerate(self.types)}
        elif isinstance(feeding, Mapping):
            self.feeding = dict(feeding)
        else:
            self.feeding = {n: i for i, n in enumerate(feeding)}

    def __call__(self, batch):
        return self.feed(batch)

    def feed(self, batch) -> dict:
        out = {}
        for name, itype in self.types.items():
            enforce(
                name in self.feeding,
                f"feeding map is missing data layer {name!r} "
                f"(feeding keys: {sorted(self.feeding)})",
            )
            idx = self.feeding[name]
            # providers may yield dict samples keyed by layer name
            # (PyDataProvider2.py supports both; dataprovider_bow yields
            # {'word': ..., 'label': ...})
            col = [sample[name] if isinstance(sample, Mapping)
                   else sample[idx] for sample in batch]
            out[name] = self._convert(col, itype, name)
        return out

    def _convert(self, col, itype, name):
        kind, seq = itype.kind, itype.seq_type
        if seq == SeqType.NO_SEQUENCE:
            if kind == DataKind.DENSE:
                arr = np.asarray(col, dtype=np.float32).reshape(len(col), -1)
                enforce(
                    arr.shape[1] == itype.dim,
                    f"data layer {name!r} expects dim {itype.dim}, "
                    f"got samples of dim {arr.shape[1]}",
                )
                return jnp.asarray(arr)
            if kind == DataKind.INTEGER:
                return jnp.asarray(np.asarray(col, dtype=np.int32).reshape(len(col)))
            if kind == DataKind.SPARSE_BINARY:
                return jnp.asarray(_densify_ids(col, itype.dim))
            if kind == DataKind.SPARSE_FLOAT:
                return jnp.asarray(_densify_pairs(col, itype.dim))
        elif seq == SeqType.SEQUENCE:
            if kind in (DataKind.INTEGER, DataKind.DENSE):
                # uniform-length columns (the common synthetic/bucketed
                # case): ONE stacked conversion + one bucket-pad alloc
                # instead of a per-row asarray loop through pad_sequences
                dt = np.int32 if kind == DataKind.INTEGER else np.float32
                stacked = _stack_uniform(col, dt)
                if stacked is not None:
                    t_true = stacked.shape[1]
                    t = (bucket_length(t_true) if self.seq_buckets is None
                         else bucket_length(t_true, self.seq_buckets))
                    if t != t_true:
                        padded = np.zeros(
                            (len(col), t) + stacked.shape[2:], dt)
                        padded[:, :t_true] = stacked
                        stacked = padded
                    return SequenceBatch(
                        data=jnp.asarray(stacked),
                        length=jnp.asarray(
                            np.full((len(col),), t_true, np.int32)))
            if kind == DataKind.INTEGER:
                seqs = [np.asarray(s, dtype=np.int32) for s in col]
            elif kind == DataKind.SPARSE_BINARY:
                # per-timestep id lists -> dense [T, dim] rows.  KNOWN
                # INEFFICIENCY for very wide slots (sequence_tagging's
                # 76k-dim features build ~40 MB/batch of mostly zeros):
                # the byte-lean alternative is an embedding-style gather
                # of weight rows at the ids, which needs the consuming
                # projection to accept id lists — tracked as future work
                seqs = [_densify_ids(s, itype.dim) for s in col]
            elif kind == DataKind.SPARSE_FLOAT:
                seqs = [_densify_pairs(s, itype.dim) for s in col]
            else:
                seqs = [np.asarray(s, dtype=np.float32) for s in col]
            return from_ragged(seqs, buckets=self.seq_buckets)
        elif seq == SeqType.SUB_SEQUENCE:
            dt = np.int32 if kind == DataKind.INTEGER else np.float32
            nested = [[np.asarray(s, dtype=dt) for s in subs] for subs in col]
            return from_nested_ragged(nested)
        enforce(False, f"unsupported input type for {name!r}: {itype}")
