"""Reader framework — successor of ``python/paddle/v2/reader``: a reader is a
zero-arg callable returning an iterator of samples; decorators compose them."""

from paddle_tpu.reader.decorator import (  # noqa: F401
    batch,
    bucket_batch,
    bucket_by_length,
    buffered,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
    xmap_readers,
)
from paddle_tpu.reader.feeder import (  # noqa: F401
    DataFeeder,
    padding_stats,
    parse_seq_buckets,
)
from paddle_tpu.reader.prefetch import (  # noqa: F401
    DevicePrefetcher,
    FeedBatch,
    SynchronousFeeds,
)
