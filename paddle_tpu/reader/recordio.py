"""Chunked record file format for elastic input dispatch.

The reference's cloud path stores datasets as recordio chunks which the Go
master partitions into tasks (go/master/service.go SetDataset/partition,
python/paddle/v2/reader/creator.py recordio).  This is the same idea as a
small self-contained format: a file is a sequence of chunks, each
independently readable, so a chunk boundary is a safe task boundary.

Chunk layout:  b"PTRC" | u32 num_records | u32 payload_len | u32 crc32
               payload = concat(u32 record_len | record_bytes)
All integers little-endian.  Records are opaque bytes.
"""

from __future__ import annotations

import os
import struct
import zlib

_MAGIC = b"PTRC"
_HEADER = struct.Struct("<4sIII")
_LEN = struct.Struct("<I")


class Writer:
    def __init__(self, path: str, max_records_per_chunk: int = 1000):
        self._f = open(path, "wb")
        self._max = max_records_per_chunk
        self._records: list[bytes] = []

    def write(self, record: bytes) -> None:
        if not isinstance(record, bytes):
            raise TypeError("records are opaque bytes; serialize first")
        self._records.append(record)
        if len(self._records) >= self._max:
            self._flush_chunk()

    def _flush_chunk(self) -> None:
        if not self._records:
            return
        payload = b"".join(_LEN.pack(len(r)) + r for r in self._records)
        self._f.write(_HEADER.pack(_MAGIC, len(self._records), len(payload),
                                   zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)
        self._records = []

    def close(self) -> None:
        self._flush_chunk()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def chunk_offsets(path: str) -> list[int]:
    """Byte offsets of every chunk in the file (the task index)."""
    offsets = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = 0
        while pos < size:
            f.seek(pos)
            hdr = f.read(_HEADER.size)
            magic, _, payload_len, _ = _HEADER.unpack(hdr)
            if magic != _MAGIC:
                raise ValueError(f"bad chunk magic at {path}:{pos}")
            offsets.append(pos)
            pos += _HEADER.size + payload_len
    return offsets


def read_chunk(path: str, offset: int):
    """Yield the records of the single chunk at ``offset``."""
    with open(path, "rb") as f:
        f.seek(offset)
        magic, n, payload_len, crc = _HEADER.unpack(f.read(_HEADER.size))
        if magic != _MAGIC:
            raise ValueError(f"bad chunk magic at {path}:{offset}")
        payload = f.read(payload_len)
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise ValueError(f"chunk crc mismatch at {path}:{offset}")
        pos = 0
        for _ in range(n):
            (rlen,) = _LEN.unpack_from(payload, pos)
            pos += _LEN.size
            yield payload[pos:pos + rlen]
            pos += rlen


def reader(path: str):
    """Plain (non-elastic) whole-file reader, reader-convention."""
    def read():
        for off in chunk_offsets(path):
            yield from read_chunk(path, off)

    return read


def task_payloads(paths: list[str]) -> list[str]:
    """One master-task payload per chunk: "path:offset"."""
    out = []
    for p in paths:
        for off in chunk_offsets(p):
            out.append(f"{p}:{off}")
    return out


def read_task(payload: str):
    """``master_reader`` adapter: payload "path:offset" -> records."""
    path, off = payload.rsplit(":", 1)
    yield from read_chunk(path, int(off))
