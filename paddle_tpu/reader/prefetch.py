"""Asynchronous device-feed pipeline — the overlap layer between a batch
reader and the jitted train step.

The synchronous v2 loop runs ``DataFeeder.feed`` (host numpy), device
placement (``mesh.shard_batch``) and the step strictly in sequence, so the
TPU idles during every Python-side conversion and the host idles during
every step.  :class:`DevicePrefetcher` moves the host half onto a worker
thread and keeps a bounded queue (default depth 2) of device-resident
sharded feeds staged ahead of the consumer — ``jax.device_put`` is async,
so by the time the step loop dequeues a feed its transfer has typically
already overlapped prior compute.

Both iterators here yield :class:`FeedBatch` ``(examples, feed,
input_wait_ms)`` so the trainer accounts input wait identically for the
overlapped and the synchronous path:

- ``DevicePrefetcher`` — reader + feeder + shard on a worker thread;
  ``input_wait_ms`` is the time the consumer spent blocked on the queue
  (0 when the pipeline keeps up).
- ``SynchronousFeeds`` — the seed behavior (everything inline on the
  consumer thread); ``input_wait_ms`` is the full conversion+placement
  time, all of it on the critical path.

Error/shutdown contract (the parts thread pipelines usually get wrong):

- a reader or feeder exception is re-raised at the consumer's ``next()``,
  not swallowed into a truncated stream;
- ``close()`` stops the producer, drains staged feeds and joins the
  thread — the trainer calls it on preemption (SIGTERM) and on any exit
  from the pass loop, so the checkpoint path always sees a consistent
  batch boundary and no thread is left blocked in ``Queue.put``;
- the consumer waits with a timeout and re-checks producer liveness, so
  a killed producer can never hang the step loop (and on the main
  thread the timed wait stays signal-interruptible for SIGTERM).

Partial final batches: ``remainder="drop"`` / ``"pad"`` apply
:func:`paddle_tpu.parallel.mesh.apply_remainder` before sharding so the
last batch of a pass cannot break mesh divisibility (see that function
for the exact semantics); ``"error"`` keeps ``shard_batch``'s strict
check.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, NamedTuple

from paddle_tpu.core.enforce import enforce
from paddle_tpu.reader.decorator import (
    _drain_and_join,
    _guarded_put,
    _ProducerError,
)


class FeedBatch(NamedTuple):
    """One step's worth of input, ready for the jitted step."""

    examples: int          # samples in the ORIGINAL batch (pre drop/pad)
    feed: dict             # sharded feed pytree
    input_wait_ms: float   # host time this batch kept the step loop waiting
    padded_timesteps: int = 0   # padded steps across SequenceBatch slots
    total_timesteps: int = 0    # all steps across SequenceBatch slots


class _EndOfStream:
    pass


_END = _EndOfStream()


def skip_feed_batches(reader, skip: int, replicas: int = 1,
                      remainder: str = "error", heartbeat=None):
    """Fast-forward a batch reader past its first ``skip`` *yieldable*
    batches — the mid-pass-resume cursor replay (``SGD.train`` restores a
    ``(pass, batch)`` checkpoint cursor and must re-enter the pass at the
    exact batch boundary).

    Skipped batches are counted the way the trainer counts them: a batch
    that ``remainder="drop"`` would discard entirely (fewer samples than
    the mesh's ``replicas``) never reached the step loop, so it does not
    count against ``skip`` — the cursor stays aligned with the original
    run no matter the partial-batch policy.  Skipping does no feed
    conversion, no device placement and consumes no RNG keys; the cost of
    a resume is one pull per already-applied batch.  ``heartbeat``
    (optional, called with the skipped-batch index) keeps a staleness
    watchdog fed through a long fast-forward over a slow reader.
    """
    if skip <= 0:
        return reader
    m = max(int(replicas), 1)

    def skipped_reader():
        remaining = skip
        it = iter(reader())
        for batch in it:
            if remaining > 0:
                n = len(batch) if hasattr(batch, "__len__") else 0
                if remainder != "drop" or n >= m:
                    remaining -= 1
                if heartbeat is not None:
                    heartbeat(skip - remaining)
                continue
            yield batch

    return skipped_reader


def _convert(batch, feeder, mesh, remainder: str):
    """batch -> (examples, sharded feed, mesh used, padded_timesteps,
    total_timesteps) | None (batch fully dropped).  The mesh rides along
    so a consumer whose mesh changed between staging and use (elastic
    resharding — ``rebind_mesh``) can detect and re-place a stale feed
    instead of handing the step arrays committed to dead devices.  The
    padding stats are taken host-side pre-shard (producer thread under
    prefetch — off the step loop's critical path)."""
    from paddle_tpu.reader.feeder import padding_stats

    examples = len(batch) if hasattr(batch, "__len__") else 0
    feed = feeder(batch) if feeder is not None else batch
    padded, total = padding_stats(feed) if isinstance(feed, dict) else (0, 0)
    if mesh is not None:
        if remainder != "error":
            from paddle_tpu.parallel.mesh import apply_remainder

            feed = apply_remainder(
                feed, mesh.mesh.shape.get("data", 1), remainder)
            if feed is None:  # "drop" left nothing: skip the batch
                return None
        feed = mesh.shard_batch(feed)
    return examples, feed, mesh, padded, total


def _replace_feed(feed, mesh, remainder: str):
    """Re-place a staged feed onto a different mesh: device_get the old
    placement and shard onto the new one, re-applying the remainder
    policy in case the new degree no longer divides the staged batch.

    The device_get reads the OLD mesh's devices — fine on a simulated
    loss (every device stays attached) and on scale-up, but after a
    REAL host loss a batch-sharded feed's slice on the dead host is
    gone.  That is unrecoverable here (the reader already advanced past
    this batch), so it raises a clear error instead of silently
    skipping data; the checkpoint-fallback / supervisor ladder is the
    recovery path then."""
    import jax

    try:
        host = jax.device_get(feed)
    except Exception as e:
        raise RuntimeError(
            "elastic rebind: a staged feed's shard is unreachable (its "
            "device died before the feed was consumed); the batch "
            "cannot be reconstructed — recover via the cursor "
            "checkpoint") from e
    return mesh.shard_batch(host, remainder=remainder)


class SynchronousFeeds:
    """The non-overlapped baseline: conversion + placement inline on the
    consumer thread, with the same FeedBatch/close contract as
    :class:`DevicePrefetcher` so the trainer has one code path."""

    def __init__(self, reader: Callable, feeder=None, mesh=None,
                 remainder: str = "error"):
        self._it = iter(reader())
        self._feeder = feeder
        self._mesh = mesh
        self._remainder = remainder

    def __iter__(self):
        return self

    def __next__(self) -> FeedBatch:
        t0 = time.perf_counter()
        while True:
            batch = next(self._it)  # StopIteration ends the pass
            item = _convert(batch, self._feeder, self._mesh, self._remainder)
            if item is not None:
                examples, feed, _, padded, total = item
                return FeedBatch(
                    examples, feed, (time.perf_counter() - t0) * 1e3,
                    padded, total)

    def rebind_mesh(self, mesh) -> None:
        """Adopt a rebuilt mesh (elastic resharding): nothing is staged
        here, so the next conversion simply places onto it."""
        self._mesh = mesh

    def close(self) -> None:
        self._it = iter(())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class DevicePrefetcher:
    """Stage up to ``depth`` converted, device-resident feeds ahead of the
    step loop (see module docstring for the full contract).

    :param reader: zero-arg callable returning an iterator of batches
        (the ``paddle.batch(...)`` output ``SGD.train`` consumes).
    :param feeder: optional ``DataFeeder`` (or any batch -> feed callable)
        run on the worker thread.
    :param mesh: optional ``MeshContext``; when given, each feed is placed
        with ``shard_batch`` (async device_put) before being queued.
    :param depth: bounded queue size — feeds staged ahead of the consumer.
    :param remainder: "error" (strict divisibility, the default), "drop"
        (trim the batch to the largest mesh multiple) or "pad" (repeat the
        last sample up to the next multiple; see ``mesh.apply_remainder``).
    """

    def __init__(self, reader: Callable, feeder=None, mesh=None,
                 depth: int = 2, remainder: str = "error"):
        enforce(depth >= 1, f"prefetch depth must be >= 1, got {depth}")
        self._reader = reader
        self._feeder = feeder
        # _mesh is written by rebind_mesh (consumer thread, elastic
        # resharding) while the producer reads it per batch — every
        # access holds _mesh_lock (the GL-THREAD audited contract)
        self._mesh_lock = threading.Lock()
        self._mesh = mesh
        self._remainder = remainder
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(
            target=self._produce, name="paddle-tpu-prefetch", daemon=True)
        self._thread.start()

    # -- producer (worker thread) ---------------------------------------------
    def _produce(self) -> None:
        from paddle_tpu.telemetry.tracing import get_tracer

        tracer = get_tracer()  # spans land in this worker's own lane
        try:
            for batch in self._reader():
                if self._stop.is_set():
                    return
                with self._mesh_lock:
                    mesh = self._mesh
                with tracer.span("prefetch", cat="reader",
                                 staged=self._q.qsize()):
                    item = _convert(batch, self._feeder, mesh,
                                    self._remainder)
                if item is None:
                    continue
                if not _guarded_put(self._q, item, self._stop):
                    return
        except BaseException as e:  # propagate to the consumer, not stderr
            _guarded_put(self._q, _ProducerError(e), self._stop)
        finally:
            _guarded_put(self._q, _END, self._stop)

    # -- consumer ---------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> FeedBatch:
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                # timed wait: stays SIGTERM-interruptible on the main
                # thread and lets us detect a dead producer
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive() and self._q.empty():
                    self._done = True
                    raise RuntimeError(
                        "prefetch producer died without signaling "
                        "end-of-stream") from None
        wait_ms = (time.perf_counter() - t0) * 1e3
        if item is _END:
            self._done = True
            self._thread.join(timeout=5.0)
            raise StopIteration
        if isinstance(item, _ProducerError):
            self._done = True
            self._thread.join(timeout=5.0)
            raise item.exc
        examples, feed, used_mesh, padded, total = item
        with self._mesh_lock:
            mesh_now = self._mesh
        if mesh_now is not None and used_mesh is not mesh_now:
            # staged under a mesh that has since been rebuilt (elastic
            # resharding): re-place on the consumer thread rather than
            # dropping — the reader already advanced past this batch,
            # so dropping would silently skip data
            feed = _replace_feed(feed, mesh_now, self._remainder)
        return FeedBatch(examples, feed, wait_ms, padded, total)

    def rebind_mesh(self, mesh) -> None:
        """Adopt a rebuilt mesh (elastic resharding).  The producer
        picks it up for every batch it converts from now on; feeds
        already staged (or mid-conversion) under the old mesh are
        detected by their mesh tag at ``__next__`` and re-placed, so
        the stream stays gapless and in order."""
        with self._mesh_lock:
            self._mesh = mesh

    # -- shutdown ---------------------------------------------------------------
    def close(self) -> None:
        """Stop the producer and drain staged feeds.  Idempotent; called by
        the trainer on preemption and on every pass-loop exit so a consumer
        that abandons the stream early never strands the worker in
        ``Queue.put``."""
        self._done = True
        _drain_and_join(self._q, [self._thread], self._stop, deadline_s=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
