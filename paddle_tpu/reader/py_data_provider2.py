"""PyDataProvider2 compatibility — the ``@provider`` decorator surface.

Reference: ``python/paddle/trainer/PyDataProvider2.py`` (``@provider``,
``:367-374``) wraps a user generator so the C++ ``PyDataProvider2.cpp``
can pull batches through embedded CPython.  Here the direction is inverted
(the runtime IS Python): the decorated generator simply becomes a
paddle-style reader over the provider's file list, and the declared
``input_types`` drive the DataFeeder.

Supported knobs: input_types (dict or list), should_shuffle, cache
(accepted, pass-level caching handled by the reader buffer), init_hook,
calc_batch_size (HONORED via length-bucketed cost-balanced batching —
``reader.decorator.bucket_batch`` — giving each bucket one static XLA
shape), pool_size (subsumed by the per-bucket pools).
"""

from __future__ import annotations

from paddle_tpu.layers.data_type import (  # noqa: F401 (re-exported surface)
    dense_array,
    dense_vector,
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_binary_vector_sequence,
    sparse_float_vector,
)


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class _Settings:
    """The ``settings`` object handed to providers/init_hooks."""

    def __init__(self, input_types=None, **kwargs):
        self.input_types = input_types
        self.__dict__.update(kwargs)

    # the reference accepts either name for the type declaration
    # (PyDataProvider2.py: ``slots`` is the pre-input_types spelling,
    # still used by benchmark/paddle/image/provider.py)
    @property
    def slots(self):
        return self.input_types

    @slots.setter
    def slots(self, value):
        self.input_types = value


def provider(input_types=None, should_shuffle=None, pool_size=-1,
             min_pool_size=-1, can_over_batch_size=True, calc_batch_size=None,
             cache=CacheType.NO_CACHE, check=False, check_fail_continue=False,
             init_hook=None, **outter_kwargs):
    """≅ @provider (PyDataProvider2.py:367): declare a data provider."""

    def deco(fn):
        def make_reader(file_list, **kwargs):
            """paddle-style reader() over the provider's file list."""
            settings = _Settings(input_types=input_types, **kwargs)
            if init_hook is not None:
                init_hook(settings, file_list=file_list, **kwargs)
                # init_hook providers declare types on settings
                # (dataprovider_bow.initializer pattern); expose them for
                # the trainer's layer-type binding
                if settings.input_types is not None:
                    fn.input_types = settings.input_types

            def reader():
                for filename in file_list:
                    yield from fn(settings, filename)

            return reader

        fn.make_reader = make_reader
        fn.input_types = input_types
        fn.is_provider = True
        fn.should_shuffle = should_shuffle
        fn.cache = cache
        fn.calc_batch_size = calc_batch_size
        fn.pool_size = pool_size
        return fn

    return deco


def read_file_list(list_path: str) -> list[str]:
    """A ``train.list`` file: one data-file path per line (≅ DataConfig.files)."""
    with open(list_path) as f:
        return [ln.strip() for ln in f if ln.strip()]
