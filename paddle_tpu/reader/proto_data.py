"""ProtoDataProvider / MultiDataProvider — readers for the reference's
binary proto data format (``proto/DataFormat.proto``, served by
``paddle/gserver/dataproviders/ProtoDataProvider.{h,cpp}`` and
``MultiDataProvider.h``), rebuilt as paddle readers.

Wire format (``ProtoReader.h:96``): a stream of varint32-length-prefixed
messages — one ``DataHeader`` then ``DataSample``s until EOF; ``.gz``
files are gzip streams of the same.  Each DataSample is one TIMESTEP;
``is_beginning`` marks sequence starts (``ProtoDataProvider.cpp:226``),
so samples are regrouped here into per-sequence rows, which is what the
trainer's feeder consumes.

Slot -> feed conversion mirrors ``ProtoDataProvider::fillSlots``:
VECTOR_DENSE -> float list, VECTOR_SPARSE_NON_VALUE -> id list,
VECTOR_SPARSE_VALUE -> [(index, value), ...] pairs, INDEX -> int.
Sequence datasets
yield, per slot, the list of per-timestep values (length-1 sequences
included); non-sequence datasets yield each timestep's value directly.
"""

from __future__ import annotations

import gzip

from paddle_tpu.proto.build import message_class

_DataHeader = message_class("DataHeader")
_DataSample = message_class("DataSample")

# SlotDef.SlotType values (DataFormat.proto:49)
VECTOR_DENSE = 0
VECTOR_SPARSE_NON_VALUE = 1
VECTOR_SPARSE_VALUE = 2
INDEX = 3
STRING = 6


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def iter_proto_stream(path: str):
    """Yield the DataHeader, then each DataSample, lazily."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        buf = f.read()
    pos = 0
    size, pos = _read_varint(buf, pos)
    yield _DataHeader.FromString(buf[pos:pos + size])
    pos += size
    n = len(buf)
    while pos < n:
        size, pos = _read_varint(buf, pos)
        yield _DataSample.FromString(buf[pos:pos + size])
        pos += size


def read_proto_stream(path: str):
    """Returns (header, list_of_samples)."""
    it = iter_proto_stream(path)
    return next(it), list(it)


def write_proto_stream(path: str, header, samples) -> None:
    """Writer for the same format (tests, data conversion tools)."""
    from google.protobuf.internal.encoder import _VarintBytes

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        for msg in [header, *samples]:
            payload = msg.SerializeToString()
            f.write(_VarintBytes(len(payload)))
            f.write(payload)


_VECTOR_TYPES = (VECTOR_DENSE, VECTOR_SPARSE_NON_VALUE,
                 VECTOR_SPARSE_VALUE)


def _slot_table(header):
    """Per-slot (type, per-kind index), computed once per header.

    The wire stores VECTOR slots in ``vector_slots`` and INDEX slots in
    ``id_slots``, each in declaration order of their kind (the header
    comment "INDEX slot should be always after VECTOR slots",
    DataFormat.proto:64, is a convention — counting per kind is correct
    for any order and never aliases another slot)."""
    table = []
    n_vec = n_idx = 0
    for sdef in header.slot_defs:
        if sdef.type in _VECTOR_TYPES:
            table.append((sdef.type, n_vec))
            n_vec += 1
        elif sdef.type == INDEX:
            table.append((sdef.type, n_idx))
            n_idx += 1
        else:
            raise NotImplementedError(
                f"proto data slot type {sdef.type} not supported")
    return table


def _slot_value(sample, table, slot_idx: int):
    """One timestep's value for one slot (fillSlots semantics)."""
    stype, kidx = table[slot_idx]
    if stype == VECTOR_DENSE:
        return list(sample.vector_slots[kidx].values)
    if stype == VECTOR_SPARSE_NON_VALUE:
        return list(sample.vector_slots[kidx].ids)
    if stype == VECTOR_SPARSE_VALUE:
        # (index, value) pair list — the v2 sparse_float convention the
        # feeder's _densify_pairs consumes (reference SparseFloatScanner
        # reads x[0]/x[1] per pair)
        vs = sample.vector_slots[kidx]
        return list(zip(vs.ids, vs.values))
    return int(sample.id_slots[kidx])


def proto_reader(file_list, sequential: bool | None = None,
                 usage_ratio: float | None = None):
    """paddle reader over proto data files: one tuple per SEQUENCE, one
    entry per slot.

    ``sequential`` decides the row shape DATASET-wide (matching the
    types ``input_types_from_header`` reports): sequences yield the
    per-timestep list for every slot — including length-1 sequences —
    while non-sequence data yields each timestep's value directly.
    ``None`` auto-detects per file (any ``is_beginning=False`` sample).

    ``usage_ratio`` < 1 consumes only that fraction of each file's
    sequences per pass (``ProtoDataProvider::sequenceLoop``,
    ProtoDataProvider.cpp:397-399: the SHUFFLED sequence list is truncated
    to ``count * usage_ratio`` — the shuffle precedes the cut, so
    successive passes sample different subsets and no fixed file tail is
    starved)."""

    import numpy as _np

    def reader():
        for path in file_list:
            header, samples = read_proto_stream(path)
            table = _slot_table(header)
            n_slots = len(header.slot_defs)
            has_seq = (any(not s.is_beginning for s in samples)
                       if sequential is None else sequential)

            def emit(seq):
                cols = []
                for i in range(n_slots):
                    vals = [_slot_value(s, table, i) for s in seq]
                    cols.append(vals if has_seq else vals[0])
                return tuple(cols)

            if usage_ratio is not None and usage_ratio < 1.0:
                # group into sequences, shuffle, THEN truncate (fresh
                # shuffle per reader() call = per pass)
                seqs: list[list] = []
                for s in samples:
                    if s.is_beginning or not seqs:
                        seqs.append([])
                    seqs[-1].append(s)
                keep = int(len(seqs) * usage_ratio)
                if keep == 0:
                    # reference-faithful floor (sequenceLoop casts
                    # count*ratio to int64 too) — but be LOUD about a
                    # file contributing nothing, a zero-batch pass NaNs
                    from paddle_tpu.core import logger as _log

                    _log.warning(
                        "usage_ratio=%.3f keeps 0 of %d sequences in %s "
                        "— the file contributes no data this pass",
                        usage_ratio, len(seqs), path)
                # global np.random so np.random.seed() makes data
                # selection reproducible (repo-wide convention)
                order = _np.random.permutation(len(seqs))
                for idx in order[:keep]:
                    yield emit(seqs[idx])
                continue

            seq: list = []
            for s in samples:
                if s.is_beginning and seq:
                    yield emit(seq)
                    seq = []
                seq.append(s)
            if seq:
                yield emit(seq)

    return reader


def input_types_from_header(path: str):
    """Provider-style input_types list derived from a file's DataHeader —
    the trainer binds these to the config's data layers in input order
    (ProtoDataProvider keeps types in the data file, not the config)."""
    from paddle_tpu.layers import data_type as dt

    it = iter_proto_stream(path)
    header = next(it)
    # sequence-ness is decidable from the first continuation sample —
    # scan a bounded prefix instead of parsing the whole (possibly huge
    # .gz) file twice
    has_seq = False
    for i, s in enumerate(it):
        if not s.is_beginning:
            has_seq = True
            break
        if i >= 512:
            break
    kinds = []
    for sdef in header.slot_defs:
        if sdef.type == VECTOR_DENSE:
            mk = (dt.dense_vector_sequence if has_seq else dt.dense_vector)
        elif sdef.type == VECTOR_SPARSE_NON_VALUE:
            mk = (dt.sparse_binary_vector_sequence if has_seq
                  else dt.sparse_binary_vector)
        elif sdef.type == VECTOR_SPARSE_VALUE:
            mk = (dt.sparse_float_vector_sequence if has_seq
                  else dt.sparse_float_vector)
        elif sdef.type == INDEX:
            mk = (dt.integer_value_sequence if has_seq
                  else dt.integer_value)
        else:
            raise NotImplementedError(f"slot type {sdef.type}")
        kinds.append(mk(int(sdef.dim)))
    return kinds


def multi_reader(sub_readers):
    """MultiDataProvider (MultiDataProvider.h:24): one sample per
    sub-provider per step, yielded as one concatenated tuple — the
    reference feeds multiple data sources into one network.  Per-sub
    sub-sampling comes from each sub-provider's own DataConfig
    usage_ratio (as in the reference, where every sub-DataProvider
    carries its own ``usageRatio_``), not from a knob here."""

    def reader():
        its = [r() for r in sub_readers]
        while True:
            row = []
            try:
                for it in its:
                    part = next(it)
                    row.extend(part if isinstance(part, tuple) else (part,))
            except StopIteration:
                return
            yield tuple(row)

    return reader
