"""Reader decorators — successor of ``python/paddle/v2/reader/decorator.py:26-233``
(map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers) and
``paddle.batch``.  Multiprocessing xmap is implemented with threads (the
reference uses threads too); the TPU input pipeline wants the host CPU free,
so heavy preprocessing should move into readers ahead of time."""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Callable, Iterable


def map_readers(func: Callable, *readers):
    """Apply func to the items of several readers zipped together."""

    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int):
    """Buffered shuffle (reference semantics: fill buf, shuffle, drain)."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuples; flattens nested tuples like the reference."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        for items in zip(*[r() for r in readers]):
            yield sum((make_tuple(i) for i in items), ())

    return composed


def buffered(reader, size: int):
    """Double-buffered async read-ahead (≅ DataProvider's
    getNextBatchFromBuffer:375 background loading)."""

    end = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def producer():
            try:
                for e in reader():
                    q.put(e)
            finally:
                q.put(end)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e

    return buffered_reader


def firstn(reader, n: int):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper: Callable, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a reader with worker threads (≅ xmap_readers)."""

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feeder():
            for i, e in enumerate(reader()):
                in_q.put((i, e))
            for _ in range(process_num):
                in_q.put(end)

        def worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    break
                i, e = item
                out_q.put((i, mapper(e)))

        threading.Thread(target=feeder, daemon=True).start()
        workers = [threading.Thread(target=worker, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending: dict[int, object] = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists (≅ paddle.batch; tail partial batch included,
    matching the v2 contract).  Pass drop_last=True on TPU hot paths: partial
    batches force a recompile and break mesh divisibility."""

    def batch_reader():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
