"""Reader decorators — successor of ``python/paddle/v2/reader/decorator.py:26-233``
(map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers) and
``paddle.batch``.  Multiprocessing xmap is implemented with threads (the
reference uses threads too); the TPU input pipeline wants the host CPU free,
so heavy preprocessing should move into readers ahead of time."""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
import time as _time
from typing import Callable, Iterable

import numpy as np


def map_readers(func: Callable, *readers):
    """Apply func to the items of several readers zipped together."""

    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int):
    """Buffered shuffle (reference semantics: fill buf, shuffle, drain)."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuples; flattens nested tuples like the reference."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        for items in zip(*[r() for r in readers]):
            yield sum((make_tuple(i) for i in items), ())

    return composed


class _ProducerError:
    """Exception raised on a reader/mapper worker thread, carried across
    the queue so the consumer re-raises it instead of seeing a silently
    truncated stream.  Shared by buffered/xmap_readers here and by
    ``reader/prefetch.py``."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def _guarded_put(q: queue.Queue, item, cancelled: threading.Event,
                 timeout: float = 0.05) -> bool:
    """Bounded put that gives up once ``cancelled`` is set — the shared
    primitive that keeps producer threads from blocking forever in
    ``Queue.put`` after the consumer walked away."""
    while not cancelled.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


def _drain_and_join(q: queue.Queue, threads, cancelled: threading.Event,
                    deadline_s: float = 2.0) -> None:
    """Shutdown counterpart of :func:`_guarded_put`: set ``cancelled``,
    then drain the queue (unblocking producers mid-put) until every
    thread exits or the deadline passes — a producer blocked outside its
    put (e.g. on IO) stays a daemon thread rather than hanging us."""
    cancelled.set()
    deadline = _time.monotonic() + deadline_s
    while (any(t.is_alive() for t in threads)
           and _time.monotonic() < deadline):
        try:
            q.get_nowait()
        except queue.Empty:
            _time.sleep(0.005)
    for t in threads:
        t.join(timeout=max(deadline - _time.monotonic(), 0.0))


def buffered(reader, size: int):
    """Double-buffered async read-ahead (≅ DataProvider's
    getNextBatchFromBuffer:375 background loading).

    A reader exception propagates to the consumer (it used to be
    swallowed, truncating the dataset as if the epoch had ended), and a
    consumer that abandons the generator early (``break`` / ``close()``)
    unblocks the producer instead of leaking a thread stuck in
    ``Queue.put``."""

    end = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        abandoned = threading.Event()

        def producer():
            try:
                for e in reader():
                    if not _guarded_put(q, e, abandoned):
                        return
            except BaseException as exc:
                _guarded_put(q, _ProducerError(exc), abandoned)
            finally:
                _guarded_put(q, end, abandoned)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is end:
                    break
                if isinstance(e, _ProducerError):
                    raise e.exc
                yield e
        finally:
            # consumer done or abandoned: release the producer and drain
            _drain_and_join(q, [t], abandoned)

    return buffered_reader


def firstn(reader, n: int):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper: Callable, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a reader with worker threads (≅ xmap_readers).

    A mapper (or source-reader) exception is put on the output queue and
    re-raised at the consumer.  The seed behavior — the worker dying
    without its ``end`` sentinel, leaving the consumer spinning forever on
    ``finished < process_num`` — is exactly the hang this guards against.
    """

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        abandoned = threading.Event()

        def feeder():
            try:
                for i, e in enumerate(reader()):
                    if not _guarded_put(in_q, (i, e), abandoned):
                        return
            except BaseException as exc:
                # source reader failed: surface it, then still release the
                # workers so their end sentinels keep the consumer's
                # bookkeeping intact
                _guarded_put(out_q, _ProducerError(exc), abandoned)
            finally:
                for _ in range(process_num):
                    _guarded_put(in_q, end, abandoned)

        def worker():
            try:
                while True:
                    try:
                        # timed get: when the consumer abandons early the
                        # feeder's end sentinels never arrive (its puts
                        # cancel), so workers must notice and exit rather
                        # than block in in_q.get() forever
                        item = in_q.get(timeout=0.05)
                    except queue.Empty:
                        if abandoned.is_set():
                            return
                        continue
                    if item is end:
                        break
                    i, e = item
                    if not _guarded_put(out_q, (i, mapper(e)), abandoned):
                        return
            except BaseException as exc:
                _guarded_put(out_q, _ProducerError(exc), abandoned)
            finally:
                _guarded_put(out_q, end, abandoned)

        threading.Thread(target=feeder, daemon=True).start()
        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending: dict[int, object] = {}
        next_i = 0
        try:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, _ProducerError):
                    raise item.exc
                if not order:
                    yield item[1]
                else:
                    pending[item[0]] = item[1]
                    while next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
            if order:
                for i in sorted(pending):
                    yield pending[i]
        finally:
            # error or early consumer exit: unblock every producer put
            _drain_and_join(out_q, workers, abandoned)

    return xreader


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists (≅ paddle.batch; tail partial batch included,
    matching the v2 contract).  Pass drop_last=True on TPU hot paths: partial
    batches force a recompile and break mesh divisibility."""

    def batch_reader():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


#: ceiling on distinct bucket lengths a bucketed reader may emit — each
#: bucket is one jit signature for the train step, so an unbounded table
#: is a recompile bomb (GL-P-RECOMPILE flags signature churn; the tests
#: assert the trainer compiles at most this many step signatures)
MAX_SEQ_BUCKETS = 16


def _sample_max_len(sample) -> int:
    """Longest sequence field of a sample — the shared length probe of
    both bucketing entries (tuple/list samples AND @provider dict
    samples; scalars count as length 1)."""
    best = 1
    if isinstance(sample, dict):
        fields = list(sample.values())
    elif isinstance(sample, (list, tuple)):
        fields = sample
    else:
        fields = [sample]
    for field in fields:
        if isinstance(field, (list, tuple, np.ndarray)) \
                and not np.isscalar(field):
            try:
                best = max(best, len(field))
            except TypeError:
                pass
    return best


def bucket_by_length(reader, batch_size,
                     buckets=(16, 32, 64, 128, 256, 512, 1024),
                     sample_length=None, seed: int = 0,
                     remainder: str = "drop", size_multiple: int = 1):
    """Length-quantized bucketing for a SAMPLE reader: group samples by
    ``bucket_length`` of their longest sequence field and emit
    fixed-size batches per bucket, so padded timesteps stop burning
    flops and bytes (a mixed-length batch pads every row to the batch
    max; a bucketed batch pads only to its own quantized ceiling).

    Contract:

    - every emitted batch has EXACTLY ``batch_size`` samples except the
      end-of-stream flush, so the jit sees at most ``len(buckets)``
      (batch, time) signatures — the table is capped at
      ``MAX_SEQ_BUCKETS`` because each bucket is one recompile;
    - ``remainder`` follows ``parallel.mesh.apply_remainder`` semantics
      for the end-of-stream leftovers: ``"drop"`` trims each leftover
      pool to the largest ``size_multiple`` multiple (dropping the
      rest, logged), ``"pad"`` repeats the pool's last sample up to the
      FULL ``batch_size`` (keeping the one-shape-per-bucket discipline
      rather than minting a fresh tail shape);
    - deterministic given ``seed``: in-stream flushes happen in arrival
      order; the leftover pools flush in a seed-shuffled bucket order
      (two runs with equal seeds yield identical batch streams).

    Feed the same ``buckets`` table to ``DataFeeder(seq_buckets=...)``
    (the trainer's ``seq_buckets``/``--seq_buckets`` knob wires both)
    so the feeder pads each batch to its bucket ceiling instead of the
    global table's.
    """
    from paddle_tpu.core.enforce import enforce
    from paddle_tpu.core.lod import bucket_length

    buckets = tuple(sorted(int(b) for b in buckets))
    enforce(len(buckets) >= 1, "bucket_by_length: empty bucket table")
    enforce(
        len(buckets) <= MAX_SEQ_BUCKETS,
        f"bucket_by_length: {len(buckets)} buckets > MAX_SEQ_BUCKETS "
        f"({MAX_SEQ_BUCKETS}) — every bucket is one jit recompile of the "
        f"train step; quantize coarser")
    enforce(remainder in ("drop", "pad"),
            f"bucket_by_length: remainder must be 'drop' or 'pad', got "
            f"{remainder!r}")
    m = max(int(size_multiple), 1)

    length_of = sample_length or _sample_max_len

    def batch_reader():
        rng = _random.Random(seed)
        pools: dict[int, list] = {}
        for sample in reader():
            key = bucket_length(length_of(sample), buckets)
            pool = pools.setdefault(key, [])
            pool.append(sample)
            if len(pool) >= batch_size:
                yield pool[:batch_size]
                pools[key] = pool[batch_size:]
        order = sorted(k for k, p in pools.items() if p)
        rng.shuffle(order)
        dropped = 0
        for key in order:
            pool = pools[key]
            if remainder == "pad":
                pool = pool + [pool[-1]] * (batch_size - len(pool))
                yield pool
                continue
            n = (len(pool) // m) * m
            dropped += len(pool) - n
            if n:
                yield pool[:n]
        if dropped:
            from paddle_tpu.core import logger as log

            log.info("bucket_by_length: dropped %d tail samples not "
                     "divisible by %d", dropped, m)

    # advertise the table on the reader so SGD.train's feeder picks it
    # up by DEFAULT (train(seq_buckets=None) and no --seq_buckets flag):
    # the dataset bucketed_batches helpers (wmt14/conll05/imdb) then
    # bucket end-to-end without the caller repeating the table
    batch_reader.seq_buckets = buckets
    return batch_reader


def bucket_batch(reader, batch_size, calc_batch_size=None, sample_length=None,
                 buckets=(16, 32, 64, 128, 256, 512, 1024),
                 drop_last: bool = False, size_multiple: int = 1):
    """Length-bucketed, cost-balanced batching — the XLA-native answer to
    PyDataProvider2's ``pool_size``/``calc_batch_size``
    (``python/paddle/trainer/PyDataProvider2.py:367-374``, served by
    ``PyDataProvider2.cpp``'s pooled dispatch).

    Samples are grouped by their bucketed sequence length (the same bucket
    table ``pad_sequences`` pads to, so every batch of a bucket compiles to
    ONE static shape), and a bucket flushes when its accumulated cost —
    ``sum(calc_batch_size(sample))``, default 1 per sample — reaches
    ``batch_size``.  calc_batch_size thereby balances variable-length
    batches exactly as the reference's pooled provider does: e.g.
    ``calc_batch_size=lambda s: len(s[0])`` makes long-sequence batches
    smaller at equal token budget.

    ``size_multiple`` trims each emitted batch to a multiple of the mesh
    replica count (sharding divisibility); trimmed samples stay pooled
    until the end-of-stream flush, which drops an under-multiple tail
    (logged).

    Shape discipline: the FIRST flush of a bucket pins that bucket's batch
    size; later flushes reuse it, so the jit sees at most one
    (batch, time-bucket) signature per bucket instead of a fresh batch dim
    every flush.
    """
    from paddle_tpu.core.lod import bucket_length

    length_of = sample_length or _sample_max_len
    cost_of = calc_batch_size or (lambda s: 1)

    m = max(int(size_multiple), 1)

    def batch_reader():
        pools: dict[int, tuple[list, float]] = {}
        pinned: dict[int, int] = {}  # bucket -> fixed batch size
        for sample in reader():
            b = bucket_length(length_of(sample), buckets)
            items, cost = pools.get(b, ([], 0.0))
            items.append(sample)
            cost += float(cost_of(sample))
            n = pinned.get(b)
            if n is None and cost >= batch_size and len(items) >= m:
                n = pinned[b] = (len(items) // m) * m
            if n is not None and len(items) >= n:
                yield items[:n]
                rest = items[n:]
                pools[b] = (rest, sum(float(cost_of(s)) for s in rest))
            else:
                pools[b] = (items, cost)
        if not drop_last:
            dropped = 0
            for b in sorted(pools):
                items, _ = pools[b]
                n = (len(items) // m) * m
                if n:
                    yield items[:n]
                dropped += len(items) - n
            if dropped:
                from paddle_tpu.core import logger as log

                log.info("bucket_batch: dropped %d tail samples not "
                         "divisible by the %d-replica mesh", dropped, m)

    return batch_reader
