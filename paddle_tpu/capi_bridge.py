"""Bridge between the C inference ABI and the Python runtime.

native/capi/paddle_capi.cc embeds CPython (the same technique the
reference uses for its config parser — ``paddle/utils/PythonUtil.cpp``
``Py_Initialize``/``callPythonFunc``) and calls these module-level
functions.  The interface is deliberately buffer-based (raw little-endian
float32 bytes + dims) so the C side needs no numpy C API.
"""

from __future__ import annotations

import os

import numpy as np

# honor JAX_PLATFORMS (set by paddle_init --use_cpu) even when a
# sitecustomize force-registers another platform: jax.config wins over it
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from paddle_tpu.utils.merge_model import MergedModel

_machines: dict[int, MergedModel] = {}
_next_handle = [1]


def create_machine(model_bytes: bytes) -> int:
    m = MergedModel(model_bytes)
    h = _next_handle[0]
    _next_handle[0] += 1
    _machines[h] = m
    return h


def create_shared_machine(origin: int) -> int:
    """A new machine handle sharing the ORIGIN's loaded artifact — the
    reference's ``paddle_gradient_machine_create_shared_param``
    (gradient_machine.h:68): weights are baked into the compiled StableHLO
    executable and the machine is a pure function, so sharing is exact
    aliasing with zero per-machine weight copies."""
    m = _machines[origin]
    h = _next_handle[0]
    _next_handle[0] += 1
    _machines[h] = m
    return h


def destroy_machine(handle: int) -> None:
    _machines.pop(handle, None)


def num_inputs(handle: int) -> int:
    return len(_machines[handle].meta["inputs"])


def input_dim(handle: int, i: int) -> int:
    return int(_machines[handle].meta["inputs"][i]["dim"])


def forward(handle: int, in_bufs: list, rows: int):
    """in_bufs: one bytes object of float32 data per input.
    Returns [(bytes, rows, cols), ...] per output."""
    m = _machines[handle]
    arrays = [
        np.frombuffer(buf, dtype="<f4").reshape(rows, spec["dim"])
        for buf, spec in zip(in_bufs, m.meta["inputs"])
    ]
    outs = m.forward(*arrays)
    result = []
    for o in outs:
        o = np.ascontiguousarray(o, dtype="<f4")
        if o.ndim == 1:
            o = o[:, None]
        result.append((o.tobytes(), int(o.shape[0]), int(o.shape[1])))
    return result
