"""Declared-evaluator specs — the bridge between the v1 config surface
(``*_evaluator`` calls inside config files,
``python/paddle/trainer_config_helpers/evaluators.py:161-774``), the
ModelConfig ``evaluators`` proto emission
(``EvaluatorConfig``, ModelConfig.proto:536), and runtime execution in the
train/test loops (``paddle/gserver/evaluators/Evaluator.cpp``).

A declaration is config-scope global state (like the reference's
``Evaluator()`` config_parser class): ``reset()`` runs at parse start, and
``collect()`` hands the accumulated specs to ParsedConfig / Topology.
"""

from __future__ import annotations

import dataclasses
from typing import Any

# EvaluatorConfig proto field names accepted as kwargs (ModelConfig.proto
# fields 4-17); anything else is rejected loudly.
PROTO_FIELDS = (
    "chunk_scheme", "num_chunk_types", "classification_threshold",
    "positive_label", "dict_file", "result_file", "num_results",
    "delimited", "excluded_chunk_types", "top_k", "overlap_threshold",
    "background_id", "evaluate_difficult", "ap_type",
)


@dataclasses.dataclass
class EvaluatorSpec:
    name: str
    type: str
    input_layers: list[str]
    fields: dict[str, Any] = dataclasses.field(default_factory=dict)

    def field(self, key, default=None):
        v = self.fields.get(key)
        return default if v is None else v


_declared: list[EvaluatorSpec] = []


def declare(spec: EvaluatorSpec) -> EvaluatorSpec:
    for k in spec.fields:
        if k not in PROTO_FIELDS:
            raise ValueError(f"unknown EvaluatorConfig field {k!r}")
    _declared.append(spec)
    return spec


def reset() -> None:
    _declared.clear()


def collect() -> list[EvaluatorSpec]:
    return list(_declared)
