"""Execute declared evaluators (EvaluatorSpecs) over batch layer values —
the runtime half of the v1 evaluator surface (≅ ``GradientMachine::eval``
driving ``paddle/gserver/evaluators/Evaluator.cpp``), including the printer
family (Evaluator.cpp:1018-1357).

The trainer loops call :func:`build` once per topology and then
``evs.eval_batch(values, feed)`` per batch with the eval-step's layer-value
dict; ``finish()`` returns the metric dict printed as ``Eval:`` lines.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from paddle_tpu import evaluator as ev_mod
from paddle_tpu.core import logger as log
from paddle_tpu.core.enforce import enforce
from paddle_tpu.evaluator.declare import EvaluatorSpec
from paddle_tpu.layers.base import is_sequence, raw


def _np(v):
    return np.asarray(raw(v))


def _valid_frames(pred_v, label_v, weight_v=None):
    """Flatten (pred, label[, weight]) to per-frame rows, DROPPING padded
    frames when either side is a SequenceBatch (the reference masks by
    sequence length; scoring padding would skew every sequence metric)."""
    lens = _lengths(pred_v)
    if lens is None:
        lens = _lengths(label_v)
    p, y = _np(pred_v), _np(label_v)
    w = _np(weight_v) if weight_v is not None else None
    if lens is None:
        return p, y, w
    p3 = p.reshape(p.shape[0], -1, p.shape[-1]) if p.ndim > 2 else         p.reshape(p.shape[0], -1, 1)
    y2 = y.reshape(y.shape[0], -1)
    ps, ys, ws = [], [], []
    for i in range(p3.shape[0]):
        t = int(lens[i])
        ps.append(p3[i, :t])
        ys.append(y2[i, :t])
        if w is not None:
            wi = w.reshape(w.shape[0], -1)[i]
            ws.append(np.broadcast_to(wi[:1] if wi.size == 1 else wi[:t],
                                      (t,)))
    return (np.concatenate(ps), np.concatenate(ys),
            np.concatenate(ws) if w is not None else None)


def _lengths(v):
    return np.asarray(v.length) if is_sequence(v) else None


def _load_dict(path: str | None):
    if not path:
        return None
    with open(path) as f:
        return [line.rstrip("\n") for line in f]


# ---- printer family ---------------------------------------------------------

class MaxIdPrinter(ev_mod.Evaluator):
    """≅ MaxIdPrinter (Evaluator.cpp:1126): top-k ids per sample."""

    name = "max_id_printer"

    def __init__(self, num_results: int = 1, prefix: str = "max_id"):
        self.k = max(num_results, 1)
        self.prefix = prefix

    def start(self):
        pass

    def eval_batch(self, value=None, **kw):
        arr = _np(value)
        arr = arr.reshape(-1, arr.shape[-1])
        ids = np.argsort(-arr, axis=-1)[:, : self.k]
        for r, row in enumerate(ids):
            log.info("%s sample %d: %s", self.prefix, r,
                     " ".join(str(int(i)) for i in row))

    def finish(self):
        return {}


class MaxFramePrinter(ev_mod.Evaluator):
    """≅ MaxFramePrinter (Evaluator.cpp:1177): for each sequence, the frame
    holding the maximum value per position."""

    name = "max_frame_printer"

    def __init__(self, num_results: int = 1, prefix: str = "max_frame"):
        self.k = max(num_results, 1)
        self.prefix = prefix

    def start(self):
        pass

    def eval_batch(self, value=None, **kw):
        lens = _lengths(value)
        arr = _np(value)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        score = arr.max(axis=-1)  # [B, T]
        order = np.argsort(-score, axis=-1)[:, : self.k]
        for b in range(arr.shape[0]):
            t = int(lens[b]) if lens is not None else arr.shape[1]
            frames = [int(f) for f in order[b] if f < t]
            log.info("%s sample %d: frames %s", self.prefix, b, frames)

    def finish(self):
        return {}


class ClassificationErrorPrinter(ev_mod.Evaluator):
    """≅ ClassificationErrorPrinter (Evaluator.cpp:1340): per-sample error."""

    name = "classification_error_printer"

    def __init__(self, classification_threshold: float = 0.5,
                 prefix: str = "classification_error"):
        self.threshold = classification_threshold
        self.prefix = prefix

    def start(self):
        pass

    def eval_batch(self, pred=None, label=None, **kw):
        p = _np(pred).reshape(-1, _np(pred).shape[-1])
        y = _np(label).reshape(-1)
        if p.shape[-1] == 1:
            err = (p[:, 0] > self.threshold).astype(int) != y
        else:
            err = np.argmax(p, axis=-1) != y
        log.info("%s per-sample: %s", self.prefix,
                 " ".join(str(int(e)) for e in err))

    def finish(self):
        return {}


class GradientPrinter(ev_mod.Evaluator):
    """≅ GradientPrinter (Evaluator.cpp:1091): prints d(cost)/d(layer).
    Gradients arrive via the trainer's tap mechanism (Topology.forward
    ``taps`` + jax.grad), not a hidden backward hook."""

    name = "gradient_printer"

    def __init__(self, prefix: str = "gradient", max_elems: int = 16):
        self.prefix = prefix
        self.max_elems = max_elems

    def start(self):
        pass

    def eval_batch(self, grad=None, layer_name="", **kw):
        if grad is None:
            log.info("%s[%s]: (no gradient in this pass)", self.prefix,
                     layer_name)
            return
        arr = np.asarray(grad)
        flat = arr.reshape(-1)[: self.max_elems]
        log.info("%s[%s] shape=%s %s%s", self.prefix, layer_name, arr.shape,
                 np.array2string(flat, precision=4),
                 "..." if arr.size > self.max_elems else "")

    def finish(self):
        return {}


class SeqTextPrinter(ev_mod.Evaluator):
    """≅ SequenceTextPrinter (Evaluator.cpp:1219): writes generated id
    sequences to ``result_file``, optionally mapping ids through
    ``dict_file`` (line i = token i) and prefixing a sample id.

    Formats (mirroring the reference's dump files, float-stream-equal):
    - single result per sample:  ``id\\t tok tok tok``
    - beam (n results):          ``id`` line, then per result
      ``rank\\tscore\\t tok tok``, blank line between samples.
    """

    name = "seq_text_printer"

    def __init__(self, result_file: str, dict_file: str | None = None,
                 delimited: bool = True):
        enforce(result_file, "seq_text_printer needs result_file")
        self.result_file = result_file
        self.words = _load_dict(dict_file)
        self.delimited = True if delimited is None else bool(delimited)
        self._fh = None

    def start(self):
        self._fh = open(self.result_file, "w")

    def _tok(self, i: int) -> str:
        if self.words is not None and 0 <= i < len(self.words):
            return self.words[i]
        return str(int(i))

    def _join(self, ids) -> str:
        sep = " " if self.delimited else ""
        return sep + sep.join(self._tok(int(i)) for i in ids)

    def eval_batch(self, value=None, sample_ids=None, **kw):
        from paddle_tpu.layers.recurrent_group import (
            GeneratedSequence,
            NestedGeneratedSequence,
        )

        out = self._fh
        enforce(out is not None, "start() not called")
        if isinstance(value, NestedGeneratedSequence):
            # nested format (Evaluator.cpp sub-sequence mode): id on the
            # first line, one tab-prefixed line per subsequence, blank line
            # between outer samples
            ids = np.asarray(value.inner.ids)
            lens = np.asarray(value.inner.length)
            scores = np.asarray(value.inner.score)
            n_res = ids.shape[1]
            n_sub = value.n_sub
            seq_len = np.asarray(value.seq_length)
            b_outer = seq_len.shape[0]
            for s in range(b_outer):
                sid = int(np.asarray(sample_ids).reshape(-1)[s]) \
                    if sample_ids is not None else s
                for j in range(int(seq_len[s])):
                    r = s * n_sub + j
                    prefix = f"{sid}\t" if j == 0 else "\t"
                    if n_res == 1:
                        out.write(prefix
                                  + f"{self._join(ids[r, 0, :lens[r, 0]])}\n")
                    else:  # beam block per subsequence (rank, score, seq)
                        out.write(prefix.rstrip("\t") + "\n" if j == 0
                                  else "")
                        for k in range(n_res):
                            out.write(f"{k}\t{float(scores[r, k]):g}\t"
                                      f"{self._join(ids[r, k, :lens[r, k]])}"
                                      "\n")
                out.write("\n")
            return
        if isinstance(value, GeneratedSequence):
            ids = np.asarray(value.ids)
            lens = np.asarray(value.length)
            scores = np.asarray(value.score)
            b, n_res, _ = ids.shape
            for s in range(b):
                sid = int(np.asarray(sample_ids).reshape(-1)[s]) \
                    if sample_ids is not None else s
                if n_res == 1:
                    out.write(f"{sid}\t{self._join(ids[s, 0, :lens[s, 0]])}\n")
                else:
                    out.write(f"{sid}\n")
                    for r in range(n_res):
                        sc = float(scores[s, r])
                        out.write(f"{r}\t{sc:g}\t"
                                  f"{self._join(ids[s, r, :lens[s, r]])}\n")
                    out.write("\n")
        else:
            lens = _lengths(value)
            arr = _np(value)
            if arr.ndim >= 2 and arr.shape[-1] > 1 and not np.issubdtype(
                    arr.dtype, np.integer):
                arr = np.argmax(arr, axis=-1)  # maxid convenience
            arr = arr.reshape(arr.shape[0], -1)
            for s in range(arr.shape[0]):
                t = int(lens[s]) if lens is not None else arr.shape[1]
                sid = int(np.asarray(sample_ids).reshape(-1)[s]) \
                    if sample_ids is not None else s
                out.write(f"{sid}\t{self._join(arr[s, :t])}\n")

    def finish(self):
        if self._fh:
            self._fh.close()
            self._fh = None
        return {}


# ---- spec -> instance + batch adapter ---------------------------------------

def _instantiate(spec: EvaluatorSpec) -> ev_mod.Evaluator:
    t = spec.type
    if t == "classification_error":
        return ev_mod.ClassificationError(
            threshold=spec.field("classification_threshold"),
            top_k=spec.field("top_k"))
    if t == "last-column-auc":
        return ev_mod.AUC()
    if t == "pnpair":
        return ev_mod.PnpairEvaluator()
    if t == "precision_recall":
        return ev_mod.PrecisionRecall(
            num_classes=None,
            positive_label=spec.field("positive_label"))
    if t == "ctc_edit_distance":
        return ev_mod.CTCError()
    if t == "chunk":
        return ev_mod.ChunkEvaluator(
            chunk_scheme=spec.field("chunk_scheme", "IOB"),
            num_chunk_types=spec.field("num_chunk_types", 1),
            excluded_chunk_types=spec.field("excluded_chunk_types"))
    if t == "sum":
        return ev_mod.SumEvaluator()
    if t == "last-column-sum":
        return ev_mod.ColumnSumEvaluator()
    if t == "detection_map":
        return ev_mod.DetectionMAP(
            overlap_threshold=spec.field("overlap_threshold", 0.5),
            ap_version=spec.field("ap_type", "11point"),
            evaluate_difficult=bool(spec.field("evaluate_difficult", False)),
            background_id=spec.field("background_id", 0))
    if t == "value_printer":
        return ev_mod.ValuePrinter(prefix=spec.name)
    if t == "gradient_printer":
        return GradientPrinter(prefix=spec.name)
    if t == "max_id_printer":
        return MaxIdPrinter(num_results=spec.field("num_results", 1),
                            prefix=spec.name)
    if t == "max_frame_printer":
        return MaxFramePrinter(num_results=spec.field("num_results", 1),
                               prefix=spec.name)
    if t == "seq_text_printer":
        return SeqTextPrinter(result_file=spec.field("result_file"),
                              dict_file=spec.field("dict_file"),
                              delimited=spec.field("delimited"))
    if t == "classification_error_printer":
        return ClassificationErrorPrinter(
            classification_threshold=spec.field("classification_threshold",
                                                0.5),
            prefix=spec.name)
    raise ValueError(f"unknown evaluator type {spec.type!r}")


@dataclasses.dataclass
class _Bound:
    spec: EvaluatorSpec
    inst: ev_mod.Evaluator


class DeclaredEvaluators:
    """All declared evaluators of a parsed config, batch-driven."""

    def __init__(self, specs: list[EvaluatorSpec]):
        self.bound = [_Bound(s, _instantiate(s)) for s in specs]

    def __bool__(self):
        return bool(self.bound)

    def grad_tap_layers(self) -> list[str]:
        return [b.spec.input_layers[0] for b in self.bound
                if b.spec.type == "gradient_printer"]

    def start(self):
        for b in self.bound:
            b.inst.start()

    def eval_batch(self, values: dict, grads: dict | None = None,
                   feed: dict | None = None):
        """values: layer-name -> batch value (the eval step's output dict);
        grads: optional layer-name -> d(cost)/d(layer) for printers; feed
        resolves input layers that are not part of the topology DAG (e.g.
        an id column consumed only by a printer)."""
        lookup = dict(feed or {})
        lookup.update(values)
        for b in self.bound:
            ins = [lookup[n] for n in b.spec.input_layers]
            t = b.spec.type
            if t in ("classification_error", "precision_recall",
                     "classification_error_printer"):
                p, y, w = _valid_frames(ins[0], ins[1],
                                        ins[2] if len(ins) > 2 else None)
                kw = dict(pred=p, label=y)
                if w is not None:
                    kw["weight"] = w
                b.inst.eval_batch(**kw)
            elif t == "last-column-auc":
                p, y, w = _valid_frames(ins[0], ins[1],
                                        ins[2] if len(ins) > 2 else None)
                kw = dict(prob=p, label=y)
                if w is not None:
                    kw["weight"] = w
                b.inst.eval_batch(**kw)
            elif t == "pnpair":
                # declared input order (ref Evaluator.cpp:880-887):
                # score, label, info[, weight]
                kw = dict(score=_np(ins[0]), label=_np(ins[1]),
                          query=_np(ins[2]))
                if len(ins) > 3:
                    kw["weight"] = _np(ins[3])
                b.inst.eval_batch(**kw)
            elif t == "ctc_edit_distance":
                lg, lb = _np(ins[0]), _np(ins[1])
                lg_len, lb_len = _lengths(ins[0]), _lengths(ins[1])
                logits = [lg[i, : (int(lg_len[i]) if lg_len is not None
                                   else lg.shape[1])]
                          for i in range(lg.shape[0])]
                labels = [lb[i, : (int(lb_len[i]) if lb_len is not None
                                   else lb.shape[1])].reshape(-1)
                          for i in range(lb.shape[0])]
                b.inst.eval_batch(logits=logits, label=labels)
            elif t == "chunk":
                # prefer the ids side of a dual-output layer (crf_decoding
                # with label: value = error indicator, "#ids" = the path —
                # the reference ChunkEvaluator reads arguments[0].ids)
                from paddle_tpu.layers.base import companion_name

                cname = companion_name(b.spec.input_layers[0])
                pred = lookup.get(cname, ins[0])
                p0 = _np(pred)
                if (cname not in lookup and p0.ndim >= 2
                        and p0.shape[-1] == 1
                        and b.inst.num_chunk_types > 1):
                    log.warning(
                        "chunk evaluator %s: input %r looks like an "
                        "error indicator, not decoded ids — its "
                        "'#ids' companion layer is not in the "
                        "topology (pass it via extra_layers)",
                        b.spec.name, b.spec.input_layers[0])
                b.inst.eval_batch(pred=p0, label=_np(ins[1]),
                                  lengths=_lengths(pred))
            elif t in ("sum", "last-column-sum"):
                if len(ins) > 1:
                    v, w2, _ = _valid_frames(ins[0], ins[1])
                    # _valid_frames pairs (pred,label); here the "label" is
                    # the weight column, flattened per valid frame
                    b.inst.eval_batch(value=v, weight=w2)
                else:
                    v = ins[0]
                    lens = _lengths(v)
                    if lens is not None:
                        v, _, _ = _valid_frames(v, v)
                        b.inst.eval_batch(value=v)
                    else:
                        b.inst.eval_batch(value=_np(v))
            elif t == "value_printer":
                b.inst.eval_batch(**{n: _np(v) for n, v in
                                     zip(b.spec.input_layers, ins)})
            elif t == "gradient_printer":
                name = b.spec.input_layers[0]
                g = (grads or {}).get(name)
                b.inst.eval_batch(grad=g, layer_name=name)
            elif t in ("max_id_printer", "max_frame_printer"):
                b.inst.eval_batch(value=ins[0])
            elif t == "seq_text_printer":
                if len(ins) == 2:  # [id_input, sequence]
                    b.inst.eval_batch(value=ins[1], sample_ids=_np(ins[0]))
                else:
                    b.inst.eval_batch(value=ins[0])
            elif t == "detection_map":
                b.inst.eval_batch(detections=_np(ins[0]), gts=_np(ins[1]))
            else:  # pragma: no cover
                raise ValueError(f"unhandled evaluator type {t!r}")

    def finish(self) -> dict:
        out = {}
        for b in self.bound:
            res = b.inst.finish()
            if isinstance(res, dict):
                for k, v in res.items():
                    key = (b.spec.name if k == getattr(b.inst, "name", k)
                           else f"{b.spec.name}/{k}")
                    out[key] = v
            elif res is not None:
                out[b.spec.name] = res
        return out


def build(specs) -> DeclaredEvaluators:
    return DeclaredEvaluators(list(specs or []))
